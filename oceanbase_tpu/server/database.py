"""Database: full-statement SQL over a replicated cluster (observer analog).

Reference surface:
  * statement dispatch: ObMPQuery::process -> ObSql::stmt_query
    (observer/mysql/obmp_query.cpp:53, sql/ob_sql.cpp:153) — here
    DbSession.sql() parsing any statement and dispatching DDL / DML / query;
  * DML operators + DAS write path: ObTableModifyOp -> ObDMLService ->
    ObAccessService -> ObMemtable::set (sql/engine/dml/ob_table_modify_op.h:190,
    storage/memtable/ob_memtable.cpp:540) — here UPDATE/DELETE qualify rows
    by running a generated SELECT through the TPU engine, then stage
    mutations through TransService into leader memtables;
  * tx control: ObSqlTransControl (sql/ob_sql_trans_control.cpp:229) —
    BEGIN/COMMIT/ROLLBACK with snapshot-isolation reads.

HTAP loop: writes go through MVCC memtables + the replicated log; reads
materialize a snapshot via scan_merge into a core Table and ship it to the
device once per data version (the marshalling point the north star names).
VARCHAR columns store APPEND-ORDER dictionary codes (stable under inserts,
so logged rows never need re-encoding); at snapshot materialization the
codes are remapped through a cached sorted dictionary so the engine's
code-order == string-order invariant holds on device.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time as _time
from dataclasses import dataclass, field

import numpy as np

from ..core.dictionary import Dictionary
from ..core.dtypes import DataType, Field, Schema, TypeKind
from ..core.table import Table
from ..log.palf import leader_of as _leader_of
from ..engine.session import ResultSet, Session
from ..rootserver import RootService
from ..share import Config, LocationService
from ..share import gap_ledger as _GL
from ..share import interrupt as _I
from ..share import retry as _R
from ..share.schema_service import SchemaError
from .diag import QueryProfile
from ..sql import ast as A
from ..sql import parser as P
from ..sql.logical import _parse_type
from ..sql.plan_cache import PlanCache
from ..storage import OP_DELETE, OP_PUT


class SqlError(Exception):
    """Statement-level error; `code` is the MySQL-compatible error code
    the wire front door puts in the ERR packet (1064 generic syntax,
    1142 table access denied, 1227 privilege required, 1396 user-admin)."""

    def __init__(self, msg: str, code: int = 1064):
        super().__init__(msg)
        self.code = code


class WorkerQueueTimeout(SqlError):
    """The statement never got a tenant worker inside its wait bound
    (ObThWorker queue overflow analog). A distinct class so the retry
    taxonomy and chaos harness can tell admission pressure from SQL
    errors; still a SqlError for wire/compat purposes."""


@dataclass
class IndexInfo:
    """One secondary index: an index tablet co-located on the base table's
    log stream, keyed by (index cols..., pk cols...) — pk suffix makes
    non-unique entries unique; a UNIQUE index keys on the index cols alone
    so duplicate values collide in the memtable (first-committer-wins).
    Reference surface: index schemas + direct-insert build
    (src/storage/ddl) and DAS index lookup iterators (src/sql/das/iter)."""

    name: str
    table: str
    cols: tuple[str, ...]
    tablet_id: int
    schema: Schema  # index cols + pk cols (deduped, in that order)
    key_cols: list[str]
    unique: bool = False
    status: str = "building"  # building -> ready
    build_version: int = 0
    reads: int = 0  # statements served through this index (diag surface)


def _part_of(value: int, n_parts: int) -> int:
    """Hash-partition routing: stable over the 64-bit mix of the partition
    column's storage value (dict codes are append-ordered and global per
    table, so string partition columns route consistently too)."""
    v = (int(value) * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    return (v >> 32) % n_parts


@dataclass
class TableInfo:
    """Schema-service record of one user table.

    `partitions` lists the table's (ls_id, tablet_id) shards — one entry
    for an unpartitioned table; PARTITION BY HASH(part_col) PARTITIONS n
    spreads n tablets across log streams (the reference's hash-partitioned
    tables; a multi-partition statement stages on several LS leaders and
    commits with 2PC — the parallel-DML shape). ls_id/tablet_id remain the
    first partition (index tablets and the table lock anchor there)."""

    name: str
    schema: Schema
    key_cols: list[str]
    ls_id: int
    tablet_id: int
    indexes: dict[str, IndexInfo] = field(default_factory=dict)
    partitions: list[tuple[int, int]] | None = None
    part_col: str | None = None
    # append-order dictionaries: code assignment is insertion order, so
    # logged/stored codes stay valid as strings arrive (the sorted view is
    # derived at read time)
    dicts: dict[str, Dictionary] = field(default_factory=dict)
    data_version: int = 0  # bumped on every committed DML batch
    schema_version: int = 0  # set at create time (schema service analog)
    # last data version materialized into the analytic catalog (-1 = stale)
    cached_data_version: int = -1
    # per-column (dict length at build time, sorted Dictionary, remap array)
    _sorted_cache: dict[str, tuple[int, Dictionary, np.ndarray]] = field(
        default_factory=dict
    )

    # per-column dictionary length already carried by COMMITTED records:
    # codes beyond this must ride the next commit's dict_appends (codes
    # created by aborted txs stay unlogged and are re-logged by the next
    # committer that references them)
    logged_dict_len: dict[str, int] = field(default_factory=dict)

    def all_partitions(self) -> list[tuple[int, int]]:
        return self.partitions or [(self.ls_id, self.tablet_id)]

    def partition_for_key(self, key: tuple) -> tuple[int, int]:
        """(ls_id, tablet_id) owning a primary-key tuple (the partition
        column is enforced to be part of the primary key)."""
        parts = self.all_partitions()
        if len(parts) == 1 or self.part_col is None:
            return parts[0]
        v = key[self.key_cols.index(self.part_col)]
        return parts[_part_of(int(v), len(parts))]

    @property
    def dict_sig(self) -> tuple:
        """Dictionary-state signature. Append-order dictionaries only grow,
        so length IS the version — derived, not book-kept, which makes it
        immune to failed statements that encoded strings before erroring."""
        return tuple(sorted((c, len(d)) for c, d in self.dicts.items()))

    def sorted_dict(self, col: str) -> tuple[Dictionary, np.ndarray]:
        """Sorted view + old-code -> sorted-code remap, cached per length.

        Returning the SAME Dictionary object while the length is unchanged
        matters: dictionaries are static metadata of device batches, so a
        stable object keeps the jit cache warm across data refreshes."""
        d = self.dicts[col]
        hit = self._sorted_cache.get(col)
        if hit is not None and hit[0] == len(d):
            return hit[1], hit[2]
        codes = np.arange(len(d), dtype=np.int32)
        sd, remap = d.finalize_sorted(codes)
        self._sorted_cache[col] = (len(d), sd, remap)
        return sd, remap


class TxCatalog(dict):
    """Catalog mapping with statement-scoped transaction overlays.

    The shared dict holds COMMITTED snapshot Tables that every session
    reads. A session with an open tx needs private views (BEGIN-time
    snapshot plus its own staged rows); installing those into the shared
    dict would let a concurrent session read uncommitted rows between that
    tx's refresh and its own (advisor finding r1). Private views therefore
    live on the _OpenTx and are ACTIVATED only for the duration of one of
    that tx's statements via `tx_scope` — a thread-local pointer, so a
    different session's statement on the same OR another thread never
    resolves through them."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._tls = threading.local()

    @contextlib.contextmanager
    def tx_scope(self, views: dict | None):
        prev = getattr(self._tls, "ov", None)
        self._tls.ov = views
        try:
            yield
        finally:
            self._tls.ov = prev

    def _overlay(self) -> dict | None:
        return getattr(self._tls, "ov", None)

    def is_private(self, name: str) -> bool:
        ov = self._overlay()
        return ov is not None and name in ov

    def __getitem__(self, name):
        ov = self._overlay()
        if ov is not None and name in ov:
            return ov[name]
        return super().__getitem__(name)

    def get(self, name, default=None):
        ov = self._overlay()
        if ov is not None and name in ov:
            return ov[name]
        return super().get(name, default)

    def __contains__(self, name) -> bool:
        ov = self._overlay()
        return (ov is not None and name in ov) or super().__contains__(name)


@dataclass
class TenantUnit:
    """Resource unit of one tenant (the OMT unit-config analog: worker
    pool size, memory quota, PX quota — observer/omt ObTenant +
    ob_unit_config). None limits = unbounded (the sys tenant default)."""

    max_workers: int | None = None  # concurrent statements
    queue_timeout_s: float = 5.0  # wait for a worker slot
    #: unified tenant memory quota, charged by TWO consumers sharing one
    #: accounting surface: (1) resident catalog snapshot bytes, enforced
    #: by Database._enforce_memory (evicts the tenant's OWN coldest
    #: tables, never a neighbour's); (2) live device-memory reservations,
    #: charged by the memory governor at statement admission
    #: (engine/memory_governor.py) — a tenant at its limit QUEUES on the
    #: "device memory reservation" wait event rather than evicting
    #: another tenant's residency. None = unbounded (sys tenant default).
    memory_limit: int | None = None
    px_target: int | None = None  # cluster-parallelism quota
    # continuous-batching admission share: the dispatch gate's weighted
    # round-robin picks this tenant's queued cohorts `weight` times per
    # unit-weight tenant when both have backlog (server/batcher.py)
    weight: int = 1


class Database:
    """An in-process replicated database: schema + cluster + analytic engine.

    One Database ~ one TENANT of the reference: a catalog, a plan cache,
    schemas, diagnostics, resource unit — plus, in standalone mode, the
    cluster itself. Pass `cluster`/`rootservice` to share one cluster
    among several tenants (observer/omt: tenants are resource-isolated
    units over shared nodes; see server/tenant.TenantManager)."""

    def __init__(self, n_nodes: int = 3, n_ls: int = 2,
                 extra_catalog: dict[str, Table] | None = None,
                 data_dir: str | None = None, fsync: bool = True,
                 cluster=None, rootservice=None, tenant_name: str = "sys",
                 unit: TenantUnit | None = None):
        # durable mode: palf logs + storage checkpoints + schema meta live
        # under data_dir; a Database pointed at an existing dir restarts
        # from disk (ckpt replay + palf replay — ob_server.cpp:923 analog)
        self.data_dir = data_dir
        self._fsync = fsync
        self.tenant_name = tenant_name
        # (schema version, name->TableInfo map) for the .tables property
        self._tables_cache: tuple | None = None
        # XA branch registry rebuilt from the LOG (ob_trans_part_ctx.h:154
        # logs prepare state): XA_PREPARE records add entries, the
        # decision records remove them — populated during boot replay and
        # kept current by normal apply. xid -> {tx_id, owner, parts,
        # tablets}; must exist before any record observer can fire.
        self._xa_registry: dict[str, dict] = {}
        self._xa_txids: dict[int, str] = {}
        # XA: externally-coordinated branches parked between PREPARE and
        # the decision; value = (live _OpenTx | None-if-recovered | the
        # _XA_PREPARING reservation, owner, registry-snapshot-or-None).
        # The snapshot lets a RETRY of a failed decide finish cleanup even
        # after the live registry entry popped.
        self._xa_prepared: dict[str, tuple] = {}
        self.unit = unit or TenantUnit()
        self._shared_cluster = cluster is not None
        # integrity counters accrued BEFORE the metrics registry exists
        # (meta/checkpoint verification runs first thing in boot); folded
        # into sysstat once the registry is built below
        self._boot_integrity: dict[str, float] = {}
        self._unique_keys: dict[str, tuple[str, ...]] = {}
        # tablet_id -> TableInfo, rebuilt lazily after DDL (apply-path hot)
        self._ti_by_tablet: dict[int, TableInfo] | None = None
        if self._shared_cluster:
            if data_dir is not None:
                raise ValueError(
                    "durable mode is per-cluster; pass data_dir to the "
                    "TenantManager, not a shared-cluster tenant"
                )
            self.cluster, self.rootservice = cluster, rootservice
            self.schema_service = self.rootservice.schema
            # record observation is multiplexed across tenants (each
            # ignores tablets it does not own)
            self.cluster.record_observers.append(self._on_applied_record)
            restored_meta = None
        else:
            node_meta = self._load_node_meta() if data_dir is not None else None
            if node_meta is not None:
                n_nodes, n_ls = node_meta["n_nodes"], node_meta["n_ls"]
                # seed the XA registry from meta (covers branches whose
                # XA_PREPARE predates the checkpoint the log recycled to);
                # replayed decision records then prune entries decided
                # after the meta snapshot
                for _xid, _e in (node_meta.get("xa_registry") or {}).items():
                    self._xa_registry[_xid] = {
                        "tx_id": _e["tx_id"], "owner": _e["owner"],
                        "parts": tuple(_e["parts"]),
                        "tablets": set(_e["tablets"]),
                    }
                    self._xa_txids[_e["tx_id"]] = _xid
            self.cluster, self.rootservice = RootService.bootstrap(
                n_nodes, n_ls, data_dir=data_dir, fsync=fsync, finalize=False
            )
            self.schema_service = self.rootservice.schema
            if node_meta is not None:
                self._restore_from_disk(node_meta)
            # every applied record re-applies logged dictionary appends and
            # advances GTS past restored commit versions (idempotent in
            # normal operation; essential during boot-time replay)
            for group in self.cluster.ls_groups.values():
                for rep in group.values():
                    rep.on_record = self._on_applied_record
            self.cluster.finalize()
            restored_meta = node_meta
        # user accounts + grants (src/sql/privilege_check analog); restored
        # from node meta alongside the schema so grants survive restart
        from ..share.privilege import PrivilegeManager

        self.privileges = PrivilegeManager.from_meta(
            restored_meta.get("privileges") if restored_meta else None
        )
        # vector index registrations: table -> col -> (lists, nprobe);
        # re-applied to every fresh snapshot Table (the built artifact
        # version-caches in the executor — DML = invalidate + lazy rebuild)
        self._vector_specs: dict[str, dict[str, tuple[int, int]]] = (
            restored_meta.get("vector_specs", {}) if restored_meta else {}
        )
        # external tables (plugin loaders): name -> (format, location);
        # re-materialized from their files once the catalog exists below
        self._external_specs: dict[str, tuple[str, str]] = (
            restored_meta.get("external_specs", {}) if restored_meta else {}
        )
        # materialized views: name -> defining SELECT text; re-run at
        # boot once base-table snapshots restore
        self._mview_specs: dict[str, str] = (
            restored_meta.get("mview_specs", {}) if restored_meta else {}
        )
        # PLAIN views: name -> defining SELECT text; nothing materializes
        # — the planner expands (and where possible MERGES) the body at
        # plan time (sql/planner.py _merge_view). The dict is shared with
        # the planner by reference, so DDL changes apply immediately.
        self._view_specs: dict[str, str] = (
            restored_meta.get("view_specs", {}) if restored_meta else {}
        )
        # row triggers: name -> {timing, event, table, body}; parsed form
        # cached lazily per process (sql/trigger.py)
        self._trigger_specs: dict[str, dict] = (
            restored_meta.get("trigger_specs", {}) if restored_meta else {}
        )
        self._trigger_parsed: dict[str, tuple] = {}
        # stored procedures: name -> definition text (sql/pl.py); parsed
        # lazily per process, persisted in node meta like schema
        self._procedure_texts: dict[str, str] = (
            restored_meta.get("procedures", {}) if restored_meta else {}
        )
        self._procedures_parsed: dict = {}
        # sequences: name -> {"next": int, "inc": int, "reserved": int}.
        # Durability via BLOCK RESERVATION (the reference's sequence
        # cache): meta persists the end of the reserved block, so a
        # crash skips at most one block and never repeats a value
        self._sequences: dict[str, dict] = (
            restored_meta.get("sequences", {}) if restored_meta else {}
        )
        for _sq in self._sequences.values():
            _sq["next"] = _sq["reserved"]  # post-restart: start past block
            _sq.pop("last", None)  # currval invalid until a nextval
        # worker pool quota (ObTenant worker queues): bounds concurrent
        # statements of this tenant
        self._worker_sem = (
            threading.BoundedSemaphore(self.unit.max_workers)
            if self.unit.max_workers else None
        )
        # global query interrupt (share/interrupt analog): one manager per
        # node, shared by every tenant on the cluster
        from ..share.interrupt import attach_cluster_interrupts

        if not hasattr(self.cluster, "_interrupt_mgrs"):
            self.cluster._interrupt_mgrs = attach_cluster_interrupts(self.cluster)
        self.interrupts = self.cluster._interrupt_mgrs
        # session_id -> interrupt id of its running statement
        self._active_stmts: dict[int, tuple] = {}
        self._stmt_seq = itertools.count(1)
        self.config = Config()
        # re-apply persisted parameter values (see _save_node_meta): a
        # restarted node keeps its ALTER SYSTEM SET state
        for _cn, _cv in ((restored_meta or {}).get("config") or {}).items():
            try:
                self.config.set(_cn, _cv)
            except Exception:
                pass
        self.location = LocationService(
            self.cluster.leader_node,
            ttl=10.0,
            clock=lambda: self.cluster.bus.now,
        )
        # analytic catalog: table name -> snapshot Table (plus any read-only
        # preloaded tables, e.g. benchmark data)
        self.catalog: dict[str, Table] = TxCatalog(extra_catalog or {})
        # placeholder entries for restored tables (create_table provides
        # one on the DDL path): the resolver requires every table in the
        # shared catalog even when the first statement reads it through a
        # statement-scoped view (index route) or a tx overlay
        for ti in self.tables.values():
            if ti.name not in self.catalog:
                self.catalog[ti.name] = Table(ti.name, ti.schema, {
                    f.name: np.zeros(0, f.dtype.storage_np)
                    for f in ti.schema.fields
                })
        # re-materialize registered external tables from their files.
        # A load failure (missing mount, transient IO) keeps the
        # REGISTRATION — queries error "unknown table" until the file is
        # back and the next boot (or re-create) materializes it; silently
        # dropping the spec would persist the loss at the next meta save
        for _ename, (_efmt, _eloc) in list(self._external_specs.items()):
            try:
                from ..plugin import load_external

                self.catalog[_ename] = load_external(_ename, _efmt, _eloc)
            except Exception:
                pass
        self.plan_cache = PlanCache(capacity=self.config["plan_cache_capacity"])
        self.config.on_change(
            "plan_cache_capacity",
            lambda _n, _o, v: setattr(self.plan_cache, "capacity", v),
        )
        # tenant-wide metrics fabric (GV$SYSSTAT / GV$SYSTEM_EVENT /
        # QUERY_RESPONSE_TIME analog): one registry threaded through the
        # statement pipeline, plan cache, replication bus and tx commit
        from ..share.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        # fold integrity counters accrued during boot-time verification
        # (before this registry existed) into sysstat
        for _n, _v in self._boot_integrity.items():
            self.metrics.add(_n, _v)
        self.plan_cache.metrics = self.metrics
        if getattr(self.cluster.bus, "metrics", None) is None:
            # shared-cluster mode: the first tenant (sys) owns the bus
            # stats — rpc traffic is cluster-wide, not per-tenant
            self.cluster.bus.metrics = self.metrics
        # diagnostics (observer/virtual_table surface)
        from .diag import (
            AshSampler,
            FlightRecorder,
            LongOps,
            PlanMonitor,
            SqlAudit,
            Tracer,
        )

        self.tracer = Tracer()
        if getattr(self.cluster.bus, "tracer", None) is None:
            # full-link propagation: replication messages stamped with the
            # sending statement's trace context land replica-side spans in
            # the same tree (first tenant owns, like bus.metrics)
            self.cluster.bus.tracer = self.tracer
        self.audit = SqlAudit(
            capacity=max(64, self.config["sql_audit_memory_limit"] // 4096)
        )
        self.plan_monitor = PlanMonitor()
        self.ash = AshSampler()
        self.long_ops = LongOps()
        self.flight = FlightRecorder(
            watermark_s=self.config["trace_log_slow_query_watermark"]
        )
        self.audit.enabled = self.config["enable_sql_audit"]
        self.plan_monitor.enabled = self.config["enable_perf_event"]
        self.config.on_change(
            "enable_sql_audit",
            lambda _n, _o, v: setattr(self.audit, "enabled", v))
        self.config.on_change(
            "enable_perf_event",
            lambda _n, _o, v: setattr(self.plan_monitor, "enabled", v))
        self.config.on_change(
            "sql_audit_memory_limit",
            lambda _n, _o, v: self.audit.set_capacity(max(64, v // 4096)))
        self.config.on_change(
            "trace_log_slow_query_watermark",
            lambda _n, _o, v: setattr(self.flight, "watermark_s", v))
        # host-tax gap ledger (share/gap_ledger.py): conservation-account
        # every statement's e2e wall into named phases + an explicit
        # unattributed residual, aggregated per digest behind
        # __all_virtual_host_tax; the stack sampler rides the slow-query
        # watermark so a recurring slow statement gets caught with
        # collapsed stacks in its flight-recorder bundle
        self.host_tax = _GL.HostTaxRegistry(
            max_digests=self.config["host_tax_max_digests"],
            window_s=self.config["host_tax_window"])
        self.host_tax.enabled = self.config["enable_host_tax"]
        self.stack_sampler = _GL.StackSampler(
            interval_s=self.config["stack_sampler_interval"])
        if self.config["enable_stack_sampler"]:
            self.stack_sampler.set_continuous(True)
        self.config.on_change(
            "enable_host_tax",
            lambda _n, _o, v: setattr(self.host_tax, "enabled", v))
        self.config.on_change(
            "host_tax_max_digests",
            lambda _n, _o, v: setattr(self.host_tax, "max_digests",
                                      max(8, v)))
        self.config.on_change(
            "enable_stack_sampler",
            lambda _n, _o, v: self.stack_sampler.set_continuous(v))
        self.config.on_change(
            "stack_sampler_interval",
            lambda _n, _o, v: setattr(self.stack_sampler, "interval_s",
                                      max(1e-4, v)))
        # operator-level plan telemetry (engine/plan_profile.py): sampled
        # per-operator profiled execution folding (estimate, actual)
        # calibration pairs into the bounded store — per-operator rows in
        # __all_virtual_sql_plan_monitor, EXPLAIN ANALYZE annotations,
        # awr_report hot operators, and the misestimate sentinel rule
        from ..engine.plan_profile import OperatorProfileStore, PlanProfiler

        self.plan_profiler = PlanProfiler(
            store=OperatorProfileStore(
                max_digests=self.config["ob_plan_profile_max_digests"]),
            sample_every=self.config["ob_plan_profile_sample"])
        self.plan_profiler.enabled = self.config["enable_plan_profile"]
        self.config.on_change(
            "enable_plan_profile",
            lambda _n, _o, v: setattr(self.plan_profiler, "enabled", v))
        self.config.on_change(
            "ob_plan_profile_sample",
            lambda _n, _o, v: setattr(self.plan_profiler, "sample_every",
                                      int(v)))
        self.config.on_change(
            "ob_plan_profile_max_digests",
            lambda _n, _o, v: self.plan_profiler.store.set_max_digests(v))
        # workload repository (server/workload.py): digest-keyed statement
        # summaries + table/column access heat folded at statement
        # completion, bounded AWR-style snapshots on demand or periodic
        from .workload import (
            StatementSummaryRegistry,
            TableAccessStats,
            WorkloadRepository,
        )

        self.stmt_summary = StatementSummaryRegistry(
            max_digests=self.config["ob_sql_stat_max_digests"],
            metrics=self.metrics)
        self.access = TableAccessStats()
        self.stmt_summary.enabled = self.config["enable_sql_stat"]
        self.access.enabled = self.config["enable_sql_stat"]
        self.workload = WorkloadRepository(
            capacity=self.config["workload_snapshot_capacity"])
        self.workload.interval_s = self.config["workload_snapshot_interval"]

        def _sql_stat_toggle(_n, _o, v):
            self.stmt_summary.enabled = v
            self.access.enabled = v

        self.config.on_change("enable_sql_stat", _sql_stat_toggle)
        self.config.on_change(
            "ob_sql_stat_max_digests",
            lambda _n, _o, v: self.stmt_summary.set_max_digests(v))
        self.config.on_change(
            "workload_snapshot_capacity",
            lambda _n, _o, v: self.workload.set_capacity(v))
        self.config.on_change(
            "workload_snapshot_interval",
            lambda _n, _o, v: setattr(self.workload, "interval_s", v))
        # serving saturation timeline (share/timeline.py): ONE ring per
        # cluster, shared like bus.metrics — tenant starvation is only
        # visible when every tenant's QoS lands in the same ledger. The
        # first tenant's config sizes it; any tenant's toggle gates it.
        from ..share.timeline import ServingTimeline

        tl = getattr(self.cluster, "_timeline", None)
        if tl is None:
            tl = ServingTimeline(
                bucket_s=self.config["serving_timeline_bucket"],
                capacity=self.config["serving_timeline_capacity"])
            self.cluster._timeline = tl
        self.timeline = tl
        tl.enabled = self.config["enable_serving_timeline"]
        tl.register_tenant(self.tenant_name, self.unit.max_workers,
                           self.unit.queue_timeout_s)
        self.config.on_change(
            "enable_serving_timeline",
            lambda _n, _o, v: setattr(self.timeline, "enabled", v))
        self.config.on_change(
            "serving_timeline_bucket",
            lambda _n, _o, v: self.timeline.set_bucket_s(v))
        self.config.on_change(
            "serving_timeline_capacity",
            lambda _n, _o, v: self.timeline.set_capacity(v))
        # health sentinel (server/sentinel.py): typed rules over each
        # snapshot interval, alert ring behind __all_virtual_alert_history
        from .sentinel import HealthSentinel

        self.sentinel = HealthSentinel(
            capacity=self.config["health_alert_capacity"])
        self.sentinel.enabled = self.config["enable_health_sentinel"]
        # closed-loop layout advisor (server/layout_advisor.py): folds the
        # workload repository's evidence into costed layout actions, and
        # (auto mode) applies them as background rebuild dags. Chained on
        # the snapshot hook next to the sentinel; either observer failing
        # must not starve the other.
        from .layout_advisor import LayoutAdvisor

        self.layout_advisor = LayoutAdvisor(self)
        # re-install persisted encoding picks (advisor view + live
        # tablets): dump-time FOR/RLE/const choices survive a restart
        for (_ht, _hc), _hv in (
                (restored_meta or {}).get("enc_hints") or {}).items():
            self.layout_advisor.encoding_hints[(_ht, _hc)] = _hv
            self.layout_advisor._push_encoding(_ht, _hc, _hv)
        # table -> advisor-set residency priority (higher = evict later);
        # _enforce_memory and the block cache's eviction consult it
        self.residency_priority: dict[str, float] = {}
        self._uid_tables: dict = {}

        def _observe_snapshot(first, last):
            for cb in (self.sentinel.observe,
                       self.layout_advisor.on_snapshot):
                try:
                    cb(first, last)
                except Exception:  # noqa: BLE001 - observer boundary
                    pass

        self.workload.on_snapshot = _observe_snapshot
        self.config.on_change(
            "enable_health_sentinel",
            lambda _n, _o, v: setattr(self.sentinel, "enabled", v))
        self.config.on_change(
            "health_alert_capacity",
            lambda _n, _o, v: self.sentinel.set_capacity(v))
        self._session_ids = itertools.count(1)
        # statement-scoped follower-read Tables, keyed on (table, chosen
        # replica applied positions, dict signature) — identical replica
        # state ⇒ identical rows, so read floods over static data reuse
        # one materialization (see _follower_table)
        self._follower_views: dict[tuple, Table] = {}
        # last rootserver rebalance pass (monotonic stamp) + the QoS
        # rejected-counts already consumed as pressure evidence
        self._last_rebalance_at: float | None = None
        self._rebalance_qos_seen: dict[str, int] = {}

        # storage maintenance: block cache, dag scheduler, freeze loop
        from ..share.cache import KVCache
        from ..share.dag_scheduler import TenantDagScheduler
        from ..storage.freezer import MaintenanceService

        self.block_cache = KVCache(self.config["block_cache_size"])
        # under memory pressure the block cache evicts the coldest entry
        # of the LOWEST advisor residency priority first (keys are
        # (sstable uid, block, column); uid -> table resolved lazily)
        self.block_cache.priority_of = self._block_priority
        self.config.on_change(
            "block_cache_size",
            lambda _n, _o, v: self.block_cache.set_capacity(v))
        # restored tablets (and their sstables) come off disk without a
        # cache: reattach
        for t in self._all_tablets():
            t.cache = self.block_cache
            for ss in t.deltas:
                ss.cache = self.block_cache
            if t.base is not None:
                t.base.cache = self.block_cache
        self.dag_scheduler = TenantDagScheduler(
            tracer=self.tracer, long_ops=self.long_ops
        )
        self.maintenance = MaintenanceService(
            self.dag_scheduler,
            config=self.config,
            tablets_fn=self._all_tablets,
            snapshot_fn=lambda: self.cluster.gts.current(),
        )
        # background storage scrubber (storage/scrub.py): queued as a
        # BACKGROUND dag from run_maintenance every ob_scrub_interval
        from ..storage.scrub import StorageScrubber

        self.scrubber = StorageScrubber(self)
        # ALTER SYSTEM SET ob_errsim_disk_* arms the shared disk-fault
        # injector live (chaos harness entry point; 0 disarms)
        from ..share.errsim import ERRSIM as _ERRSIM

        _disk_arms = {
            "ob_errsim_disk_bitflip": "EN_DISK_BITFLIP",
            "ob_errsim_disk_torn_write": "EN_DISK_TORN_WRITE",
            "ob_errsim_disk_truncate": "EN_DISK_TRUNCATE",
            "ob_errsim_disk_io_error": "EN_IO_ERROR",
        }

        def _arm_disk(name, _old, v):
            point = _disk_arms[name]
            if float(v) > 0.0:
                _ERRSIM.arm(point, prob=float(v), count=-1)
            else:
                _ERRSIM.clear(point)

        for _k in _disk_arms:
            self.config.on_change(_k, _arm_disk)

        from ..tx.tablelock import LockManager

        self.lock_mgr = LockManager()

        # XA recovery: every undecided branch in the log-rebuilt registry
        # parks again — locks re-held, and the leader replica RE-STAGES the
        # pending redo into its memtables so write-write conflict detection
        # guards the prepared rows exactly as before the restart (the
        # reference re-inserts prepared redo through the tx ctx on
        # recovery, ob_trans_part_ctx.h:154).
        from ..tx.tablelock import LockMode as _LockMode

        for _xid, _e in self._xa_registry.items():
            self._xa_prepared.setdefault(_xid, (None, _e["owner"], _e))
            # the recovered branch keeps its pre-crash tx_id: the owning
            # node's counter must never re-issue it (a collision would
            # hand the branch's locks + re-staged rows to a stranger)
            _svc = self.cluster.services.get(
                _e["tx_id"] // 1_000_000_000)
            if _svc is not None:
                _svc.ensure_tx_id_above(_e["tx_id"])
            for _tab in _e["tablets"]:
                try:
                    self.lock_mgr.lock(_e["tx_id"], _tab, _LockMode.ROW_X)
                except Exception:
                    pass
            for _ls in _e["parts"]:
                for _rep in (self.cluster.ls_groups.get(_ls) or {}).values():
                    if _rep.is_leader and _e["tx_id"] in _rep._pending_redo:
                        _ms = _rep._pending_redo.pop(_e["tx_id"])
                        _snap = self.cluster.gts.current()
                        for _m in _ms:
                            _t = _rep.tablets.get(_m.tablet_id)
                            if _t is not None:
                                _t.stage(_e["tx_id"], _snap, _m.key,
                                         _m.op, _m.values)
                        _rep._locally_staged.add(_e["tx_id"])
                        _rep.tx_table[_e["tx_id"]] = "prepared"

        # indexes built since the last checkpoint lost their (unlogged)
        # backfill sstables in a crash: re-backfill now that leaders exist
        for ti, idx in getattr(self, "_index_rebuild_pending", []):
            self._backfill_index(ti, idx)
        self._index_rebuild_pending = []

        self.engine = Session(
            self.catalog,
            unique_keys=self._unique_keys,
            plan_cache=self.plan_cache,
            key_extra_fn=self._key_extra,
            cache_enabled_fn=lambda: self.config["ob_enable_plan_cache"],
            plan_monitor=self.plan_monitor,
            views=self._view_specs,
            metrics=self.metrics,
            tracer=self.tracer,
            profile_enabled_fn=lambda: self.config["enable_query_profile"],
        )
        # workload access heat folds per execution inside the engine
        self.engine.access = self.access
        # sampled per-operator profiling decisions + calibration folds
        # happen inside the engine's dispatch (engine/plan_profile.py)
        self.engine.plan_profiler = self.plan_profiler
        # the measured ANN route rates (IVF vs brute us/row) come out of
        # the same calibration store — the optimizer's _vector_topn_spec
        # reads them through this hook when costing the index route
        self.engine.executor.profile_store = self.plan_profiler.store
        # serving timeline feeds: engine dispatches (device busy +
        # compile interference), executor uploads (transfer
        # interference), batcher dispatches (occupancy) — server-side
        # feeds (admission, completion) go through db.timeline directly
        self.engine.timeline = self.timeline
        self.engine.executor.timeline = self.timeline
        # spill-segment corruption counting (storage/tmp_file.py) reaches
        # sysstat through the executor the grace-hash pipeline holds
        self.engine.executor.metrics = self.metrics
        # whole-statement fusion: the engine fuses the final result-frame
        # gather into the plan's device program (one dispatch, one D2H of
        # final bytes). Knobs: ob_enable_result_narrow,
        # ob_result_narrow_rows, ob_result_narrow_max_rows
        self.engine.narrow_enabled_fn = (
            lambda: self.config["ob_enable_result_narrow"])
        self.engine.narrow_default_rows = int(
            self.config["ob_result_narrow_rows"])
        self.engine.narrow_max_rows = int(
            self.config["ob_result_narrow_max_rows"])
        self.config.on_change(
            "ob_result_narrow_rows",
            lambda _n, _o, v: setattr(
                self.engine, "narrow_default_rows", int(v)))
        self.config.on_change(
            "ob_result_narrow_max_rows",
            lambda _n, _o, v: setattr(
                self.engine, "narrow_max_rows", int(v)))
        # cross-session continuous-batching scheduler: concurrent
        # fast-path hits fold into batched device dispatches behind ONE
        # cluster-shared DispatchGate (like cluster._timeline) — the
        # weighted per-tenant admission only means anything when every
        # tenant queues at the same gate. Knobs: ob_batch_max_size,
        # ob_batch_max_wait_us, ob_batch_follower_timeout,
        # ob_batch_queue_depth, ob_tenant_admission_slots; admission
        # share: TenantUnit.weight
        from .batcher import DispatchGate, StatementBatcher

        gate = getattr(self.cluster, "_dispatch_gate", None)
        if gate is None:
            gate = DispatchGate()
            self.cluster._dispatch_gate = gate
        self.batcher = StatementBatcher(
            metrics=self.metrics, gate=gate, tenant=self.tenant_name)
        gate.register(self.tenant_name, self.unit.weight)
        self.batcher.timeline = self.timeline
        self.batcher.follower_timeout_s = (
            self.config["ob_batch_follower_timeout"])
        self.batcher.queue_depth = self.config["ob_batch_queue_depth"]
        gate.slots = self.config["ob_tenant_admission_slots"]
        self.config.on_change(
            "ob_batch_follower_timeout",
            lambda _n, _o, v: setattr(self.batcher, "follower_timeout_s", v))
        self.config.on_change(
            "ob_batch_queue_depth",
            lambda _n, _o, v: setattr(self.batcher, "queue_depth", v))
        self.config.on_change(
            "ob_tenant_admission_slots",
            lambda _n, _o, v: setattr(gate, "slots", v))
        # device-memory governor: ONE per-device HBM ledger shared by
        # every tenant on the cluster (like the dispatch gate) — per-
        # tenant shares seeded from TenantUnit.memory_limit, statement
        # admission reserves its estimated peak working set before any
        # upload. Knobs: ob_device_memory_limit (0 = auto/synthetic),
        # ob_governor_queue_timeout, ob_governor_max_queue,
        # ob_governor_cold_reserve
        from ..engine.memory_governor import (MemoryGovernor,
                                              detect_device_budget)

        gov = getattr(self.cluster, "_memory_governor", None)
        if gov is None:
            limit = int(self.config["ob_device_memory_limit"])
            gov = MemoryGovernor(
                limit if limit > 0 else detect_device_budget(),
                max_queue=self.config["ob_governor_max_queue"])
            self.cluster._memory_governor = gov
        self.governor = gov
        gov.register_tenant(self.tenant_name, self.unit.memory_limit,
                            self._resident_bytes)
        self.engine.executor.governor = gov
        self.batcher.governor = gov
        self.config.on_change(
            "ob_device_memory_limit",
            lambda _n, _o, v: gov.set_budget(
                int(v) if int(v) > 0 else detect_device_budget()))
        self.config.on_change(
            "ob_governor_max_queue",
            lambda _n, _o, v: setattr(gov, "max_queue", int(v)))
        # device-resident result cache: repeated dashboard statements
        # serve their narrowed frame with ZERO dispatches. Keyed on the
        # logical entry key + bound literals + committed-data watermark;
        # eagerly dropped by DML (_invalidate), schema bumps, plan-cache
        # flush (the hook below) and the OOM ladder. Its frames are
        # charged against the tenant unit through _resident_bytes.
        from ..engine.result_cache import ResultCache

        self.result_cache = ResultCache(
            capacity_bytes=int(self.config["ob_result_cache_size"]),
            entry_limit=int(self.config["ob_result_cache_entry_limit"]),
            enabled_fn=lambda: self.config["ob_enable_result_cache"],
            pressure_fn=gov.under_pressure,
            metrics=self.metrics,
        )
        self.engine.result_cache = self.result_cache
        self.engine.result_watermark_fn = self._result_watermark
        self.plan_cache.result_cache = self.result_cache
        self.config.on_change(
            "ob_result_cache_size",
            lambda _n, _o, v: setattr(
                self.result_cache, "capacity_bytes", int(v)))
        self.config.on_change(
            "ob_result_cache_entry_limit",
            lambda _n, _o, v: setattr(
                self.result_cache, "entry_limit", int(v)))
        # micro-batch coalescing: two heterogeneous-plan cohorts sharing
        # a pow2 bucket shape fuse into one device dispatch at the gate
        self.batcher.coalesce_enabled = bool(
            self.config["ob_enable_batch_coalesce"])
        self.config.on_change(
            "ob_enable_batch_coalesce",
            lambda _n, _o, v: setattr(
                self.batcher, "coalesce_enabled", bool(v)))
        # completion drain: statement accounting (audit/summary/metrics/
        # timeline folds, governor release) moves behind the wire write
        # when ob_enable_completion_drain is on
        from .completion import CompletionDrain

        self.completion = CompletionDrain(
            depth=int(self.config["ob_completion_drain_depth"]),
            metrics=self.metrics)
        self.config.on_change(
            "ob_completion_drain_depth",
            lambda _n, _o, v: setattr(self.completion, "depth", int(v)))
        # one shared virtual-clock closure: sql() builds a statement
        # Deadline from it on every call — no per-statement lambda
        self._bus_clock = lambda: self.cluster.bus.now
        # distributed (PX) executor, built lazily on the first statement a
        # session routes with ob_px_dop — mesh construction touches every
        # device, so tenants that never use PX never pay for it
        self._px_executor_obj = None
        # PX admission quota (built lazily with the executor): bounds the
        # cluster-wide worker grant before a PX statement may run
        self._px_admission_obj = None
        self._ddl_lock = threading.RLock()
        # persistent compiled-plan artifacts (engine/plan_artifact.py):
        # when ob_plan_artifact_mode != off, exported executables live
        # under plan_artifact_dir (default <data_dir>/plan_artifacts) and
        # boot warm-loads the hottest digests — ranked by the workload
        # repository's statement summaries, bounded by
        # plan_artifact_max_bytes — so a rebooted node serves cached
        # statements with ZERO engine traces
        self.plan_artifact = None
        self.config.on_change(
            "ob_plan_artifact_mode",
            lambda _n, _o, _v: self._reconfigure_plan_artifacts())
        self.config.on_change(
            "plan_artifact_dir",
            lambda _n, _o, _v: self._reconfigure_plan_artifacts())
        self.config.on_change(
            "plan_artifact_max_bytes",
            lambda _n, _o, v: setattr(self.plan_artifact, "max_bytes",
                                      int(v))
            if self.plan_artifact is not None else None)
        self._reconfigure_plan_artifacts()
        # re-materialize restored mviews against the recovered base data
        # (failures keep the registration: REFRESH can retry once the
        # base objects are available again)
        for _mname, _msql in list(self._mview_specs.items()):
            try:
                self._materialize_mview(_mname, _msql)
            except Exception:
                pass

    @property
    def tables(self):
        """Current-version schema view (name -> TableInfo). Cached per
        schema version: the serving path reads this 2x per statement and
        the guard only changes on DDL. The (version, map) tuple swaps
        atomically under the GIL; a stale version check just re-guards."""
        ss = self.schema_service
        v = ss.version
        c = self._tables_cache
        if c is not None and c[0] == v:
            return c[1]
        t = ss.guard(v).tables
        self._tables_cache = (v, t)
        return t

    def _own_tablet_ids(self) -> set[int]:
        ids = set()
        for ti in self.tables.values():
            for _ls, tab in ti.all_partitions():
                ids.add(tab)
            for idx in getattr(ti, "indexes", {}).values():
                ids.add(idx.tablet_id)
        return ids

    def _all_tablets(self):
        """This tenant's tablets on every replica (each replica maintains
        its own LSM). In standalone mode that is every tablet; on a shared
        cluster, only the tenant's own (maintenance/freeze isolation)."""
        own = self._own_tablet_ids() if self._shared_cluster else None
        out = []
        for group in self.cluster.ls_groups.values():
            for rep in group.values():
                for tid, t in rep.tablets.items():
                    if own is None or tid in own:
                        out.append(t)
        return out

    def run_maintenance(self) -> dict:
        """One deterministic freeze/compaction pass (tests and the
        post-commit hook); live servers call maintenance.start()."""
        out = self.maintenance.tick()
        self.maybe_rebalance_leaders()
        self.scrubber.maybe_queue()
        self.dag_scheduler.run_until_idle()
        return out

    # -------------------------------------------- leader rebalance driver
    def maybe_rebalance_leaders(self, force: bool = False) -> list:
        """Rootserver-driven leader rebalancing: feed FailureDetector
        evidence (the keepalive majority vote) and the tenant QoS ledger
        into RootService.balance_leaders, and queue each decided move as
        a background dag that runs cluster.transfer_leader off the
        statement path. A healthy, unpressured cluster plans no moves, so
        this is a cheap no-op on every maintenance tick; throttled by
        leader_rebalance_min_interval regardless."""
        import time as _time

        try:
            if not bool(self.config["enable_leader_rebalance"]):
                return []
        except Exception:  # noqa: BLE001 — config-less Database stub
            return []
        cluster = self.cluster
        if not getattr(cluster, "keepalives", None) or cluster.n_nodes < 2:
            return []
        now = _time.monotonic()
        min_iv = float(self.config["leader_rebalance_min_interval"])
        if not force and self._last_rebalance_at is not None \
                and now - self._last_rebalance_at < min_iv:
            return []
        self._last_rebalance_at = now
        unreachable = cluster.unreachable_nodes()
        moves = self.rootservice.balance_leaders(
            unreachable, spread=self._qos_pressure())
        if not moves:
            return []
        from ..share.dag_scheduler import Dag, DagPriority

        for ls_id, frm, to in moves:
            dag = Dag("leader rebalance", DagPriority.URGENT,
                      key=("leader rebalance", ls_id))

            def move(ls_id=ls_id, frm=frm, to=to):
                cluster.transfer_leader(ls_id, to)
                # the moved LS's cached leader is now wrong everywhere;
                # targeted invalidation, same as the NotMaster path
                self.location.invalidate(ls_id)
                self.metrics.add("leader moved")

            dag.add_task(move, name=f"move ls {ls_id}: {frm} -> {to}")
            self.dag_scheduler.add_dag(dag)
        return moves

    def simulate_node_restart(self, node: int, settle: float = 1.0) -> None:
        """One observer's rolling restart, in-process: take the node's
        bus endpoints down past the lease window (survivors re-elect and
        keep serving), drop the host-side memory state a real restart
        loses — plan-cache memory tiers (NOT the disk artifact store)
        and follower-read views — then rejoin and warm-boot compiled
        plans from the artifact store, so the restarted node's first
        statement is a warm artifact hit, not a cold trace+compile."""
        self.cluster.kill_node(node, settle=settle)
        self.plan_cache.flush(memory_only=True)
        self._follower_views.clear()
        self.cluster.revive_node(node, settle=settle)
        if self.plan_artifact is not None:
            self._warm_boot_plan_artifacts()

    def _qos_pressure(self) -> bool:
        """Serving-pressure bit from the tenant QoS ledger: True when any
        tenant accumulated NEW admission rejections since the last check
        (cumulative totals are diffed against what this driver already
        consumed, so one historic overload doesn't spread leaders
        forever)."""
        tl = getattr(self, "timeline", None)
        if tl is None:
            return False
        try:
            totals = tl.qos_totals()
        except Exception:  # noqa: BLE001 — ledger shape is advisory here
            return False
        pressure = False
        for tenant, row in totals.items():
            rej = int(row.get("rejected", 0))
            if rej > self._rebalance_qos_seen.get(tenant, 0):
                pressure = True
            self._rebalance_qos_seen[tenant] = rej
        return pressure

    # -------------------------------------------------- node durability
    def _meta_path(self) -> str:
        import os

        return os.path.join(self.data_dir, "node_meta.pkl")

    def _ckpt_path(self, node: int, ls_id: int) -> str:
        import os

        return os.path.join(self.data_dir, f"n{node}", f"ls_{ls_id}", "ckpt.pkl")

    def _load_node_meta(self) -> dict | None:
        """Read the newest verifiable node-meta snapshot. Missing means a
        fresh boot (None); a corrupt latest copy is counted, quarantined,
        and boot falls back to the retained .prev (schema changes since
        that snapshot replay from the log). All copies corrupt raises —
        booting with guessed schema would be silent data loss."""
        import os
        import pickle

        from ..storage.integrity import (META, CorruptBlock, CounterSink,
                                         quarantine_file, read_verified)

        sink = CounterSink(self._boot_integrity)
        path = self._meta_path()
        last_err: CorruptBlock | None = None
        for p in (path, path + ".prev"):
            if not os.path.exists(p):
                continue
            try:
                return pickle.loads(read_verified(p, path_class=META))
            except CorruptBlock as e:
                last_err = e
            except Exception as e:  # unpicklable despite a valid crc
                last_err = CorruptBlock(p, f"{type(e).__name__}: {e}")
            sink.add("node meta corruption")
            sink.add("checksum failures")
            quarantine_file(p, last_err.reason)
        if last_err is not None:
            raise last_err
        return None

    def _save_node_meta(self) -> None:
        """Persist schema + TableInfo state (the slog meta-redo analog,
        collapsed to an atomic whole-snapshot at DDL/checkpoint time).
        MUST be written after LS checkpoints within checkpoint(): the meta's
        dictionaries have to cover every code referenced by checkpointed
        tablet rows (later codes are recovered from logged dict_appends)."""
        import pickle

        if self.data_dir is None:
            return
        meta = {
            "n_nodes": self.cluster.n_nodes,
            "n_ls": len(self.cluster.ls_groups),
            "tables": dict(self.tables),
            "next_tablet_id": self.rootservice.next_tablet_id,
            "privileges": self.privileges.to_meta(),
            "vector_specs": dict(self._vector_specs),
            "external_specs": dict(self._external_specs),
            "mview_specs": dict(self._mview_specs),
            "view_specs": dict(self._view_specs),
            "trigger_specs": dict(self._trigger_specs),
            "procedures": dict(self._procedure_texts),
            "sequences": {k: dict(v) for k, v in self._sequences.items()},
            # non-default parameter values: ObConfigManager persists its
            # config file (etc/observer.config.bin), so ALTER SYSTEM SET
            # survives a restart — the plan-artifact warm boot depends on
            # its mode parameter still being rw after the reboot
            "config": (
                {n: v for n, v, p in self.config.snapshot()
                 if v != p.default}
                if getattr(self, "config", None) is not None else {}
            ),
            # advisor encoding picks: the dump path re-applies them on the
            # restarted node even before the advisor re-learns the workload
            "enc_hints": (
                dict(self.layout_advisor.encoding_hints)
                if getattr(self, "layout_advisor", None) is not None else {}
            ),
            # undecided XA branches: belt-and-braces alongside log replay
            # (covers an XA_PREPARE recycled below a later checkpoint)
            "xa_registry": {
                x: {"tx_id": e["tx_id"], "owner": e["owner"],
                    "parts": tuple(e["parts"]),
                    "tablets": sorted(e["tablets"])}
                for x, e in self._xa_registry.items()
            },
        }
        import os

        from ..storage.integrity import META, write_atomic

        path = self._meta_path()
        if os.path.exists(path):
            # keep the previous snapshot: a damaged latest copy still has
            # a fallback (same rotation as LS checkpoints)
            try:
                os.replace(path, path + ".prev")
            except OSError:
                pass
        write_atomic(
            path,
            pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL),
            fsync=self._fsync,
            path_class=META,
        )

    def _restore_from_disk(self, meta: dict) -> None:
        """Boot-time recovery, BEFORE the first election: install LS storage
        checkpoints, reinstall the schema, recreate tablets that postdate
        the last checkpoint. Replay of entries (applied_lsn, commit] then
        happens through the normal apply path once leaders elect."""
        from ..storage.ckpt import read_ls_checkpoint, restore_ls_replica
        from ..storage.integrity import CorruptBlock, CounterSink

        sink = CounterSink(self._boot_integrity)
        for ls_id, group in self.cluster.ls_groups.items():
            for node, rep in group.items():
                try:
                    st = read_ls_checkpoint(
                        self._ckpt_path(node, ls_id), metrics=sink)
                except CorruptBlock:
                    # EVERY retained copy failed verification (each one
                    # counted + quarantined by the reader). Recovery is
                    # full log replay — only safe while nothing below the
                    # checkpoint was recycled, checked just like the
                    # missing-checkpoint case below.
                    st = None
                if st is not None:
                    restore_ls_replica(rep, st)
                    # GTS must clear every restored commit version even if
                    # no log records remain to replay (fully-applied ckpt)
                    self.cluster.gts.advance_to(st.get("max_version", 0))
                elif rep.palf.log.base > 0:
                    raise RuntimeError(
                        f"ls {ls_id} node {node}: log recycled to "
                        f"{rep.palf.log.base} but no readable checkpoint; "
                        "replica needs a snapshot rebuild"
                    )
        tables = meta["tables"]

        def mutate(t: dict) -> None:
            t.update(tables)

        self.schema_service.apply_ddl(mutate)
        for ti in tables.values():
            ti.cached_data_version = -1
            if not hasattr(ti, "indexes"):  # pre-index node_meta snapshots
                ti.indexes = {}
            if not hasattr(ti, "partitions") or ti.partitions is None:
                ti.partitions = [(ti.ls_id, ti.tablet_id)]
                ti.part_col = getattr(ti, "part_col", None)
            for pls, ptab in ti.all_partitions():
                for rep in self.cluster.ls_groups[pls].values():
                    if ptab not in rep.tablets:
                        rep.create_tablet(ptab, ti.schema, ti.key_cols)
            for rep in self.cluster.ls_groups[ti.ls_id].values():
                for idx in ti.indexes.values():
                    if idx.tablet_id not in rep.tablets:
                        rep.create_tablet(idx.tablet_id, idx.schema, idx.key_cols)
            self._unique_keys[ti.name] = tuple(ti.key_cols)
        self.rootservice.next_tablet_id = meta["next_tablet_id"]
        self._ti_by_tablet = None
        # index entries live in sstables installed outside the log (the
        # direct-load analog); a checkpoint covers them, a crash since the
        # last checkpoint may not — re-backfill is idempotent (same-content
        # rows at a newer version) and restores completeness
        self._index_rebuild_pending = [
            (ti, idx)
            for ti in tables.values()
            for idx in ti.indexes.values()
            if idx.status == "ready"
        ]

    def _on_applied_record(self, rec) -> None:
        """Observer of every applied tx record. Normal operation: keeps GTS
        ahead of replicated commit versions. Boot replay: re-applies logged
        dictionary appends (codes past the checkpointed dictionaries) —
        idempotent because codes are dense and append-ordered."""
        from ..tx.records import RecordType as _RT

        if rec.commit_version:
            self.cluster.gts.advance_to(rec.commit_version)
        # XA registry maintenance (idempotent: records apply once per
        # replica; keyed by xid / pruned by tx_id)
        if rec.rtype is _RT.XA_PREPARE and rec.xid and \
                rec.tenant == self.tenant_name:
            e = self._xa_registry.setdefault(rec.xid, {
                "tx_id": rec.tx_id, "owner": rec.owner,
                "parts": tuple(rec.participants), "tablets": set(),
            })
            e["tablets"].update(m.tablet_id for m in rec.mutations)
            self._xa_txids[rec.tx_id] = rec.xid
        elif rec.rtype in (_RT.COMMIT, _RT.ABORT, _RT.REDO_COMMIT):
            _xid = self._xa_txids.pop(rec.tx_id, None)
            if _xid is not None:
                self._xa_registry.pop(_xid, None)
                self._xa_prepared.pop(_xid, None)
        if not rec.dict_appends:
            return
        by_tab = self._ti_by_tablet
        if by_tab is None:
            by_tab = self._ti_by_tablet = {
                ti.tablet_id: ti for ti in self.tables.values()
            }
        apply_dict_appends(by_tab, rec.dict_appends)

    def checkpoint(self, recycle: bool = True) -> bool:
        """slog-ckpt analog: snapshot every replica's storage state, then
        persist schema meta; optionally recycle palf logs below each
        checkpoint. Returns False if any replica skipped (uncommitted
        leader-staged rows) — its log is kept whole and boot replays it."""
        if self.data_dir is None:
            return False
        ok_all = True
        from ..storage.ckpt import write_ls_checkpoint

        done: list[tuple] = []
        for ls_id, group in self.cluster.ls_groups.items():
            for node, rep in group.items():
                covered = write_ls_checkpoint(
                    self._ckpt_path(node, ls_id), rep, fsync=self._fsync
                )
                if covered is not None:
                    done.append((rep, covered))
                else:
                    ok_all = False
        # meta BEFORE recycling: the checkpointed rows' dictionary codes
        # must be durable in meta (or still recoverable from log records)
        # at every instant — recycling first would open a crash window
        # where neither survives
        self._save_node_meta()
        if recycle:
            for rep, covered in done:
                # recycle only what the WRITTEN snapshot covers — the live
                # applied_lsn may have advanced past it since the pickle
                rep.palf.recycle(covered + 1)
        return ok_all

    def close(self) -> None:
        """Flush and release durable resources (log stores), failing
        any forming statement batches to the solo path first."""
        b = getattr(self, "batcher", None)
        if b is not None:
            b.shutdown()
        # deferred completion folds must land before the process goes:
        # close() drains the backlog inline (exactly-once accounting)
        cd = getattr(self, "completion", None)
        if cd is not None:
            cd.close()
        pa = getattr(self, "plan_artifact", None)
        if pa is not None:
            # fold this boot's statement-summary exec counts into the
            # artifact ranking index so the NEXT boot warm-loads the
            # hottest digests first
            try:
                pa.sync_exec_counts(self.stmt_summary.snapshot())
            except Exception:
                pass
            # queued XLA-cache primes must land before the next boot
            # reads them, or the first warm boot re-pays the compile
            try:
                pa.drain()
            except Exception:
                pass
        for group in self.cluster.ls_groups.values():
            for rep in group.values():
                if rep.palf.store is not None:
                    rep.palf.store.close()

    # ------------------------------------------------------------ schema
    def _invalidate(self, name: str) -> None:
        """Drop one table's cached device batches on EVERY executor that
        may hold them — the single-chip engine executor and (when built)
        the PX executor, whose sharded upload cache is separate."""
        self.engine.executor.invalidate_table(name)
        if self._px_executor_obj is not None:
            self._px_executor_obj.invalidate_table(name)
        # cached result frames over this table died with the snapshot
        # (the watermark key already misses; the eager drop frees bytes)
        rc = getattr(self, "result_cache", None)
        if rc is not None:
            rc.invalidate_tables((name,))

    def _px_executor(self):
        """Lazily-built distributed executor over the full device mesh
        (sessions route statements here via SET ob_px_dop)."""
        if self._px_executor_obj is None:
            from ..parallel.mesh import make_mesh
            from ..parallel.px import PxExecutor

            px = PxExecutor(
                self.catalog,
                make_mesh(),
                unique_keys=self._unique_keys,
                stats=self.engine.stats,
                tracer=self.tracer,
                metrics=self.metrics,
                access=self.access,
            )
            # serving-plane wiring: sharded uploads land in the transfer
            # timeline, the partitioned residency charges the memory
            # governor bytes/n_shards per device, and PX prepare()
            # consults the governed upload budget like single-chip
            px.timeline = self.timeline
            gov = getattr(self, "governor", None)
            if gov is not None:
                px.governor = gov
                gov.register_sharded_residency(
                    px.residency.per_device_bytes)
            self._px_executor_obj = px
        return self._px_executor_obj

    def _px_admission(self):
        """Cluster-wide DOP quota (ObPxAdmission / ObPxTargetMgr): every PX
        statement acquires its worker grant here before executing, so a
        burst queues instead of oversubscribing the mesh. Sized from the
        parallel_servers_target config parameter (live-updatable)."""
        if self._px_admission_obj is None:
            from ..parallel.px import PxAdmission

            self._px_admission_obj = PxAdmission(
                target=self.config["parallel_servers_target"]
            )
            self.config.on_change(
                "parallel_servers_target",
                lambda _n, _o, v: setattr(self._px_admission_obj, "target", v),
            )
        return self._px_admission_obj

    def _key_extra(self, table_names: tuple[str, ...]) -> tuple:
        """Plan-cache key material: schema + dictionary versions of the
        referenced DML-backed tables (string literals bake dictionary
        lookups at trace time; a grown dictionary needs a fresh trace)."""
        out = []
        tables = self.tables
        for t in table_names:
            ti = tables.get(t)
            if ti is not None:
                out.append((t, ti.schema_version, ti.dict_sig))
        return tuple(out)

    def _result_watermark(self, table_names) -> tuple:
        """Result-cache key material: the referenced tables' committed
        data versions (the snapshot watermark). Any committed DML bumps a
        version, so a cached frame can never serve across it — the
        key_extra half (schema/dict versions) rides the logical entry key
        already."""
        out = []
        tables = self.tables
        for t in table_names:
            ti = tables.get(t)
            if ti is not None:
                out.append((t, ti.data_version))
        return tuple(out)

    # ---------------------------------------------- plan artifact store
    def _reconfigure_plan_artifacts(self) -> None:
        """(Re)wire the on-disk plan-artifact tier from config. Called at
        boot and on ob_plan_artifact_mode / plan_artifact_dir changes."""
        import os

        mode = self.config["ob_plan_artifact_mode"]
        adir = str(self.config["plan_artifact_dir"] or "")
        if not adir and self.data_dir is not None:
            adir = os.path.join(self.data_dir, "plan_artifacts")
        if mode == "off" or not adir:
            self.plan_artifact = None
            self.plan_cache.artifact_store = None
            return
        store = self.plan_artifact
        if store is not None and store.root == adir:
            store.mode = mode
            self.plan_cache.artifact_store = store
            return
        from ..engine.plan_artifact import PlanArtifactStore

        store = PlanArtifactStore(
            adir, mode=mode,
            max_bytes=self.config["plan_artifact_max_bytes"],
            metrics=self.metrics)
        self.plan_artifact = store
        self.plan_cache.artifact_store = store
        self._warm_boot_plan_artifacts()

    def _warm_boot_plan_artifacts(self) -> None:
        """Boot-time warm load: hydrate the hottest exported executables
        — ranked by the statement-summary exec counts persisted in the
        store index — until the byte budget is spent. Each hydrated entry
        lands in the plan cache under the same logical key the session
        computes, so the first execution of that statement is a plain
        cache hit: zero engine traces, and the backend compile of the
        deserialized program comes out of the XLA persistent cache."""
        from ..sql.plan_cache import CacheEntry, FastEntry

        store = self.plan_artifact
        if store is None or not store.readable:
            return
        budget = int(store.max_bytes)
        spent = loaded = 0
        for aid, info in store.ranked():
            nbytes = int(info.get("bytes", 0))
            if spent + nbytes > budget:
                continue
            meta = store.read_meta(aid)
            if meta is None:
                continue
            ex = self.engine.executor
            if meta.px_nsh:
                try:
                    ex = self._px_executor()
                except Exception:
                    continue
                if getattr(ex, "nsh", 0) != meta.px_nsh:
                    continue  # mesh shape moved; entry stays for ro tools
            got = store.hydrate(aid, ex, key_extra_fn=self._key_extra,
                                meta=meta)
            if got is None:
                continue
            meta, prepared = got
            extra = self._key_extra(meta.tables)
            if meta.px_nsh:
                extra = (*extra, "#exec", id(ex))
            key = (id(self.catalog), meta.art_key[0], meta.art_key[1],
                   meta.art_key[2], meta.art_key[3], extra)
            if self.plan_cache.get(key, count_miss=False) is None:
                entry = CacheEntry(prepared, tuple(meta.output_names),
                                   list(meta.dtypes))
                entry.json_specs, entry.json_hidden = (), ()
                self.plan_cache.put(key, entry)
            if meta.fast and meta.text_key:
                try:
                    self.plan_cache.fast_put(
                        meta.text_key, FastEntry(**meta.fast))
                except Exception:
                    pass
            spent += nbytes
            loaded += 1
        if loaded:
            self.metrics.add("plan artifact warm load", loaded)
            self.metrics.add("plan artifact warm bytes", spent)

    def refresh_virtual(self, names) -> bool:
        """Materialize referenced __all_virtual_* tables for this statement.
        Returns True if any were referenced (such statements bypass the plan
        cache: per-materialization dictionaries make entries unreusable)."""
        from .virtual_tables import PROVIDERS

        any_vt = False
        for name in names:
            p = PROVIDERS.get(name)
            if p is None:
                continue
            if not any_vt:
                # read-your-own-accounting barrier: deferred completion
                # folds (audit/summary/metrics) must land before a
                # diagnostic snapshot materializes, or `SELECT ... FROM
                # sql_audit` would miss the statements just served
                cd = getattr(self, "completion", None)
                if cd is not None and cd.submitted > cd.drained:
                    cd.flush()
            self.catalog[name] = p(self)
            self._invalidate(name)
            any_vt = True
        return any_vt

    def create_table(self, stmt: A.CreateTable) -> None:
        with self._ddl_lock:
            if stmt.name in self.tables or stmt.name in self.catalog:
                if stmt.if_not_exists:
                    return
                raise SqlError(f"table {stmt.name} already exists")
            fields = []
            for c in stmt.columns:
                dt = _parse_type(c.type_name)
                if not c.not_null:
                    dt = dt.with_nullable(True)
                fields.append(Field(c.name, dt))
            schema = Schema(tuple(fields))
            pk = list(stmt.primary_key) or [stmt.columns[0].name]
            for k in pk:
                if k not in schema:
                    raise SqlError(f"primary key column {k} not in table")
                # key columns are implicitly NOT NULL (MySQL semantics)
                i = schema.index(k)
                fields[i] = Field(k, fields[i].dtype.with_nullable(False))
            schema = Schema(tuple(fields))
            if stmt.partition_by is not None:
                if stmt.partition_by not in schema:
                    raise SqlError(
                        f"partition column {stmt.partition_by} not in table"
                    )
                if stmt.partition_by not in pk:
                    # MySQL rule: the partition key must be part of every
                    # unique key, or cross-partition duplicates could hide
                    raise SqlError(
                        "partition column must be part of the primary key"
                    )

            def factory(partitions: list[tuple[int, int]]) -> TableInfo:
                ls_id, tablet_id = partitions[0]
                ti = TableInfo(
                    stmt.name, schema, pk, ls_id, tablet_id,
                    partitions=list(partitions),
                    part_col=stmt.partition_by,
                )
                for f in schema.fields:
                    if f.dtype.kind is TypeKind.VARCHAR:
                        ti.dicts[f.name] = Dictionary()
                return ti

            try:
                ti = self.rootservice.create_table(
                    factory, n_partitions=stmt.n_partitions
                )
            except SchemaError as e:
                raise SqlError(str(e)) from None
            for ls_id, tablet_id in ti.all_partitions():
                for rep in self.cluster.ls_groups[ls_id].values():
                    rep.tablets[tablet_id].cache = self.block_cache
            self._unique_keys[stmt.name] = tuple(pk)
            self._ti_by_tablet = None
            self.catalog[stmt.name] = Table(stmt.name, schema, {
                f.name: np.zeros(0, f.dtype.storage_np) for f in schema.fields
            })
            self._save_node_meta()

    def drop_table(self, stmt: A.DropTable) -> None:
        with self._ddl_lock:
            try:
                ti = self.rootservice.drop_table(stmt.name)
            except SchemaError:
                if stmt.if_exists:
                    return
                raise SqlError(f"no such table {stmt.name}") from None
            for idx in getattr(ti, "indexes", {}).values():
                for rep in self.cluster.ls_groups[ti.ls_id].values():
                    rep.tablets.pop(idx.tablet_id, None)
            self.catalog.pop(stmt.name, None)
            self._unique_keys.pop(stmt.name, None)
            self._ti_by_tablet = None
            self._invalidate(stmt.name)
            self._save_node_meta()

    # ---------------------------------------------------------- sequences
    SEQ_CACHE = 100  # values reserved per meta write

    def create_sequence(self, name: str, start: int = 1,
                        inc: int = 1) -> None:
        with self._ddl_lock:
            if name in self._sequences:
                raise SqlError(f"sequence {name} already exists")
            self._sequences[name] = {
                "next": start, "inc": inc, "reserved": start,
            }
            self._save_node_meta()

    def drop_sequence(self, name: str) -> None:
        with self._ddl_lock:
            if self._sequences.pop(name, None) is None:
                raise SqlError(f"no sequence {name}")
            self._save_node_meta()

    def sequence_next(self, name: str) -> int:
        with self._ddl_lock:
            sq = self._sequences.get(name)
            if sq is None:
                raise SqlError(f"no sequence {name}")
            v = sq["next"]
            inc = sq["inc"]
            sq["next"] = v + inc
            sq["last"] = v  # in-process only: currval before any
            # nextval (or right after restart) is an error, never a
            # value that was skipped or never issued
            past = (
                sq["next"] > sq["reserved"] if inc > 0
                else sq["next"] < sq["reserved"]
            )
            if past or v == sq["reserved"]:
                # crossed into unreserved territory: reserve a new block
                sq["reserved"] = sq["next"] + inc * self.SEQ_CACHE
                self._save_node_meta()
            return v

    # --------------------------------------------------------- plain views
    def create_view(self, st: "A.CreateView") -> None:
        """CREATE [OR REPLACE] VIEW (ob_create_view_resolver.h analog):
        only the definition text persists; expansion/merge happens at plan
        time through the planner's shared view dict."""
        from ..sql import parser as P2

        with self._ddl_lock:
            if st.name in self.tables or st.name in self._mview_specs or \
                    st.name in self._external_specs:
                raise SqlError(f"object {st.name} already exists")
            if st.name in self._view_specs and not st.or_replace:
                raise SqlError(f"view {st.name} already exists")
            body = P2.parse(st.query_sql)
            if not isinstance(body, (A.Select, A.SetSelect)):
                raise SqlError("CREATE VIEW body must be a SELECT")
            # validate references NOW (MySQL checks at create): every
            # referenced name must be a table, view, or mview
            for n in self.expand_views(_tables_in_ast(body)):
                if n not in self.tables and n not in self._mview_specs \
                        and n not in self.catalog:
                    raise SqlError(f"view references unknown table {n}")
            self._view_specs[st.name] = st.query_sql
            self._save_node_meta()

    def drop_view(self, name: str) -> None:
        with self._ddl_lock:
            if self._view_specs.pop(name, None) is None:
                raise SqlError(f"no view {name}")
            self._save_node_meta()

    def expand_views(self, names: set) -> set:
        """Map a statement's referenced names through view definitions to
        the BASE tables that must be fresh in the analytic catalog."""
        from ..sql import parser as P2

        out: set = set()
        stack, seen = list(names), set()
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            spec = self._view_specs.get(n)
            if spec is None:
                out.add(n)
                continue
            try:
                stack.extend(_tables_in_ast(P2.parse(spec)))
            except SyntaxError:
                pass
        return out

    # ------------------------------------------------------------ triggers
    def create_trigger(self, st: "A.CreateTrigger") -> None:
        from ..sql.trigger import TriggerError, parse_body

        with self._ddl_lock:
            if st.name in self._trigger_specs:
                raise SqlError(f"trigger {st.name} already exists")
            if st.table not in self.tables:
                raise SqlError(f"no such table {st.table}")
            try:
                acts = parse_body(st.body_sql)
            except (TriggerError, SyntaxError) as e:
                raise SqlError(f"bad trigger body: {e}") from None
            if st.timing == "after" and any(a[0] == "setnew" for a in acts):
                raise SqlError("SET NEW.x is only valid in BEFORE triggers")
            if st.event == "delete" and any(a[0] == "setnew" for a in acts):
                raise SqlError("DELETE triggers have no NEW row")
            self._trigger_specs[st.name] = {
                "timing": st.timing, "event": st.event,
                "table": st.table, "body": st.body_sql,
            }
            self._trigger_parsed[st.name] = acts
            self._save_node_meta()

    def drop_trigger(self, name: str) -> None:
        with self._ddl_lock:
            if self._trigger_specs.pop(name, None) is None:
                raise SqlError(f"no trigger {name}")
            self._trigger_parsed.pop(name, None)
            self._save_node_meta()

    def triggers_for(self, table: str, event: str, timing: str) -> list:
        """Parsed bodies of matching triggers, in name order (the firing
        order contract)."""
        from ..sql.trigger import parse_body

        out = []
        for name in sorted(self._trigger_specs):
            spec = self._trigger_specs[name]
            if spec["table"] != table or spec["event"] != event or \
                    spec["timing"] != timing:
                continue
            acts = self._trigger_parsed.get(name)
            if acts is None:
                acts = self._trigger_parsed[name] = parse_body(spec["body"])
            out.append((name, acts))
        return out

    # -------------------------------------------------- materialized views
    def create_mview(self, st: A.CreateMaterializedView) -> None:
        """Full-refresh materialized view (src/storage/mview analog at
        this engine's scale: definition text in meta like the reference's
        schema-service mview definitions; REFRESH re-plans and
        re-materializes against current data)."""
        with self._ddl_lock:
            if st.name in self.tables or st.name in self.catalog:
                raise SqlError(f"table {st.name} already exists")
        # materialization (plan + XLA compile + run) happens OUTSIDE the
        # DDL lock — it can take seconds and must not stall other DDL;
        # the lock re-checks before the catalog swap
        from ..sql import parser as P2

        self.refresh_catalog(_tables_in_ast(P2.parse(st.query_sql)), tx=None)
        t = self.engine.materialize(st.query_sql, st.name)
        with self._ddl_lock:
            if st.name in self.tables or st.name in self.catalog:
                raise SqlError(f"table {st.name} already exists")
            self.catalog[st.name] = t
            self._invalidate(st.name)
            self._mview_specs[st.name] = st.query_sql
            self._save_node_meta()

    def _materialize_mview(self, name: str, sql_text: str) -> None:
        from ..sql import parser as P2

        # base-table snapshots must be current before the defining query
        # runs (the same refresh every SELECT path does)
        self.refresh_catalog(
            _tables_in_ast(P2.parse(sql_text)), tx=None)
        self.catalog[name] = self.engine.materialize(sql_text, name)
        self._invalidate(name)

    def refresh_mview(self, name: str) -> None:
        with self._ddl_lock:
            sql_text = self._mview_specs.get(name)
        if sql_text is None:
            raise SqlError(f"no materialized view {name}")
        from ..sql import parser as P2

        self.refresh_catalog(
            _tables_in_ast(P2.parse(sql_text)), tx=None)
        t = self.engine.materialize(sql_text, name)
        with self._ddl_lock:
            if name not in self._mview_specs:
                return  # dropped concurrently: discard, don't resurrect
            self.catalog[name] = t
            self._invalidate(name)

    def drop_mview(self, name: str) -> None:
        with self._ddl_lock:
            if self._mview_specs.pop(name, None) is None:
                raise SqlError(f"no materialized view {name}")
            self.catalog.pop(name, None)
            self._invalidate(name)
            self._save_node_meta()

    def create_external_table(self, st: A.CreateExternalTable) -> None:
        """External table via the plugin loader registry (src/plugin's
        ob_external_arrow_data_loader analog): the file materializes as
        a columnar catalog Table readable by every query path; DML is
        rejected (the table is not LSM-backed), matching the reference's
        read-only external tables."""
        from ..plugin import ExternalFormatError, load_external

        with self._ddl_lock:
            if st.name in self.tables or st.name in self.catalog:
                raise SqlError(f"table {st.name} already exists")
            try:
                t = load_external(st.name, st.format, st.location)
            except ExternalFormatError as e:
                raise SqlError(str(e)) from None
            except OSError as e:
                raise SqlError(f"cannot read {st.location}: {e}") from None
            self.catalog[st.name] = t
            self._external_specs[st.name] = (st.format, st.location)
            self._save_node_meta()

    # ----------------------------------------------------------- indexes
    def create_vector_index(self, st: A.CreateVectorIndex) -> None:
        """IVF-flat ANN index registration (storage/vector_index.py);
        the artifact builds lazily per table version, so DML maintenance
        is the usual invalidate + rebuild contract."""
        from ..core.dtypes import TypeKind
        from ..storage.vector_index import register_vector_index

        ti = self.tables.get(st.table)
        if ti is None:
            raise SqlError(f"no such table {st.table}")
        try:
            ct = ti.schema[st.column]
        except Exception:
            raise SqlError(f"no such column {st.column}") from None
        if ct.kind is not TypeKind.VECTOR:
            raise SqlError(f"{st.column} is not a VECTOR column")
        self._vector_specs.setdefault(st.table, {})[st.column] = (
            st.lists, st.nprobe)
        t = self.catalog.get(st.table)
        if t is not None:
            register_vector_index(
                self.catalog, st.table, st.column, st.lists, st.nprobe)
        self._save_node_meta()

    def drop_vector_index(self, st: A.DropVectorIndex) -> None:
        from ..storage.vector_index import drop_vector_index

        specs = self._vector_specs.get(st.table, {})
        specs.pop(st.column, None)
        t = self.catalog.get(st.table)
        if t is not None:
            drop_vector_index(self.catalog, st.table, st.column)
        self._save_node_meta()

    def create_index(self, st: A.CreateIndex) -> None:
        """Online-ish index build (src/storage/ddl direct-insert analog):

        1. register the index under a momentary SHARE table lock — from
           that instant every DML statement maintains it, and the SHARE
           grant guarantees no tx holding ROW_X (staged base writes that
           would miss maintenance) spans the registration;
        2. backfill from a base-table snapshot taken after registration via
           the direct-load path (an sstable at the snapshot version on all
           replicas) — concurrent post-registration DML lands at HIGHER
           commit versions, so MVCC ordering resolves every interleaving;
        3. flip to ready."""
        from ..tx.tablelock import LockMode, WouldBlock

        with self._ddl_lock:
            ti = self.tables.get(st.table)
            if ti is None:
                raise SqlError(f"no such table {st.table}")
            if st.name in ti.indexes:
                if st.if_not_exists:
                    return
                raise SqlError(f"index {st.name} already exists on {st.table}")
            for c in st.columns:
                if c not in ti.schema:
                    raise SqlError(f"unknown column {c}")
            icols = list(st.columns)
            kcols = icols + [k for k in ti.key_cols if k not in icols]
            ischema = Schema(tuple(Field(c, ti.schema[c]) for c in kcols))
            ikey = icols if st.unique else kcols

            lock_tx = -next(self._session_ids)  # DDL-private lock owner
            deadline = _time.monotonic() + 10.0
            while True:
                try:
                    self.lock_mgr.lock(lock_tx, ti.tablet_id, LockMode.SHARE)
                    break
                except WouldBlock:
                    if _time.monotonic() > deadline:
                        raise SqlError(
                            f"create index on {st.table}: writers did not drain"
                        ) from None
                    _time.sleep(0.005)
            try:
                tablet_id = self.rootservice.create_index_tablet(
                    ti.ls_id, ischema, ikey
                )
                idx = IndexInfo(
                    st.name, st.table, tuple(icols), tablet_id, ischema,
                    ikey, unique=st.unique,
                )

                def mutate(tables: dict) -> None:
                    tables[st.table].indexes[st.name] = idx

                ti.schema_version = self.schema_service.apply_ddl(mutate)
            finally:
                self.lock_mgr.release_all(lock_tx)
            for rep in self.cluster.ls_groups[ti.ls_id].values():
                rep.tablets[tablet_id].cache = self.block_cache
            try:
                self._backfill_index(ti, idx)
            except Exception:
                def unmutate(tables: dict) -> None:
                    tables[st.table].indexes.pop(st.name, None)

                self.schema_service.apply_ddl(unmutate)
                for rep in self.cluster.ls_groups[ti.ls_id].values():
                    rep.tablets.pop(tablet_id, None)
                raise
            self._save_node_meta()

    def _backfill_index(self, ti: TableInfo, idx: IndexInfo) -> None:
        """Fill the index tablet from a base snapshot (direct-load style:
        one sorted sstable installed on every replica at the snapshot
        version). Idempotent — re-running adds same-content rows at a newer
        version, which is how crash recovery re-completes an index."""
        from ..storage.sstable import SSTable, write_sstable

        s0 = self.cluster.gts.next_ts()
        parts = []
        for pls, ptab in ti.all_partitions():
            rep = self._leader_replica_ls(pls)
            parts.append(rep.tablets[ptab].scan(
                s0, columns=list(idx.schema.names())
            ))
        data = (
            parts[0] if len(parts) == 1
            else {c: np.concatenate([p[c] for p in parts]) for c in parts[0]}
        )
        n = len(data[idx.schema.names()[0]]) if idx.schema.names() else 0
        if n:
            keys = [data[k].astype(np.int64) for k in idx.key_cols]
            order = np.lexsort(tuple(reversed(keys)))
            cols = {c: data[c][order] for c in idx.schema.names()}
            if idx.unique:
                k2d = np.stack([cols[k].astype(np.int64) for k in idx.key_cols], axis=1)
                dup = (k2d[1:] == k2d[:-1]).all(axis=1)
                if dup.any():
                    raise SqlError(
                        f"unique index {idx.name}: duplicate value "
                        f"{tuple(k2d[1:][dup][0])}"
                    )
            blob = write_sstable(
                idx.schema, idx.key_cols, cols,
                versions=np.full(n, s0, np.int64),
                ops=np.zeros(n, np.int8),
                base_version=0, end_version=s0,
            )
            for r in self.cluster.ls_groups[ti.ls_id].values():
                t = r.tablets[idx.tablet_id]
                with t._meta_lock:
                    t.deltas.append(
                        SSTable(blob, idx.schema, idx.key_cols,
                                cache=self.block_cache)
                    )
        idx.build_version = s0
        idx.status = "ready"

    def drop_index(self, st: A.DropIndex) -> None:
        with self._ddl_lock:
            ti = self.tables.get(st.table)
            idx = ti.indexes.get(st.name) if ti is not None else None
            if idx is None:
                if st.if_exists:
                    return
                raise SqlError(f"no such index {st.name} on {st.table}")

            def mutate(tables: dict) -> None:
                tables[st.table].indexes.pop(st.name, None)

            ti.schema_version = self.schema_service.apply_ddl(mutate)
            for rep in self.cluster.ls_groups[ti.ls_id].values():
                rep.tablets.pop(idx.tablet_id, None)
            self._save_node_meta()

    # ---------------------------------------------------------- snapshots
    #: bound on per-call location refreshes before the stale entry is
    #: surfaced to the statement retry layer as a classified error
    _LOCATION_RETRY_LIMIT = 8

    def _leader_replica_ls(self, ls_id: int):
        """Route through the location cache; stale entries retry under the
        STALE_LOCATION policy — bounded, backed off on the virtual clock so
        an in-flight election can settle between probes (the NOT_MASTER
        feedback loop of the reference's DAS routing). Exhausting the bound
        raises StaleLocation, which the statement retry controller treats
        as retryable-after-refresh."""
        from ..share.interrupt import checkpoint

        policy = _R.STALE_LOCATION
        attempt = 0
        while True:
            try:
                node = self.location.leader(ls_id)
            except RuntimeError:
                # the resolver itself found no ready leader (election still
                # in flight): same retry treatment as a stale cache entry
                rep = None
            else:
                rep = self.cluster.ls_groups[ls_id][node]
            if rep is not None and rep.is_ready:
                return rep
            attempt += 1
            if attempt > self._LOCATION_RETRY_LIMIT:
                self.metrics.add("location retries exhausted")
                raise _R.StaleLocation(
                    f"ls {ls_id}: no ready leader after "
                    f"{self._LOCATION_RETRY_LIMIT} location refreshes"
                )
            self.metrics.add("location cache refreshes")
            self.location.invalidate(ls_id)
            checkpoint()  # deadline / KILL QUERY unwind between probes
            wait = min(policy.base_wait * attempt, policy.max_wait)
            with self.metrics.waiting("location cache refresh"):
                self.cluster.settle(wait)

    def _leader_replica(self, ti: TableInfo):
        return self._leader_replica_ls(ti.ls_id)

    def snapshot_table(self, name: str, snapshot: int) -> Table:
        """FLASHBACK read: materialize `name` AS OF an older MVCC
        snapshot (reference: ob_log_flashback_service / Oracle-mode
        SELECT ... AS OF SNAPSHOT). Versions survive until major
        compaction discards them — reads below the discarded snapshot
        raise SnapshotDiscarded, the same undo-retention contract."""
        ti = self.tables.get(name)
        if ti is None:
            raise SqlError(f"no such table {name}")
        parts = []
        for ls_id, tablet_id in ti.all_partitions():
            rep = self._leader_replica_ls(ls_id)
            parts.append(rep.tablets[tablet_id].scan(snapshot, tx_id=0))
        if len(parts) == 1:
            data = parts[0]
        else:
            data = {
                c: np.concatenate([p[c] for p in parts])
                for c in parts[0]
            }
        dicts = {}
        for col in ti.dicts:
            sd, remap = ti.sorted_dict(col)
            if len(data[col]):
                data[col] = remap[data[col]]
            dicts[col] = sd
        return Table(name, ti.schema, data, dicts)

    def refresh_catalog(self, names, tx=None) -> None:
        """Bring catalog snapshot Tables of the given tables up to date.

        Inside an open tx every tablet table reads at the tx's BEGIN-time
        snapshot (repeatable reads across the whole statement set); tables
        the tx wrote additionally see their own staged rows via tx_id. Tx
        views are never left in the committed cache."""
        for name in names:
            ti = self.tables.get(name)
            if ti is None:
                continue  # preloaded read-only table
            in_tx = tx is not None and tx.ctx is not None
            if not in_tx and ti.cached_data_version == ti.data_version:
                continue
            touched = in_tx and name in tx.touched_tables
            snap = (
                tx.ctx.read_snapshot if in_tx else self.cluster.gts.current()
            )
            parts = []
            for ls_id, tablet_id in ti.all_partitions():
                if touched:
                    rep = tx.svc.replicas[ls_id]
                else:
                    rep = self._leader_replica_ls(ls_id)
                parts.append(rep.tablets[tablet_id].scan(
                    snap, tx_id=tx.ctx.tx_id if touched else 0,
                ))
            if len(parts) == 1:
                data = parts[0]
            else:
                data = {
                    c: np.concatenate([p[c] for p in parts])
                    for c in parts[0]
                }
            dicts = {}
            for col in ti.dicts:
                sd, remap = ti.sorted_dict(col)
                if len(data[col]):
                    data[col] = remap[data[col]]
                dicts[col] = sd
            for f in ti.schema.fields:
                # tablet cells store vectors as tuples, so the scan
                # yields a 1-D object column; every downstream consumer
                # (IVF build, route costing, H2D upload, mesh sharding)
                # wants the dense (n, d) float32 form — normalize once
                if f.dtype.kind is TypeKind.VECTOR:
                    a = data[f.name]
                    dim = int(f.dtype.precision)
                    data[f.name] = (
                        np.asarray(a.tolist(), dtype=np.float32)
                        .reshape(len(a), dim)
                        if len(a) else np.zeros((0, dim), np.float32))
            t = Table(name, ti.schema, data, dicts)
            if in_tx:
                # tx-private view (BEGIN snapshot + own staged rows): lives
                # on the tx, activated per-statement via catalog.tx_scope —
                # never the shared committed entry other sessions read
                tx.views[name] = t
            else:
                # the replaced Table object carries no sorted_projections
                # registration, so routing stops by construction; delete
                # the orphaned projection tables, their device batches,
                # and every cached plan (a cached plan routed to the
                # dropped projection would KeyError — or worse, a
                # re-materialized namesake would serve stale device
                # columns)
                old = self.catalog.get(name)
                projs = getattr(old, "sorted_projections", None)
                requeue = None
                if projs:
                    from ..storage.sorted_projection import drop_projections

                    # DML invalidation is not silent: it counts in sysstat
                    # and the advisor re-queues a background rebuild (auto
                    # mode / advisor-managed layouts) instead of losing
                    # the projection until someone hand-rebuilds it
                    self.metrics.add(
                        "sorted projection invalidations", len(projs))
                    try:
                        requeue = self.layout_advisor.note_invalidated(
                            name, projs)
                    except Exception:  # noqa: BLE001 - advisory path
                        pass
                    for pname in projs.values():
                        self._invalidate(pname)
                    drop_projections(self.catalog, name)
                    self.plan_cache.flush()
                self.catalog[name] = t
                vspecs = self._vector_specs.get(name)
                if vspecs:
                    from ..storage.vector_index import register_vector_index

                    for col, (lists, nprobe) in vspecs.items():
                        register_vector_index(
                            self.catalog, name, col, lists, nprobe)
                    # DML invalidated the built IVF artifacts (the
                    # _invalidate below drops the executor's #ivfh/#ivfd
                    # caches): re-queue background rebuilds so the next
                    # ANN query probes warm instead of k-means inline
                    self.metrics.add(
                        "vector index invalidations", len(vspecs))
                    try:
                        self.layout_advisor.note_vector_invalidated(
                            name, list(vspecs))
                    except Exception:  # noqa: BLE001 - advisory path
                        pass
                self._invalidate(name)
                ti.cached_data_version = ti.data_version
                if requeue is not None:
                    try:
                        # only now that the refreshed snapshot landed: a
                        # dag worker starting the rebuild must see the
                        # current version, not re-enter this refresh
                        requeue()
                    except Exception:  # noqa: BLE001 - advisory path
                        pass
                self._enforce_memory(keep=name)

    # ------------------------------------------------------ follower reads
    #: bound on replica-snapshot catch-up waits before a bounded-staleness
    #: read rejects back to the leader path
    _FOLLOWER_WAIT_LIMIT = 3
    _FOLLOWER_VIEW_CACHE_MAX = 128

    def _follower_replica(self, ls_id: int, dead: set[int]):
        """Serving replica for a follower read of ls_id: the highest-
        watermark non-leader replica on a reachable node, falling back to
        the leader itself (a one-survivor cluster keeps serving). None
        when every replica is unreachable."""
        group = self.cluster.ls_groups[ls_id]
        best = None
        for node, rep in sorted(group.items()):
            if node in dead or rep.is_leader:
                continue
            if best is None or rep.apply_watermark > best.apply_watermark:
                best = rep
        if best is not None:
            return best
        for node, rep in sorted(group.items()):
            if node not in dead and rep.is_leader:
                return rep
        return None

    def _follower_snapshot(self, reps) -> int:
        """Largest provably-complete snapshot across the chosen replicas.

        Caught-up fast path: under gts.submit_lock a fresh GTS read is
        safe when every replica has applied its live leader's last
        appended entry — the lock excludes any committer between version
        fetch and log append, so no commit version <= ts can be missing.
        Otherwise the min apply watermark: submit-lock ordering makes an
        applied scn dominate every earlier commit version in that log."""
        gts = self.cluster.gts
        with gts.submit_lock:
            ts = gts.current()
            for rep in reps:
                group = self.cluster.ls_groups[rep.ls_id]
                lead = _leader_of([r.palf for r in group.values()])
                if lead is None or rep.palf.applied_lsn != len(lead.log) - 1:
                    break
            else:
                return ts
        return min((rep.apply_watermark for rep in reps), default=ts)

    def follower_read_views(self, names, max_stale_us: int,
                            weak: bool = False):
        """Statement-scoped follower Tables for the replicated tables
        among `names`, read at a bounded-staleness snapshot.

        Returns (views, snapshot, stale_us), or None when the bound
        cannot be met (counted as a staleness reject — the caller falls
        back to the leader path), when no replicated table is involved,
        or when an LS has an undecided prepared (2PC/XA) transaction on
        its chosen replica — the prepare carries no version floor in
        this rebuild, so a non-weak read cannot prove completeness."""
        dead = self.cluster.unreachable_nodes()
        involved: dict[str, TableInfo] = {}
        chosen: dict[int, "LSReplica"] = {}
        for name in names:
            ti = self.tables.get(name)
            if ti is None:
                continue
            involved[name] = ti
            for ls_id, _tab in ti.all_partitions():
                if ls_id in chosen:
                    continue
                rep = self._follower_replica(ls_id, dead)
                if rep is None:
                    return None
                chosen[ls_id] = rep
        if not involved:
            return None
        reps = list(chosen.values())
        if not weak and any(rep._pending_redo for rep in reps):
            self.metrics.add("follower read staleness rejects")
            return None
        attempt = 0
        while True:
            snap = self._follower_snapshot(reps)
            stale_us = max(0, self.cluster.gts.current() - snap)
            if weak or stale_us <= max_stale_us:
                break
            attempt += 1
            if attempt > self._FOLLOWER_WAIT_LIMIT:
                self.metrics.add("follower read staleness rejects")
                return None
            # lagging replication may catch up within the bound: drive
            # the cluster briefly before rejecting back to the leader
            with self.metrics.waiting("replica snapshot wait"):
                self.cluster.settle(0.05 * attempt)
        views = {
            name: self._follower_table(name, ti, chosen, snap)
            for name, ti in involved.items()
        }
        return views, snap, stale_us

    def _follower_table(self, name: str, ti: "TableInfo",
                        chosen: dict, snap: int) -> Table:
        """Materialize one table from its chosen replicas at `snap`,
        cached by (replica apply positions, dict signature): an unchanged
        applied_lsn means no new rows applied, so any snapshot >= the
        cached one scans to identical rows."""
        pkey = tuple(
            (ls_id, chosen[ls_id].node_id, chosen[ls_id].palf.applied_lsn)
            for ls_id, _tab in ti.all_partitions()
        )
        key = (name, pkey, ti.dict_sig)
        hit = self._follower_views.get(key)
        if hit is not None:
            return hit
        parts = []
        for ls_id, tablet_id in ti.all_partitions():
            parts.append(chosen[ls_id].tablets[tablet_id].scan(snap, tx_id=0))
        if len(parts) == 1:
            data = parts[0]
        else:
            data = {
                c: np.concatenate([p[c] for p in parts]) for c in parts[0]
            }
        dicts = {}
        for col in ti.dicts:
            sd, remap = ti.sorted_dict(col)
            if len(data[col]):
                data[col] = remap[data[col]]
            dicts[col] = sd
        t = Table(name, ti.schema, data, dicts)
        while len(self._follower_views) >= self._FOLLOWER_VIEW_CACHE_MAX:
            self._follower_views.pop(next(iter(self._follower_views)))
        self._follower_views[key] = t
        return t

    def _resident_bytes(self) -> int:
        """Approximate bytes of DML-backed catalog snapshots (the tenant's
        resident analytic memory — the unit's accounting surface)."""
        total = 0
        for name, ti in self.tables.items():
            t = self.catalog.get(name)
            if t is None:
                continue
            for a in t.data.values():
                total += getattr(a, "nbytes", 0)
        # device-pinned result-cache frames are tenant residency too —
        # the governor must see them or cache growth would hide from
        # admission control
        rc = getattr(self, "result_cache", None)
        if rc is not None:
            total += rc.device_bytes()
        # device-resident IVF artifacts: an index the advisor keeps hot
        # is tenant memory too (eviction via the same priority order —
        # dropping a table's snapshot invalidates its index caches)
        try:
            total += self.engine.executor.ann_device_bytes()
        except Exception:  # noqa: BLE001 - accounting must not fail DML
            pass
        return total

    def _enforce_memory(self, keep: str) -> None:
        """Tenant memory unit: evict other tables' snapshots (they re-
        materialize on next use) until under the quota; raise if the kept
        table alone exceeds it (the unit is simply too small)."""
        limit = self.unit.memory_limit
        if limit is None:
            return
        if self._resident_bytes() <= limit:
            return
        # advisor residency priorities order the eviction: the lowest-
        # priority tables lose their snapshots (and, via _invalidate,
        # their device batches) first; ties keep insertion order
        order = sorted(
            self.tables.items(),
            key=lambda kv: self.residency_priority.get(kv[0], 0.0),
        )
        for name, ti in order:
            if name == keep:
                continue
            t = self.catalog.get(name)
            if t is None or not t.data or ti.cached_data_version < 0:
                continue
            self.catalog[name] = Table(name, ti.schema, {
                f.name: np.zeros(0, f.dtype.storage_np)
                for f in ti.schema.fields
            })
            ti.cached_data_version = -1
            self._invalidate(name)
            if self._resident_bytes() <= limit:
                return
        if self._resident_bytes() > limit:
            raise SqlError(
                f"tenant {self.tenant_name}: memory unit exceeded "
                f"({self._resident_bytes()} > {limit} bytes)"
            )

    def _evict_cold_residency(self) -> None:
        """Degradation ladder rung 1 (after a device OOM): free the
        coldest device-resident state without touching durable data —
        cached device batches of low-priority tables (advisor residency
        priorities order the walk, like _enforce_memory) and half the
        decoded block cache. Everything re-materializes on next use."""
        # cached result frames first: the most re-creatable bytes on the
        # chip (one warm dispatch rebuilds any of them)
        rc = getattr(self, "result_cache", None)
        if rc is not None and rc.flush():
            self.metrics.add("result cache evictions: device oom")
        ex = self.engine.executor
        order = sorted(
            {k[0] for k in ex._batch_cache} | {k[0] for k in ex._assembled},
            key=lambda n: self.residency_priority.get(n, 0.0),
        )
        for name in order:
            ex.invalidate_table(name)
        bc = self.block_cache
        if bc.bytes_used > 0:
            cap = bc.capacity_bytes
            bc.set_capacity(bc.bytes_used // 2)
            bc.capacity_bytes = cap  # one-shot trim, budget unchanged
        self.metrics.add("residency evictions: device oom")

    _UID_MISS = object()

    def _block_priority(self, key) -> float:
        """Residency priority of a block-cache key ((sstable uid, block,
        column)); unknown uids rebuild the uid map once and then cache a
        negative answer so eviction stays O(1)."""
        try:
            uid = key[0]
        except Exception:
            return 0.0
        name = self._uid_tables.get(uid, self._UID_MISS)
        if name is self._UID_MISS:
            m = {}
            for tname, ti in self.tables.items():
                for ls_id, tablet_id in ti.all_partitions():
                    for rep in (
                            self.cluster.ls_groups.get(ls_id) or {}
                    ).values():
                        tab = rep.tablets.get(tablet_id)
                        if tab is None:
                            continue
                        for ss in getattr(tab, "deltas", ()):
                            m[ss.uid] = tname
                        if getattr(tab, "base", None) is not None:
                            m[tab.base.uid] = tname
            m.setdefault(uid, None)
            self._uid_tables = m
            name = m[uid]
        if name is None:
            return 0.0
        return float(self.residency_priority.get(name, 0.0))

    def kill_query(self, session_id: int, reason: str = "killed by user") -> None:
        """Interrupt a session's running statement cluster-wide (the
        ObGlobalInterruptManager call analog; KILL QUERY <session>)."""
        iid = self._active_stmts.get(session_id)
        if iid is None:
            raise SqlError(f"session {session_id} has no running statement")
        self.interrupts[0].interrupt(iid, reason)

    # ------------------------------------------------------------ session
    def metrics_text(self) -> str:
        """Prometheus text exposition of the whole engine (one scrape):
        every counter/gauge/wait-event/histogram in the tenant registry,
        plus the cache and audit-ring stats kept outside it."""
        m = self.metrics
        m.gauge_set("plan cache entries", len(self.plan_cache))
        m.gauge_set("sql audit records", len(self.audit.records()))
        m.gauge_set("active statements", len(self._active_stmts))
        # serving-timeline self-metering: ring occupancy/bytes/records +
        # the retained window's device-busy fraction
        self.timeline.meter(m)
        m.gauge_set("health alerts", len(self.sentinel.alerts()))
        return m.prometheus_text()

    def session(self, user: str = "root") -> "DbSession":
        return DbSession(self, user=user)


# reservation marker in _xa_prepared while a PREPARE is still logging:
# blocks duplicate xids atomically without presenting as decidable
_XA_PREPARING = object()


class _OpenTx:
    """Client-side state of an open transaction."""

    def __init__(self, db: Database, deadline: "_R.Deadline | None" = None):
        self.db = db
        # ob_trx_timeout deadline, fixed at BEGIN on the virtual clock:
        # every statement of the tx runs under min(its own query deadline,
        # this) — an expired tx surfaces TrxTimeout at the next checkpoint
        self.deadline = deadline
        # home the tx where leadership currently lives (location cache):
        # after a failover/demotion new txs follow the leaders instead of
        # dragging leadership back to a fixed node
        try:
            home = db.location.leader(min(db.cluster.ls_groups))
        except Exception:
            home = 0
        self.svc = db.cluster.services[home]
        self.ctx = self.svc.begin()
        self.touched_tables: set[str] = set()
        # tx-private catalog views (BEGIN snapshot + own staged rows),
        # activated per-statement through TxCatalog.tx_scope
        self.views: dict[str, Table] = {}

    def ensure_leader(self, ls_id: int) -> None:
        """Co-locate the LS leader with this tx's coordinating node (the
        analog of routing the statement to a server leading the
        participants), and wait until it is READY (replay caught up) —
        role transfer alone is not enough to serve writes."""
        from ..tx.txn import NotMaster

        rep = self.svc.replicas[ls_id]
        if rep.is_ready:
            return
        try:
            self.db.cluster.transfer_leader(ls_id, self.svc.node_id)
        except TimeoutError as e:
            # the drag failed (home node dead/partitioned, or no leader to
            # hand off yet): OB_NOT_MASTER — the statement retry layer
            # re-homes the tx after a location refresh
            raise NotMaster(f"ls {ls_id}: {e}", ls_id=ls_id) from e
        if not self.db.cluster.drive_until(lambda: rep.is_ready):
            raise NotMaster(f"ls {ls_id} leadership did not settle",
                            ls_id=ls_id)
        self.db.location.invalidate(ls_id)


class DbSession:
    """One client session: statement dispatch + transaction state."""

    def __init__(self, db: Database, user: str = "root"):
        self.db = db
        self.user = user
        self._tx: _OpenTx | None = None
        self.session_id = next(db._session_ids)
        self._last_stmt_type = ""
        self._stmt_cache_hit = False
        self._retry_ctrl = None
        self._stmt_adds: list = []
        # (fkey, params, kinds) from the statement fast path — also the
        # statement-summary digest source. Reset per statement in
        # _sql_inner: prefix-dispatched statements (SET/XA/CALL/...)
        # return before _dispatch clears it, and a stale value would
        # mis-digest them under the previous SELECT
        self._fast_reg = None
        # lazily-created statement-summary accumulator (workload.py)
        self._ws_acc = None
        # per-statement host-tax gap ledger (share/gap_ledger.py); also
        # published thread-locally so batcher/governor waits self-report
        self._gap = None
        self._last_digest = ""
        # device-OOM degradation ladder state (reset per statement in
        # _sql_inner): None | "chunk" | "host", plus the fired rungs
        self._degrade_mode = None
        self._ladder = []
        # text -> digest memo for the governor's admission estimate (a
        # serving session repeats few texts; re-tokenizing each repeat
        # just to look up its measured peak would tax the fast path)
        self._digest_memo: dict[str, str] = {}
        # session variables (SET <name> = <value>): full-link trace
        # collection flag, PX degree-of-parallelism routing, and the
        # statement/transaction deadlines in MICROSECONDS of virtual time
        # (the reference's ob_query_timeout / ob_trx_timeout units).
        # Defaults are wider than the reference's 10s/100s because test
        # drives legitimately burn tens of virtual seconds (commit waits
        # and elections cap at 30s each)
        self._vars: dict[str, int] = {
            "ob_enable_show_trace": 0,
            "ob_px_dop": 0,
            "ob_query_timeout": 100_000_000,
            "ob_trx_timeout": 500_000_000,
            # cross-session micro-batching (server/batcher.py), seeded
            # from the tenant config so ALTER SYSTEM moves the default
            # for new sessions while SET overrides per session
            "ob_batch_max_size": int(db.config["ob_batch_max_size"]),
            "ob_batch_max_wait_us": int(db.config["ob_batch_max_wait_us"]),
            # read-consistency routing (0 strong / 1 bounded_staleness /
            # 2 weak): non-strong SELECTs serve from follower replicas at
            # a GTS-checked snapshot within ob_max_read_stale_us
            "ob_read_consistency": self._CONSISTENCY_WORDS.get(
                str(db.config["ob_read_consistency"]), 0),
            "ob_max_read_stale_us": int(db.config["ob_max_read_stale_us"]),
            # device-resident result cache: per-session opt-out (a bench
            # A/B or a test that must observe real dispatches turns it
            # off without flipping the tenant-wide config)
            "ob_enable_result_cache": int(
                bool(db.config["ob_enable_result_cache"])),
        }
        # (snapshot, stale_us) of the last follower-served SELECT — the
        # staleness-contract tests and chaos bench read it to re-run the
        # same statement on the leader AS OF the identical snapshot
        self.last_follower_read: tuple[int, int] | None = None
        # trace_id of the last traced NON-meta statement — what SHOW TRACE
        # renders (meta statements: SHOW/SET themselves, so the flag and
        # the inspection don't overwrite the statement under diagnosis)
        self._last_trace_id = 0

    def close(self) -> None:
        """Session drop: roll back an open transaction and flush the
        statement-summary accumulator NOW instead of waiting for GC —
        the wire front ends call this on client disconnect so workload-
        repository digest counts reconcile promptly."""
        if self._tx is not None:
            try:
                self.sql("rollback")
            except Exception:
                self._tx = None
        acc = self._ws_acc
        if acc is not None:
            self._ws_acc = None
            try:
                acc.flush()
            except Exception:
                pass

    # ------------------------------------------------------------ public
    def sql(self, text: str) -> ResultSet:
        """Execute one statement, instrumented: trace span + ASH activity
        around execution, one sql_audit record at completion."""
        db = self.db
        t0 = _time.perf_counter()
        err, rs = "", None
        self._last_stmt_type = ""  # "": did not parse
        self._stmt_cache_hit = False  # set by any inner _select
        # host-tax gap ledger: one per statement, spanning the SAME t0 as
        # the audit elapsed_s. Published thread-locally so the batcher and
        # governor (which run their waits on this thread) self-report
        # hints without any API plumbing.
        led = None
        if db.host_tax.enabled:
            # one ledger object per session, re-armed per statement
            # (begin() fully resets) — no per-statement allocation
            led = self._gap
            if led is None:
                led = _GL.GapLedger()
            led.begin(t0)
            _GL.set_current(led)
        self._gap = led
        # statement deadline: min(ob_query_timeout from now, the open tx's
        # ob_trx_timeout deadline) on the bus virtual clock — one Deadline
        # object bounds the worker wait, PX admission, DAS routing retries,
        # commit waits and every engine checkpoint below
        clock = db._bus_clock
        deadline = _R.Deadline(
            clock=clock,
            at=clock() + self._vars["ob_query_timeout"] / 1e6,
            label="ob_query_timeout",
        )
        if self._tx is not None and self._tx.deadline is not None:
            deadline = _R.Deadline.earliest(deadline, self._tx.deadline)
        # tenant worker quota (ObThWorker queue analog): bound concurrent
        # statements; waiting beyond the queue timeout (or the statement
        # deadline, when that is nearer) fails the statement
        sem = db._worker_sem
        if sem is not None:
            wait_s = db.unit.queue_timeout_s
            bounded = deadline is not None and deadline.tighter_than(wait_s)
            if bounded:
                wait_s = max(deadline.remaining(), 0.0)
            if led is not None:
                led.cut("setup")  # deadline/quota bookkeeping since t0
            tq = _time.perf_counter()
            ok = sem.acquire(timeout=wait_s)
            waited = _time.perf_counter() - tq
            if led is not None:
                led.cut("admission queue")
            db.metrics.wait("tenant worker queue", waited)
            tl = db.timeline
            if tl.enabled:
                # per-tenant QoS ledger: admission wait (and, on a
                # timeout, the rejection) against the TenantUnit quota
                tl.record_admission(db.tenant_name, waited, ok)
            if not ok:
                db.metrics.add("worker queue timeouts")
                if bounded:
                    db.metrics.add("statement timeouts")
                    raise deadline._error()
                raise WorkerQueueTimeout(
                    f"tenant {db.tenant_name}: worker queue timeout "
                    f"({db.unit.max_workers} workers busy)"
                )
        # per-statement interrupt registration (KILL QUERY target)
        iid = ("stmt", db.tenant_name, self.session_id, next(db._stmt_seq))
        checker = db.interrupts[0].register(iid)
        db._active_stmts[self.session_id] = iid
        prev = _I.set_current(checker)
        # inlined _R.deadline_scope: this frame already owns a finally,
        # and the generator contextmanager is measurable per-statement
        prev_dl = _R.current_deadline()
        _R.set_current_deadline(deadline)
        if led is not None:
            # interrupt + deadline registration (and admission metrics/
            # timeline above): small but real, and the residual gate is
            # strict — name it instead of leaking it
            led.cut("setup")
        try:
            return self._sql_inner(text, t0)
        finally:
            if led is not None:
                _GL.set_current(None)
            _R.set_current_deadline(prev_dl)
            _I.set_current(prev)
            db._active_stmts.pop(self.session_id, None)
            db.interrupts[0].unregister(iid)
            if sem is not None:
                sem.release()

    def _sql_inner(self, text: str, t0) -> ResultSet:
        db = self.db
        err, rs = "", None
        # last_profile is per-run_ast; statements that never reach run_ast
        # (pure DDL, SHOW) must not inherit the previous statement's.
        # last_phases likewise: the host-tax carve reads it after the
        # engine window and must never see a previous statement's walls
        db.engine.last_profile = None
        db.engine.last_phases = {}
        # retry bookkeeping spans attempts but the statement keeps ONE
        # span tree, ASH activity and audit record — retries are an
        # internal redrive, not new statements. The controller is built
        # lazily by _run_with_retries on the FIRST failure: the serving
        # hot path never pays for bookkeeping it doesn't use.
        self._retry_ctrl = None
        # per-statement counter batch: the fast path appends its plan
        # cache hit bumps here so the whole statement flushes through
        # ONE metrics.bulk() below
        self._stmt_adds = []
        self._fast_reg = None
        # degradation-ladder state (device OOM): None -> "chunk" -> "host";
        # _ladder records the rungs fired, in order, for tests/diagnosis
        self._degrade_mode = None
        self._ladder = []
        with db.tracer.span("sql", session=self.session_id) as sp:
            with db.ash.activity(self.session_id, "EXECUTING", text,
                                 sp.trace_id):
                if self._gap is not None:
                    # tracer span + ASH activity registration glue
                    self._gap.cut("setup")
                pp = db.plan_profiler
                if pp is not None and pp.enabled:
                    # hand the statement digest to the engine's operator
                    # profiler (memoized text->digest: one dict lookup on
                    # warm statements) so sampling, EXPLAIN ANALYZE
                    # forcing and slow-query marks all key identically
                    pp.set_pending(self._digest_of(text))
                try:
                    rs = self._run_with_retries(text)
                except Exception as e:
                    err = f"{type(e).__name__}: {e}"
                    if isinstance(e, _R.StatementTimeout):
                        db.metrics.add("statement timeouts")
                    raise
                finally:
                    if pp is not None:
                        pp.clear_pending()
                    elapsed_s = _time.perf_counter() - t0
                    stype = self._last_stmt_type or "Unknown"
                    m = db.metrics
                    prof = db.engine.last_profile
                    if rs is not None \
                            and getattr(rs, "profile", None) is not None:
                        # batched fast path: the per-lane profile rides
                        # the ResultSet (engine.last_profile is shared
                        # across sessions and races under concurrency)
                        prof = rs.profile
                    bi = (getattr(rs, "batch_info", None)
                          if rs is not None else None)
                    led = self._gap
                    fr = self._fast_reg
                    digest = ""
                    ws = db.stmt_summary
                    if ws.enabled or led is not None:
                        digest = (fr[0] if fr is not None
                                  else P.digest_text(text))
                        # fronts annotate post-close wall (wire write)
                        # against this digest via host_tax.fold_extra
                        self._last_digest = digest
                    if ws.enabled:
                        # exactly-once digest fold per statement — here in
                        # the completion finally, never in the except arm
                        # or the flight recorder, so a statement that both
                        # fails AND trips the slow-query watermark counts
                        # its error once. Fast-path statements reuse the
                        # already-tokenized key in _fast_reg for free, and
                        # the fold buffers into this session's own
                        # accumulator (readers flush before reading) so
                        # a completing batch cohort takes no shared lock.
                        acc = self._ws_acc
                        if acc is None:
                            acc = self._ws_acc = ws.session_acc()
                        acc.fold(
                            digest,
                            stype, elapsed_s, err,
                            self._retry_ctrl.retry_cnt
                            if self._retry_ctrl else 0,
                            rs, bi is not None, prof,
                        )
                    snap = None
                    if led is not None:
                        # the return path + digest + summary fold are host
                        # wall too: cut everything since the engine window
                        # closed, then freeze e2e/residual/chip-idle.
                        # Deferred folds must NOT hold the live ledger —
                        # begin() re-arms it in place for this session's
                        # next statement — so they read a frozen snapshot
                        led.cut("completion fold")
                        led.close()
                        snap = _GL.LedgerSnapshot(led)
                    retry_cnt = (self._retry_ctrl.retry_cnt
                                 if self._retry_ctrl else 0)
                    retry_info = (self._retry_ctrl.retry_info
                                  if self._retry_ctrl else "")
                    sid = self.session_id
                    trace_id = sp.trace_id
                    stype2 = self._last_stmt_type
                    depth = len(db._active_stmts)
                    stmt_adds = self._stmt_adds

                    def _complete():
                        # statement accounting, exactly once — inline on
                        # the serving thread, or behind the wire write on
                        # the completion drain (ob_enable_completion_drain)
                        if snap is not None:
                            db.host_tax.fold(digest, snap)
                        # hot-path diet: when metrics/audit are disabled,
                        # skip even the counter lookups and kwargs
                        # construction — the serving path pays zero for
                        # observability it isn't using
                        if m.enabled:
                            adds = stmt_adds
                            adds.append(("sql statements", 1))
                            if stype in ("Select", "SetSelect"):
                                adds.append(("sql select count", 1))
                            elif stype in ("Insert", "Update", "Delete"):
                                adds.append(("sql dml count", 1))
                            if err:
                                adds.append(("sql fail count", 1))
                            observes = [("sql response time", elapsed_s)]
                            waits = ()
                            if snap is not None:
                                # per-phase wait events: sysstat/
                                # system_event rows AND prometheus
                                # summaries for free
                                adds.append(("host tax statements", 1))
                                observes.append(("host chip idle pct",
                                                 snap.chip_idle_pct))
                                waits = [("host tax: " + k, v)
                                         for k, v in snap.phases.items()]
                                if snap.unattributed_s > 0.0:
                                    waits.append(
                                        ("host tax: unattributed",
                                         snap.unattributed_s))
                            m.bulk(adds=adds, observes=tuple(observes),
                                   waits=tuple(waits))
                        tl = db.timeline
                        if tl.enabled:
                            # timeline completion feed (exactly once per
                            # statement, beside the summary fold): host
                            # wall seconds + tenant admitted count +
                            # in-flight depth sample for the queue
                            # histograms
                            tl.record_stmt(db.tenant_name, elapsed_s,
                                           bool(err), depth)
                        if db.audit.enabled:
                            p = prof
                            db.audit.record(
                                session_id=sid,
                                trace_id=trace_id,
                                sql=text,
                                stmt_type=stype2,
                                elapsed_s=elapsed_s,
                                rows=rs.nrows if rs is not None else 0,
                                affected=(rs.affected
                                          if rs is not None else 0),
                                plan_cache_hit=(rs.plan_cache_hit
                                                if rs is not None
                                                else False),
                                error=err,
                                compile_s=p.compile_s if p else 0.0,
                                device_bytes=p.device_bytes if p else 0,
                                transfer_bytes=(p.transfer_bytes
                                                if p else 0),
                                peak_bytes=p.peak_bytes if p else 0,
                                retry_cnt=retry_cnt,
                                retry_info=retry_info,
                                fastparse_us=(int(p.fastparse_s * 1e6)
                                              if p else 0),
                                bind_us=int(p.bind_s * 1e6) if p else 0,
                                dispatch_us=(int(p.dispatch_s * 1e6)
                                             if p else 0),
                                fetch_us=int(p.fetch_s * 1e6) if p else 0,
                                is_fast_path=(bool(p.fast_path_hit)
                                              if p else False),
                                is_batched=bi is not None,
                                batch_id=bi[0] if bi is not None else 0,
                                batch_wait_us=(bi[2]
                                               if bi is not None else 0),
                                chip_idle_us=int(
                                    max(0.0, snap.e2e_s - snap.device_s)
                                    * 1e6) if snap is not None else 0,
                                unattributed_us=int(
                                    snap.unattributed_s * 1e6)
                                if snap is not None else 0,
                            )

                    cd = db.completion
                    if (cd is not None
                            and db.config["ob_enable_completion_drain"]):
                        cd.submit(_complete)
                    else:
                        _complete()
                    if stype not in ("Show", "SetVar", ""):
                        if self._vars.get("ob_enable_show_trace"):
                            self._last_trace_id = sp.trace_id
                        self._maybe_flight_record(
                            text, sp, elapsed_s, rs, err, prof)
                    wr = db.workload
                    if wr.interval_s > 0:
                        wr.maybe_auto(db)
        return rs

    def _stmt_retryable(self) -> bool:
        """Whole-statement redrive is safe only when nothing of the failed
        attempt outlives it: reads always (the snapshot re-resolves);
        DML only in autocommit, where _dml aborted the auto-tx with the
        failure — a DML inside an explicit transaction keeps its partial
        stages and must surface the error to the client instead."""
        st = self._last_stmt_type
        if st in ("Select", "SetSelect"):
            return True
        if st in ("Insert", "Update", "Delete"):
            return self._tx is None
        return False

    def _run_with_retries(self, text: str):
        """ObQueryRetryCtrl's loop: classify each failure, re-resolve
        locations/routing, back off on the bus virtual clock (driving the
        cluster so elections settle during the wait), and redrive until
        success, a non-retryable error, or the statement deadline — which
        surfaces as a timeout chaining the last transient, never as a raw
        NotMaster/InjectedError.

        The RetryController is built on the first failure only (stored on
        ``self._retry_ctrl`` so the audit record can read retry_cnt /
        retry_info after the loop returns)."""
        db = self.db
        schema_v = db.schema_service.version
        ctrl = None
        reserve_bytes = self._reserve_estimate(text)
        while True:
            res = None
            ok = False
            try:
                if reserve_bytes > 0:
                    # admission-time device-memory reservation, held for
                    # the whole attempt (re-taken per attempt so post-OOM
                    # attempts charge the SHRUNK pool)
                    res = self._reserve_device_memory(reserve_bytes)
                out = self._dispatch(text)
                ok = True
                return out
            except Exception as e:
                if ctrl is None:
                    ctrl = _R.RetryController(deadline=_R.current_deadline())
                    self._retry_ctrl = ctrl
                policy = ctrl.decide(e, stmt_retryable=self._stmt_retryable())
                if policy is None:
                    # a DDL racing this statement invalidated any cached
                    # plan it compiled against: reclassify once per version
                    # move as OB_SCHEMA_EAGAIN and redrive fresh
                    cur_v = db.schema_service.version
                    if (cur_v != schema_v and self._stmt_retryable()
                            and not isinstance(e, _R.StatementTimeout)):
                        schema_v = cur_v
                        policy = ctrl.decide(
                            _R.SchemaVersionMismatch(
                                f"schema version moved under the statement "
                                f"({type(e).__name__}: {e})"),
                            stmt_retryable=True,
                        )
                    if policy is None:
                        raise
                d = ctrl.deadline
                if d is not None and d.expired:
                    raise ctrl.timeout_error(e) from e
                wait = ctrl.record(policy, e)
                m = db.metrics
                m.add("statement retries")
                m.add(f"statement retries: {policy.reason}")
                if policy.reason == "device oom":
                    # the three-rung degradation ladder: rung N is chosen
                    # by how many device OOMs THIS statement has already
                    # absorbed. Each rung strictly weakens the memory
                    # demand, so the sequence terminates: host execution
                    # (rung 3) cannot device-OOM at all.
                    rung = ctrl._per_policy.get("device oom", 0)
                    m.add("device OOM retries")
                    if rung <= 1:
                        # rung 1: evict cold residency + shrink the
                        # reservation pool, retry the same plan
                        db._evict_cold_residency()
                        db.governor.note_oom()
                        self._ladder.append("evict")
                    elif rung == 2:
                        # rung 2: re-plan through the chunked executor,
                        # chunk size derived from the remaining budget
                        self._degrade_mode = "chunk"
                        m.add("stmt degraded chunked")
                        self._ladder.append("chunked")
                    else:
                        # rung 3: host fallback, bit-identical
                        self._degrade_mode = "host"
                        m.add("stmt degraded host")
                        self._ladder.append("host")
                if policy.flush_plan_cache:
                    db.plan_cache.flush()
                if policy.refresh_location:
                    ls_id = getattr(e, "ls_id", None)
                    if ls_id is not None:
                        # NotMaster names the LS whose cached leader went
                        # stale: invalidate exactly that entry — dropping
                        # the whole cache forces every OTHER ls through a
                        # resolver round trip for one node's election
                        m.add("location targeted invalidations")
                        db.location.invalidate(ls_id)
                    else:
                        db.location.clear()
                if wait > 0:
                    tb = _time.perf_counter()
                    with m.waiting("statement retry backoff"):
                        db.cluster.settle(wait)
                    led = _GL.current()
                    if led is not None:
                        led.add("retry backoff",
                                _time.perf_counter() - tb)
                if d is not None and d.expired:
                    raise ctrl.timeout_error(e) from e
            finally:
                # the ledger must balance: release THIS attempt's grant on
                # every exit — success, retry, or surfaced error. A
                # successful attempt's release may ride the completion
                # drain (the client isn't waiting on ledger arithmetic);
                # failed attempts release inline so the next attempt/rung
                # charges an honest pool.
                if res is not None:
                    cd = db.completion
                    if (ok and cd is not None
                            and db.config["ob_enable_completion_drain"]):
                        cd.submit(res.release)
                    else:
                        res.release()

    def _maybe_flight_record(self, text, sp, elapsed_s, rs, err,
                             prof) -> None:
        """Slow-query flight recorder: when a statement crosses the
        trace_log_slow_query_watermark, freeze the evidence (span tree,
        plan text, audit-shaped record, metrics delta, active config)
        into the bounded bundle ring — tools/obdiag_dump.py exports it."""
        db = self.db
        if not db.flight.should_record(elapsed_s):
            return
        # arm the stack sampler: THIS statement is already over, but slow
        # statements recur — the next occurrence gets sampled stacks into
        # its bundle. Config-armed mode (enable_stack_sampler) keeps it
        # running regardless.
        auto = db.config["stack_sampler_auto_arm"]
        if auto > 0:
            db.stack_sampler.arm(auto)
        spans = [
            {
                "depth": depth,
                "name": s.name,
                "node": s.tags.get("node", ""),
                "elapsed_us": int(s.elapsed * 1e6),
                "tags": {k: repr(v) for k, v in s.tags.items()},
            }
            for depth, s in db.tracer.trace_tree(sp.trace_id)
        ]
        digest = (self._fast_reg[0] if self._fast_reg is not None
                  else P.digest_text(text))
        pp = db.plan_profiler
        op_profile: list = []
        if pp is not None and pp.enabled:
            # arm the operator profiler: the NEXT occurrence of this slow
            # digest runs profiled, so a recurring slow statement's later
            # bundles carry per-operator evidence — and whatever profile
            # the store already holds rides THIS bundle now. UNLESS this
            # very run already carried a profile: a profiled run is
            # slower (fences), so re-arming on its own slowness would
            # lock a watermark-straddling digest into profiling forever
            opp = db.engine.last_op_profile
            if opp is None or opp.get("digest") != digest:
                pp.mark_slow(digest)
            op_profile = pp.store.digest_profile(digest)
        bundle = {
            "trace_id": sp.trace_id,
            "session_id": self.session_id,
            "sql": text,
            # same digest the statement summary folded under — a bundle
            # joins its aggregate without re-normalizing
            "digest": digest,
            # per-operator calibration records for this digest (est vs
            # actual rows, device_us) from engine/plan_profile.py
            "op_profile": op_profile,
            "stmt_type": self._last_stmt_type,
            "elapsed_s": elapsed_s,
            "rows": rs.nrows if rs is not None else 0,
            "error": err,
            "profile": prof.as_dict() if prof is not None else {},
            "plan": repr(db.engine.last_plan),
            "spans": spans,
            "config": {
                n: v for n, v, _p in db.config.snapshot()
            },
            # host-tax ledger: where THIS statement's wall went, phase by
            # phase, residual named — plus whatever collapsed stacks the
            # sampler holds (armed by a previous slow statement or config)
            "host_tax": (self._gap.to_dict()
                         if self._gap is not None and self._gap.closed
                         else {}),
            "stacks": db.stack_sampler.snapshot(),
        }
        db.flight.record(bundle, counters=db.metrics.counters_snapshot())
        db.metrics.add("flight recorder bundles")

    @staticmethod
    def _referenced_tables(node) -> set:
        """Every base-table name the statement reads: TableRef names
        anywhere in the AST (FROM lists, joins, subqueries inside
        predicates, INSERT..SELECT sources) MINUS names declared as CTEs
        — a CTE reference is statement-local, not a catalog object."""
        import dataclasses

        from ..engine.recursive import _table_refs

        refs = _table_refs(node)

        def cte_names(n, out):
            for name, _b in getattr(n, "ctes", ()) or ():
                out.add(name)
            if dataclasses.is_dataclass(n) and not isinstance(n, type):
                for f in dataclasses.fields(n):
                    cte_names(getattr(n, f.name), out)
            elif isinstance(n, (tuple, list)):
                for x in n:
                    cte_names(x, out)
            return out

        return refs - cte_names(node, set())

    def _check_privs(self, stmt) -> None:
        """Resolve-time privilege enforcement (the reference checks in
        sql/privilege_check before optimization; same point here: after
        parse, before any plan executes)."""
        from ..share.privilege import AccessDenied

        if self.user == "root":
            return  # superuser: skip the AST walk on the hot path
        pm = self.db.privileges
        try:
            if isinstance(stmt, (A.Select, A.SetSelect)):
                pm.check(self.user, "select", self._referenced_tables(stmt))
            elif isinstance(stmt, (A.Insert, A.Update, A.Delete)):
                priv = type(stmt).__name__.lower()
                target = stmt.table
                pm.check(self.user, priv, {target})
                others = self._referenced_tables(stmt) - {target}
                if others:
                    pm.check(self.user, "select", others)
            elif isinstance(stmt, (A.CreateTable, A.CreateExternalTable)):
                pm.check(self.user, "create", {stmt.name})
                if isinstance(stmt, A.CreateExternalTable):
                    # secure_file_priv gate: a bare 'create' grant must
                    # not turn SELECT into arbitrary-host-file read (a
                    # CSV loader would happily ingest /etc/passwd).
                    self._check_external_location(stmt.location)
            elif isinstance(stmt, A.LockTable):
                # shared holds need read rights, exclusive holds write
                # rights — otherwise a zero-grant user can block writers.
                pm.check(self.user,
                         "update" if stmt.exclusive else "select",
                         {stmt.name})
            elif isinstance(stmt, (A.CreateMaterializedView, A.CreateView)):
                pm.check(self.user, "create", {stmt.name})
                pm.check(self.user, "select", self._referenced_tables(
                    P.parse(stmt.query_sql)))
            elif isinstance(stmt, A.DropView):
                pm.check(self.user, "drop", {stmt.name})
            elif isinstance(stmt, A.CreateTrigger):
                # trigger bodies run with the firing statement's rights;
                # creating one therefore needs write-shaping power over
                # the subject table
                pm.check(self.user, "create", {stmt.table})
            elif isinstance(stmt, A.DropTrigger):
                pm.check(self.user, "drop", {stmt.name})
            elif isinstance(stmt, A.RefreshMaterializedView):
                pm.check(self.user, "create", {stmt.name})
                spec = self.db._mview_specs.get(stmt.name)
                if spec is not None:
                    pm.check(self.user, "select",
                             self._referenced_tables(P.parse(spec)))
            elif isinstance(stmt, A.DropMaterializedView):
                pm.check(self.user, "drop", {stmt.name})
            elif isinstance(stmt, A.DropTable):
                pm.check(self.user, "drop", {stmt.name})
            elif isinstance(stmt, (A.CreateIndex, A.DropIndex,
                                   A.CreateVectorIndex, A.DropVectorIndex)):
                pm.check(self.user, "index", {stmt.table})
            elif isinstance(stmt, (A.AlterSystemSet, A.RunLayoutAdvisor,
                                   A.KillQuery)):
                if self.user != "root":
                    raise AccessDenied(
                        f"'{self.user}' lacks SUPER", 1227)
        except AccessDenied as e:
            raise SqlError(str(e), code=e.code) from None

    def _check_external_location(self, location: str) -> None:
        """Non-root external-table locations must resolve inside the
        secure_file_priv directory (empty = root-only), checked on the
        os.path.realpath so ../ and symlink escapes don't bypass it."""
        import os

        allowed = str(self.db.config.get("secure_file_priv") or "")
        if not allowed:
            raise SqlError(
                "external tables are restricted to root "
                "(secure_file_priv is unset)", code=1227)
        real = os.path.realpath(location)
        base = os.path.realpath(allowed)
        if os.path.commonpath([real, base]) != base:
            raise SqlError(
                f"location {location!r} is outside secure_file_priv",
                code=1227)

    def _dcl(self, stmt) -> ResultSet:
        from ..share.privilege import AccessDenied

        if self.user != "root":
            raise SqlError(
                f"'{self.user}' may not administer users/grants", code=1227
            )
        pm = self.db.privileges
        try:
            if isinstance(stmt, A.CreateUser):
                pm.create_user(stmt.name, stmt.password)
            elif isinstance(stmt, A.DropUser):
                pm.drop_user(stmt.name)
            elif isinstance(stmt, A.Grant):
                pm.grant(stmt.user, stmt.obj, stmt.privs)
            elif isinstance(stmt, A.Revoke):
                pm.revoke(stmt.user, stmt.obj, stmt.privs)
        except AccessDenied as e:
            raise SqlError(str(e), code=e.code) from None
        self.db._save_node_meta()  # grants survive restart like schema
        return ResultSet((), {})

    def _dispatch(self, text: str) -> ResultSet:
        low = text.lstrip().lower()
        if low.startswith("create procedure"):
            self._last_stmt_type = "CreateProcedure"
            return self._create_procedure(text)
        if low.startswith("drop procedure"):
            self._last_stmt_type = "DropProcedure"
            return self._drop_procedure(text)
        if low.startswith("call ") or low.startswith("call("):
            self._last_stmt_type = "Call"
            return self._call_procedure(text)
        if low.startswith("xa "):
            self._last_stmt_type = "Xa"
            return self._xa(text)
        if low.startswith("set ") and not low.startswith("set transaction"):
            self._last_stmt_type = "SetVar"
            return self._set_session_var(text)
        if low.startswith("create sequence") or low.startswith("drop sequence"):
            self._last_stmt_type = "Sequence"
            return self._sequence_ddl(text)
        if low.startswith("snapshot workload"):
            # workload repository capture (server/workload.py): freeze the
            # current summary/access/census/sysstat state into the bounded
            # snapshot ring; tools/awr_report.py diffs two of them
            self._last_stmt_type = "SnapshotWorkload"
            snap = self.db.workload.take(self.db)
            return ResultSet(
                ("snap_id", "ts"),
                {"snap_id": [snap["snap_id"]], "ts": [float(snap["ts"])]},
            )
        if low.split(None, 1)[:1] == ["explain"]:
            self._last_stmt_type = "Explain"
            return self._explain(text.lstrip()[len("explain"):].lstrip())
        # statement fast path: a warm SELECT whose kind-marked text key is
        # registered skips parse/resolve/rewrite/plan entirely — one
        # tokenize pass, re-bind the literals, dispatch the cached
        # executable. Any rejection falls through to the full path (and
        # leaves self._fast_reg set so the full path registers the text).
        self._fast_reg = None
        if low.startswith("select"):
            rs = self._fast_select(text)
            if rs is not None:
                return rs
        tp = _time.perf_counter()
        stmt = P.parse_statement(text)
        self.db.metrics.observe("sql parse", _time.perf_counter() - tp)
        led = self._gap
        if led is not None:
            # cut, not a tp-anchored add: covers the fast-tier fallthrough
            # glue since the miss cut (or dispatch entry) too
            led.cut("parse bind")
        self._last_stmt_type = type(stmt).__name__
        # privileges first: a DENIED statement must not burn sequence
        # values or write node meta
        self._check_privs(stmt)
        stmt = self._bind_sequences(stmt)
        if self._fast_reg is not None:
            # the plain plan-cache key is the fast key with kind markers
            # collapsed (the tokenizer never emits a bare '?')
            norm_key = self._fast_reg[0].replace("?n", "?").replace("?s", "?")
        else:
            norm_key = P.normalize_for_cache(text)[0]
        if led is None:
            return self._dispatch_stmt(stmt, norm_key,
                                       fast_reg=self._fast_reg)
        # full-path engine window: whatever the engine measured
        # (plan/compile/bind/dispatch/fetch) carves the window wall; the
        # rest is the named measured remainder "engine host"
        led.window_start()
        try:
            return self._dispatch_stmt(stmt, norm_key,
                                       fast_reg=self._fast_reg)
        finally:
            led.window_end_carved(self.db.engine.last_phases, "engine host")

    def _fast_select(self, text: str) -> "ResultSet | None":
        """Server half of the statement fast path. Eligibility mirrors the
        plain single-chip _select route: autocommit (no open tx), no PX
        DOP, and — via registration-side guards — no virtual tables, views
        or index routing. Privileges re-check against the registered scan
        tables on EVERY hit (a REVOKE between repeats must bite), and the
        per-table catalog refresh runs as usual (it no-ops per table while
        data_version is unchanged, which is what makes the path cheap).
        Returns None to fall through to the full parse path."""
        db = self.db
        if self._tx is not None or self._vars.get("ob_px_dop", 0) > 0:
            return None
        if self._degrade_mode is not None:
            # a device OOM put this statement on the degradation ladder:
            # the cached fast plan is exactly what just OOMed — force the
            # full parse path so _select can re-plan chunked/host
            return None
        if self._vars.get("ob_read_consistency", 0) != 0:
            # the fast tier replays against the shared committed catalog
            # (leader state); non-strong sessions route through the
            # follower view path in _select instead
            return None
        t0 = _time.perf_counter()
        led = self._gap

        def miss():
            # the fast tier's wall is host tax even when it MISSES — the
            # tokenize/peek attempt preceded the full parse path
            if led is not None:
                led.cut("fast lookup")
            return None

        try:
            fkey, params, kinds = P.fast_normalize(text)
        except Exception:
            return miss()  # tokenizer rejects: the full parser owns the error
        if "nextval" in fkey or "currval" in fkey:
            # sequence draws are side-effecting: _bind_sequences rewrites
            # them into fresh literals pre-resolution, which a text-keyed
            # replay would freeze. Never serve OR register these.
            return miss()
        self._fast_reg = (fkey, params, kinds)
        fe = db.plan_cache.fast_peek(fkey)
        if fe is None:
            db.plan_cache.note_fast_miss()
            return miss()
        if self.user != "root":
            from ..share.privilege import AccessDenied

            try:
                db.privileges.check(self.user, "select", set(fe.tables))
            except AccessDenied as e:
                raise SqlError(str(e), code=e.code) from None
        db.refresh_catalog(fe.tables, tx=None)
        hit = db.engine.fast_lookup(fkey, params, fe=fe,
                                    defer_adds=self._stmt_adds)
        if hit is None:
            return miss()
        # set BEFORE execute: the audit record and the retry controller's
        # retryability decision both read it if dispatch raises
        self._last_stmt_type = fe.stmt_type
        fastparse_s = _time.perf_counter() - t0
        if led is not None:
            # tokenize + peek + priv + catalog refresh + lookup: the fast
            # tier's whole host cost, as one contiguous cut from the
            # dispatch-entry cursor
            led.cut("fast lookup")
        # device-resident result cache: probed AFTER the privilege check
        # (a REVOKE between repeats must bite a cached hit) and the
        # catalog refresh (the watermark key must see fresh committed
        # data versions). A hit serves the statement with ZERO device
        # dispatches; a miss threads the key down so the solo execute
        # admits the fresh narrowed frame.
        rc_key = (db.engine.result_cache_key(hit)
                  if self._vars.get("ob_enable_result_cache", 1) else None)
        if rc_key is not None and db.plan_profiler is not None \
                and db.plan_profiler.enabled \
                and db.plan_profiler.wants_force(fkey):
            # a pending forced operator profile (EXPLAIN ANALYZE, slow
            # mark) needs a real execution — neither serve nor admit
            rc_key = None
        if rc_key is not None:
            rs = db.engine.result_cache_probe(hit, rc_key, fastparse_s)
            if rs is not None:
                if led is not None:
                    led.cut("result cache")
                self._stmt_cache_hit = True
                return rs
        # cross-session micro-batching: concurrent hits on the SAME entry
        # fold into one batched device dispatch. Admission honors the
        # tenant unit — a batch wider than max_workers could never form
        # (each lane holds a worker permit while it waits). None from the
        # batcher = graceful degradation to the solo fast path below.
        bmax = self._vars.get("ob_batch_max_size", 1)
        if db.unit.max_workers is not None:
            bmax = min(bmax, db.unit.max_workers)
        if bmax > 1 and db.batcher.enabled:
            # weighted tenant admission: hold one running permit for the
            # whole gated execution — dispatch order alone cannot shield
            # a quiet tenant from a flooding one when the contention is
            # CPU time across session threads
            db.batcher.admit()
            try:
                # host-tax window over the gated execution: the batcher
                # self-reports hints (window wait; dispatch on the leader
                # only — the cohort's device busy is counted ONCE) from
                # this thread via gap_ledger.current()
                if led is not None:
                    led.window_start()
                rs = db.batcher.execute(
                    hit, bmax, self._vars.get("ob_batch_max_wait_us", 0))
                if rs is not None:
                    if led is not None:
                        # batched lane: hints only; batcher glue stays in
                        # the unattributed residual (no engine ran here)
                        led.window_end()
                    if db.config["enable_query_profile"]:
                        rs.profile = QueryProfile(
                            compile_hit=True,
                            d2h_bytes=rs.batch_info[4],
                            fastparse_s=fastparse_s,
                            dispatch_s=rs.batch_info[3],
                            fast_path_hit=True,
                        )
                    self._stmt_cache_hit = True
                    return rs
                # None = degrade to the solo fast path (idle gate,
                # bypass, follower timeout, dispatch error, shutdown).
                # The batcher left ONE dispatch-gate busy token held for
                # this solo run; solo_done hands it to the next queued
                # cohort — the release is what keeps the
                # continuous-batching queue draining.
                try:
                    rs = db.engine.fast_execute(
                        hit, fastparse_s=fastparse_s, rc_key=rc_key)
                finally:
                    db.batcher.solo_done()
                    if led is not None:
                        led.window_end_carved(
                            db.engine.last_phases, "engine host")
                self._stmt_cache_hit = True
                return rs
            finally:
                db.batcher.admit_done()
        if led is not None:
            led.window_start()
        try:
            rs = db.engine.fast_execute(hit, fastparse_s=fastparse_s,
                                        rc_key=rc_key)
        finally:
            if led is not None:
                led.window_end_carved(db.engine.last_phases, "engine host")
        self._stmt_cache_hit = True
        return rs

    def _sequence_ddl(self, text: str) -> ResultSet:
        from ..share.privilege import AccessDenied

        if self.user != "root":
            try:
                self.db.privileges.check(self.user, "create", {"*"})
            except AccessDenied as e:
                raise SqlError(str(e), code=e.code) from None
        toks = text.replace(";", " ").split()
        if len(toks) < 3:
            raise SqlError("sequence DDL needs a name")
        name = toks[2].lower()
        if toks[0].lower() == "drop":
            self.db.drop_sequence(name)
            return ResultSet((), {})
        start, inc = 1, 1
        low = [t.lower() for t in toks]

        def clause_value(kw, filler):
            # scan AFTER the name token so a sequence named 'start'
            # cannot shadow its own clause; malformed values surface as
            # SqlError, not IndexError
            try:
                i = low.index(kw, 3)
            except ValueError:
                return None
            j = i + 2 if i + 1 < len(low) and low[i + 1] == filler else i + 1
            if j >= len(toks):
                raise SqlError(f"{kw.upper()} needs a value")
            try:
                return int(toks[j])
            except ValueError:
                raise SqlError(
                    f"bad {kw.upper()} value {toks[j]!r}") from None

        v = clause_value("start", "with")
        if v is not None:
            start = v
        v = clause_value("increment", "by")
        if v is not None:
            inc = v
        if inc == 0:
            raise SqlError("INCREMENT BY must be nonzero")
        self.db.create_sequence(name, start, inc)
        return ResultSet((), {})

    def _bind_sequences(self, stmt):
        """Replace nextval('s')/currval('s') calls with literal values
        BEFORE resolution (side-effecting functions cannot live in a
        traced program; each textual occurrence draws once per
        statement, the reference's per-statement sequence semantics)."""
        import dataclasses

        if not self.db._sequences:
            return stmt

        def rw(node):
            if isinstance(node, A.FuncCall) and node.name in (
                "nextval", "currval"
            ):
                if len(node.args) != 1 or not isinstance(
                    node.args[0], A.StringLit
                ):
                    raise SqlError(f"{node.name}('sequence_name')")
                sname = node.args[0].value.lower()
                if node.name == "nextval":
                    v = self.db.sequence_next(sname)
                else:
                    sq = self.db._sequences.get(sname)
                    if sq is None:
                        raise SqlError(f"no sequence {sname}")
                    if "last" not in sq:
                        raise SqlError(
                            f"currval of {sname} before nextval in this "
                            "server lifetime"
                        )
                    v = sq["last"]
                return A.NumberLit(str(v))
            if dataclasses.is_dataclass(node) and not isinstance(node, type):
                ch = {}
                for f in dataclasses.fields(node):
                    cur = getattr(node, f.name)
                    new = rw(cur)
                    if new is not cur:
                        ch[f.name] = new
                return dataclasses.replace(node, **ch) if ch else node
            if isinstance(node, tuple):
                items = tuple(rw(x) for x in node)
                if any(a is not b for a, b in zip(items, node)):
                    return items
                return node
            return node

        return rw(stmt)

    def _dispatch_stmt(self, stmt, norm_key: str, fast_reg=None) -> ResultSet:
        if isinstance(stmt, (A.CreateUser, A.DropUser, A.Grant, A.Revoke)):
            return self._dcl(stmt)
        if isinstance(stmt, (A.Select, A.SetSelect)):
            return self._select(stmt, norm_key, fast_reg=fast_reg)
        if isinstance(stmt, A.CreateTable):
            self.db.create_table(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.DropTable):
            self.db.drop_table(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.CreateIndex):
            self.db.create_index(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.DropIndex):
            self.db.drop_index(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.CreateExternalTable):
            self.db.create_external_table(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.CreateView):
            self.db.create_view(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.CreateTrigger):
            self.db.create_trigger(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.DropTrigger):
            self.db.drop_trigger(stmt.name)
            return ResultSet((), {})
        if isinstance(stmt, A.DropView):
            self.db.drop_view(stmt.name)
            return ResultSet((), {})
        if isinstance(stmt, A.CreateMaterializedView):
            self.db.create_mview(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.DropMaterializedView):
            self.db.drop_mview(stmt.name)
            return ResultSet((), {})
        if isinstance(stmt, A.RefreshMaterializedView):
            self.db.refresh_mview(stmt.name)
            return ResultSet((), {})
        if isinstance(stmt, A.CreateVectorIndex):
            self.db.create_vector_index(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.DropVectorIndex):
            self.db.drop_vector_index(stmt)
            return ResultSet((), {})
        if isinstance(stmt, A.Begin):
            if self._tx is not None:
                raise SqlError("transaction already open")
            self._tx = _OpenTx(self.db, deadline=self._new_trx_deadline())
            return ResultSet((), {})
        if isinstance(stmt, A.Commit):
            self._end_tx(commit=True)
            return ResultSet((), {})
        if isinstance(stmt, A.Rollback):
            self._end_tx(commit=False)
            return ResultSet((), {})
        if isinstance(stmt, A.AlterSystemSet):
            from ..share.config import ConfigError

            try:
                self.db.config.set(stmt.name, stmt.value)
            except ConfigError as e:
                raise SqlError(str(e)) from None
            if self.db.data_dir is not None:
                self.db._save_node_meta()  # config survives restart
            return ResultSet((), {})
        if isinstance(stmt, A.RunLayoutAdvisor):
            recs = self.db.layout_advisor.run()
            return ResultSet(
                ("action", "table_name", "column_name", "detail",
                 "benefit", "cost_bytes", "status"),
                {
                    "action": [r.action for r in recs],
                    "table_name": [r.table for r in recs],
                    "column_name": [r.column for r in recs],
                    "detail": [r.detail for r in recs],
                    "benefit": [float(r.benefit) for r in recs],
                    "cost_bytes": [int(r.cost_bytes) for r in recs],
                    "status": [r.status for r in recs],
                },
            )
        if isinstance(stmt, A.Show):
            return self._show(stmt)
        if isinstance(stmt, A.LockTable):
            return self._lock_table(stmt)
        if isinstance(stmt, A.KillQuery):
            self.db.kill_query(stmt.session_id)
            return ResultSet((), {})
        if isinstance(stmt, A.Insert):
            return self._dml(lambda tx: self._insert(stmt, tx))
        if isinstance(stmt, A.Update):
            return self._dml(lambda tx: self._update(stmt, tx))
        if isinstance(stmt, A.Delete):
            return self._dml(lambda tx: self._delete(stmt, tx))
        raise SqlError(f"unsupported statement {type(stmt).__name__}")

    # ------------------------------------------------------------- explain
    def _explain(self, text: str) -> ResultSet:
        """EXPLAIN <select>: the routed plan with physical annotations
        (never compiles — all host-side planning state). Privileges
        apply exactly like the SELECT itself (a plan leaks table/column
        names and estimates); inside an open tx the plan reflects the
        tx's OWN view of the data, like the statement would.

        EXPLAIN ANALYZE <select> additionally EXECUTES the statement
        through the normal dispatch path and appends the measured phase
        breakdown (parse/plan/compile/execute) and actual row count —
        the per-plan analog of GV$SQL_PLAN_MONITOR's timing columns."""
        from ..sql.explain import explain_plan

        head = text.split(None, 1)
        analyze = bool(head) and head[0].lower() == "analyze"
        if analyze:
            text = text[len(head[0]):].lstrip()
            if not text:
                raise SqlError("EXPLAIN ANALYZE needs a statement")
        tp = _time.perf_counter()
        ast = P.parse(text)
        parse_s = _time.perf_counter() - tp
        self._check_privs(ast)
        names = self.db.expand_views(_tables_in_ast(ast))
        any_vt = self.db.refresh_virtual(names)
        self.db.refresh_catalog(names, tx=self._tx)
        in_tx = self._tx is not None and self._tx.ctx is not None
        views = self._tx.views if in_tx else None
        engine = self.db.engine
        try:
            with self.db.catalog.tx_scope(views):
                planned = engine.planner.plan(ast)
                ex = engine.executor
                plan = ex._route_projections(planned.plan)
                params = ex.seed_params(plan)
                # host-only detection passes (same as compile())
                from ..engine.executor import _number_nodes
                from ..sql.logical import Aggregate as _Agg, TopN as _TopN

                for nid, op in _number_nodes(plan).items():
                    if isinstance(op, _Agg) and ex.clustered_agg_enabled:
                        spec = ex._clustered_agg_spec(op)
                        if spec is not None:
                            params.clustered_aggs[nid] = spec
                    if isinstance(op, _TopN) and ex.clustered_agg_enabled:
                        vspec = ex._vector_topn_spec(op)
                        if vspec is not None:
                            params.vector_topns[nid] = vspec
                lines = explain_plan(ex, plan, params)
        finally:
            if any_vt:
                from .virtual_tables import PROVIDERS

                for n in names:
                    if n in PROVIDERS:
                        self.db.catalog.pop(n, None)
                        self.db._invalidate(n)
        if analyze:
            engine.last_phases = {}
            engine.last_op_profile = None
            pp = self.db.plan_profiler
            if pp is not None and pp.enabled:
                # EXPLAIN ANALYZE always profiles: force exactly one
                # profiled (segmented, fenced) run of the ANALYZED
                # statement's digest — re-point the pending digest too
                # (the one set at statement start named the outer
                # EXPLAIN text, not the inner select)
                d_inner = self._digest_of(text)
                pp.force_next(d_inner)
                pp.set_pending(d_inner)
            ta = _time.perf_counter()
            rs = self._select(ast, P.normalize_for_cache(text)[0])
            wall_s = _time.perf_counter() - ta
            ph = engine.last_phases

            def us(s: float) -> int:
                return int(s * 1e6)

            opp = engine.last_op_profile
            lines = list(lines)
            if opp is not None:
                from ..sql.explain import annotate_plan_lines

                lines = annotate_plan_lines(lines, opp)
            lines.append("")
            hit = "hit" if ph.get("cache_hit") else "miss"
            lines.append(
                f"ANALYZE rows={rs.nrows} plan_cache={hit}"
            )
            lines.append(f"  phase parse:   {us(parse_s)} us")
            if ph:
                lines.append(f"  phase plan:    {us(ph['plan_s'])} us")
                lines.append(f"  phase compile: {us(ph['compile_s'])} us")
                lines.append(f"  phase execute: {us(ph['exec_s'])} us")
            if opp is not None and wall_s > 0:
                # the host-tax view on the same report: how much of the
                # analyzed statement's e2e wall the chip actually worked
                # (device time = the profile's fenced per-operator sum)
                dev_s = sum(
                    s.device_us for s in opp["samples"]) / 1e6
                idle = max(0.0, wall_s - dev_s) / wall_s * 100.0
                lines.append(
                    f"  chip_idle_pct: {idle:.1f} "
                    f"(device {us(dev_s)} us of {us(wall_s)} us e2e)"
                )
        return ResultSet(("plan",), {"plan": lines})

    # ------------------------------------------------------------------ XA
    def _xa(self, text: str) -> ResultSet:
        """XA surface (src/storage/tx/ob_xa_ctx analog at this engine's
        scale): START/END tag a session tx with an external xid, PREPARE
        logs the branch's redo DURABLY through palf (XA_PREPARE records on
        every participant LS, ob_trans_part_ctx.h:154) and parks it with
        locks + staged rows held, and COMMIT/ROLLBACK finish it from ANY
        session — the external-coordinator contract. A restart rebuilds
        the parked set from log replay (+ the node-meta registry
        snapshot), re-stages the pending redo on the leader, and re-holds
        the locks: prepared branches survive kill-9 and remain decidable,
        which is the window XA exists for."""
        import re as _re

        m = _re.match(
            r"\s*xa\s+(\w+)\s*(?:'([^']*)'|\"([^\"]*)\"|([^\s;]+))?",
            text, _re.IGNORECASE,
        )
        if not m:
            raise SqlError("bad XA syntax")
        verb = m.group(1).lower()
        if verb == "recover":
            # owners see their branches; root sees everything; branches
            # still mid-PREPARE are not yet recoverable
            xids = sorted(
                x for x, entry in self.db._xa_prepared.items()
                if entry[0] is not _XA_PREPARING
                and (self.user == "root" or entry[1] == self.user)
            )
            return ResultSet(("xid",), {"xid": xids})
        xid = next((g for g in m.groups()[1:] if g is not None), None)
        if xid is None:
            raise SqlError("XA needs an xid", code=1398)  # XAER_INVAL
        if verb in ("start", "begin"):
            if self._tx is not None:
                raise SqlError("transaction already open", code=1399)
            self._tx = _OpenTx(self.db, deadline=self._new_trx_deadline())
            self._xa_id = xid
            return ResultSet((), {})
        if verb == "end":
            if self._tx is None or getattr(self, "_xa_id", None) != xid:
                raise SqlError(f"unknown xid {xid!r}", code=1397)
            return ResultSet((), {})  # idle marker; state kept implicit
        if verb == "prepare":
            from ..tx.txn import NotMaster, TxState

            if self._tx is None or getattr(self, "_xa_id", None) != xid:
                raise SqlError(f"unknown xid {xid!r}", code=1397)
            # RESERVE the xid before logging (one atomic check+insert): two
            # concurrent prepares under the same xid must not both log —
            # the loser's branch would park forever without a handle
            with self.db._ddl_lock:
                if xid in self.db._xa_prepared:
                    raise SqlError(f"xid {xid!r} already prepared",
                                   code=1399)
                self.db._xa_prepared[xid] = (_XA_PREPARING, self.user, None)
            tx = self._tx
            self._tx = None
            self._xa_id = None
            try:
                try:
                    tx.svc.xa_prepare(tx.ctx, xid, self.user,
                                      self.db.tenant_name)
                except NotMaster as e:
                    # xa_prepare already rolled the tx back locally (and
                    # logged ABORT where a PREPARE reached the log): only
                    # the server-side locks remain to release
                    self._post_tx_cleanup(tx, committed_ok=False)
                    raise SqlError(f"XA PREPARE failed: {e}", code=1399)
                self.db.cluster.drive_until(
                    lambda: tx.ctx.state is not TxState.PREPARING)
                if tx.ctx.state is not TxState.XA_PREPARED:
                    try:
                        if not tx.ctx.is_done:
                            tx.svc.abort(tx.ctx)
                    except Exception:
                        pass
                    self._post_tx_cleanup(tx, committed_ok=False)
                    raise SqlError(
                        f"XA PREPARE did not reach the log for {xid!r}",
                        code=1399)
            except BaseException:
                with self.db._ddl_lock:
                    self.db._xa_prepared.pop(xid, None)
                raise
            with self.db._ddl_lock:
                self.db._xa_prepared[xid] = (tx, self.user, None)
            return ResultSet((), {})
        if verb in ("commit", "rollback"):
            with self.db._ddl_lock:
                hit = self.db._xa_prepared.get(xid)
                if hit is not None:
                    _tx, owner = hit[0], hit[1]
                    if _tx is _XA_PREPARING:
                        raise SqlError(
                            f"xid {xid!r} is being prepared", code=1399)
                    # the decide step is guarded: only the preparing
                    # user or root may finish a parked branch
                    if self.user != "root" and owner != self.user:
                        raise SqlError(
                            f"xid {xid!r} belongs to {owner!r}",
                            code=1227,
                        )
                    del self.db._xa_prepared[xid]
            if hit is not None:
                parked_tx = hit[0]
                try:
                    if parked_tx is not None:
                        self._xa_finish_parked(parked_tx,
                                               commit=(verb == "commit"))
                    else:
                        self._xa_finish_recovered(
                            xid, hit[2], commit=(verb == "commit"))
                except BaseException:
                    # a FAILED decide must stay decidable: restore the
                    # handle so a retry can re-drive the same decision
                    # (locks stay held until it lands — see the gated
                    # cleanup in the finish helpers)
                    with self.db._ddl_lock:
                        self.db._xa_prepared.setdefault(xid, hit)
                    raise
                return ResultSet((), {})
            # one-phase: this session's own un-prepared xid
            if self._tx is not None and \
                    getattr(self, "_xa_id", None) == xid:
                tx = self._tx
                self._tx = None
                self._xa_id = None
            else:
                raise SqlError(f"unknown xid {xid!r}", code=1397)
            self._finish_tx(tx, commit=(verb == "commit"))
            return ResultSet((), {})
        raise SqlError(f"bad XA verb {verb!r}", code=1398)

    def _xa_finish_parked(self, tx: "_OpenTx", commit: bool) -> None:
        """Decide a live parked (XA_PREPARED) branch: redo is already in
        the log, so commit only logs the decision records. Locks release
        ONLY once the decision lands (ctx.is_done) — releasing on a
        timeout while COMMIT records sit undelivered would let a new
        writer slip under the prepared rows (lost update). A timed-out
        decide leaves the branch parked for retry (same decision)."""
        from ..tx.txn import TxState

        ctx = tx.ctx
        try:
            tx.svc.xa_decide(ctx, commit)
        except RuntimeError as e:
            raise SqlError(str(e), code=1399) from None

        def done() -> bool:
            tx.svc.retry_decisions(ctx)
            return ctx.is_done

        ok = self.db.cluster.drive_until(done)
        if ctx.is_done:
            committed_ok = commit and ctx.state is TxState.COMMITTED
            self._post_tx_cleanup(tx, committed_ok)
        if not ok:
            raise SqlError(f"XA decision for tx {ctx.tx_id} timed out")

    def _xa_finish_recovered(self, xid: str, snapshot: dict | None,
                             commit: bool) -> None:
        """Decide a branch recovered from log replay after a restart: no
        live ctx exists — submit the decision records straight to the
        participant leader replicas and wait for apply (which commits the
        re-staged rows / replays pending redo). `snapshot` is the handle's
        registry snapshot: a retry after a failed decide can finish
        cleanup from it even once the live registry entry has popped."""
        from ..tx.records import RecordType, TxRecord

        e = self.db._xa_registry.get(xid) or snapshot
        if e is None:
            return  # decision already applied (e.g. raced another session)
        want = "commit" if commit else "rollback"
        prior = e.get("decision")
        if prior is not None and prior != want:
            # records of the FIRST decision may already sit in participant
            # logs; reversing would split the branch across directions —
            # this guard holds on RETRIES too (the registry entry may have
            # popped, but the handle snapshot remembers the direction)
            raise SqlError(
                f"xid {xid!r} already deciding {prior}; retry that",
                code=1399)
        e["decision"] = want
        tx_id, parts = e["tx_id"], tuple(e["parts"])
        if xid in self.db._xa_registry:
            # first attempt (or retry whose records never reached a log):
            # submit the decision to every participant leader
            version = self.db.cluster.gts.next_ts() if commit else 0
            rtype = RecordType.COMMIT if commit else RecordType.ABORT
            for ls in parts:
                group = self.db.cluster.ls_groups.get(ls) or {}

                def try_submit(ls=ls, group=group) -> bool:
                    for rep in group.values():
                        if rep.is_ready and rep.submit_record(
                                TxRecord(rtype, tx_id, (), version)
                        ) is not None:
                            return True
                    return False

                if not self.db.cluster.drive_until(try_submit):
                    raise SqlError(
                        f"no ready leader for ls {ls} to decide xid {xid!r}")

        def all_applied() -> bool:
            # the branch is decided only when the decision has applied on
            # EVERY participant replica (registry pop happens at the FIRST
            # apply — releasing locks then would expose a torn multi-LS
            # branch / lost-update window)
            for ls in parts:
                for rep in (self.db.cluster.ls_groups.get(ls) or {}).values():
                    if tx_id in rep.tx_table:
                        return False
            return xid not in self.db._xa_registry

        if not self.db.cluster.drive_until(all_applied):
            raise SqlError(f"XA decision for xid {xid!r} did not apply")
        self.db.lock_mgr.release_all(tx_id)
        if commit:
            self._xa_bump_versions(e)

    def _xa_bump_versions(self, e: dict) -> None:
        by_tab = {ti.tablet_id: ti for ti in self.db.tables.values()}
        for tab in e["tablets"]:
            ti = by_tab.get(tab)
            if ti is not None:
                ti.data_version += 1
                ti.cached_data_version = -1
        self.db.run_maintenance()

    # -------------------------------------------------- stored procedures
    def _create_procedure(self, text: str) -> ResultSet:
        from ..sql.pl import parse_procedure

        if self.user != "root":
            from ..share.privilege import AccessDenied

            try:
                self.db.privileges.check(self.user, "create", {"*"})
            except AccessDenied as e:
                raise SqlError(str(e), code=e.code) from None
        try:
            proc = parse_procedure(text)
        except SyntaxError as e:
            raise SqlError(f"PL syntax: {e}") from None
        with self.db._ddl_lock:
            if proc.name in self.db._procedure_texts:
                raise SqlError(f"procedure {proc.name} already exists")
            self.db._procedure_texts[proc.name] = text
            self.db._procedures_parsed[proc.name] = proc
            self.db._save_node_meta()
        return ResultSet((), {})

    def _drop_procedure(self, text: str) -> ResultSet:
        if self.user != "root":
            from ..share.privilege import AccessDenied

            try:
                self.db.privileges.check(self.user, "drop", {"*"})
            except AccessDenied as e:
                raise SqlError(str(e), code=e.code) from None
        parts = text.split()
        if len(parts) < 3:
            raise SqlError("DROP PROCEDURE needs a name")
        # the lexer lowercases identifiers at CREATE: match it
        name = parts[2].rstrip(";").lower()
        with self.db._ddl_lock:
            if self.db._procedure_texts.pop(name, None) is None:
                raise SqlError(f"no procedure {name}")
            self.db._procedures_parsed.pop(name, None)
            self.db._save_node_meta()
        return ResultSet((), {})

    def lookup_procedure(self, name: str):
        proc = self.db._procedures_parsed.get(name)
        if proc is None:
            text = self.db._procedure_texts.get(name)
            if text is None:
                return None
            from ..sql.pl import parse_procedure

            proc = parse_procedure(text)
            self.db._procedures_parsed[name] = proc
        return proc

    def run_statement(self, stmt, cache_key: str | None = None) -> ResultSet:
        """Execute one already-parsed statement (PL interpreter's SQL
        hook). Privileges enforce under the CALLING user (invoker
        rights); `cache_key` must identify the STORED statement node
        (not the per-call substituted copy) so plans stay cached across
        invocations — literal substitutions parameterize away inside
        the plan cache exactly like client literals."""
        self._check_privs(stmt)
        return self._dispatch_stmt(
            stmt, cache_key or f"#pl:{id(stmt)}")

    def _call_procedure(self, text: str) -> ResultSet:
        from ..sql.pl import PlError, PlInterpreter, PlParser

        p = PlParser(text.rstrip().rstrip(";") + ";")
        try:
            call = p._pl_statement()
        except SyntaxError as e:
            raise SqlError(f"bad CALL: {e}") from None
        from ..sql.pl import PlCall

        if not isinstance(call, PlCall):
            raise SqlError("expected CALL name(args)")
        proc = self.lookup_procedure(call.name)
        if proc is None:
            raise SqlError(f"no procedure {call.name}")
        interp = PlInterpreter(self)
        try:
            args = [interp._eval(a, {}) for a in call.args]
            ret, _env = interp.call(proc, args)
        except PlError as e:
            raise SqlError(f"PL: {e}") from None
        if ret is None:
            return ResultSet((), {})
        return ResultSet(("result",), {"result": [ret]})

    def _select_flashback(self, ast, fb) -> ResultSet:
        """FLASHBACK query: every `t AS OF SNAPSHOT s` reference reads a
        statement-scoped materialization of the OLDER MVCC snapshot;
        plain references in the same statement read current data (so
        `t` can join `t AS OF SNAPSHOT s` to diff history). Plans do
        not cache: the snapshot tables are per-statement."""
        import dataclasses as _dc

        tmp_names = []
        # session-scoped keys: two sessions flashing back to the SAME
        # (table, snapshot) must not share one catalog entry — the first
        # finisher would pop it under the other statement
        sid = self.session_id
        try:
            for name, snap in fb:
                tmp = f"#fb:{name}@{snap}#{sid}"
                self.db.catalog[tmp] = self.db.snapshot_table(name, snap)
                self.db._invalidate(tmp)
                tmp_names.append(tmp)

            def rw(node):
                if isinstance(node, A.TableRef) and node.snapshot is not None:
                    return A.TableRef(
                        f"#fb:{node.name}@{node.snapshot}#{sid}",
                        node.alias or node.name,
                    )
                if _dc.is_dataclass(node) and not isinstance(node, type):
                    ch = {}
                    for f in _dc.fields(node):
                        cur = getattr(node, f.name)
                        new = rw(cur)
                        if new is not cur:
                            ch[f.name] = new
                    return _dc.replace(node, **ch) if ch else node
                if isinstance(node, tuple):
                    items = tuple(rw(x) for x in node)
                    if any(a is not b for a, b in zip(items, node)):
                        return items
                    return node
                return node

            ast2 = rw(ast)
            plain = _tables_in_ast(ast2) - set(tmp_names)
            self.db.refresh_virtual(plain)
            self.db.refresh_catalog(plain, tx=self._tx)
            rs = self.db.engine.run_ast(ast2, "#flashback", use_cache=False)
            return rs
        finally:
            for tmp in tmp_names:
                self.db.catalog.pop(tmp, None)
                self.db._invalidate(tmp)

    # -------------------------------------------------------------- lock
    def _lock_table(self, st: A.LockTable) -> ResultSet:
        from ..tx.tablelock import DeadlockDetected, LockMode

        ti = self.db.tables.get(st.name)
        if ti is None:
            raise SqlError(f"no such table {st.name}")
        if self._tx is None:
            raise SqlError("LOCK TABLE requires an open transaction")
        mode = LockMode.EXCLUSIVE if st.exclusive else LockMode.SHARE
        try:
            self.db.lock_mgr.lock(self._tx.ctx.tx_id, ti.tablet_id, mode)
        except DeadlockDetected:
            # victim policy: the cycle-closing tx aborts (share/deadlock)
            self._end_tx(commit=False)
            raise
        return ResultSet((), {})

    # -------------------------------------------------------------- show
    _BOOL_WORDS = {"true": 1, "on": 1, "false": 0, "off": 0}
    _CONSISTENCY_WORDS = {"strong": 0, "bounded_staleness": 1, "weak": 2}
    # enum-valued session variables: accepted words -> stored int
    _ENUM_VARS = {"ob_read_consistency": _CONSISTENCY_WORDS}

    def _set_session_var(self, text: str) -> ResultSet:
        """SET <name> = <value> — session-scoped variables (the reference's
        sys-var surface, narrowed to the diagnosability knobs):
        ob_enable_show_trace gates full-link collection for THIS session,
        ob_px_dop routes SELECTs through the distributed (PX) executor."""
        body = text.strip().rstrip(";")
        body = body[3:].strip()  # after SET
        name, eq, val = body.partition("=")
        if not eq:
            raise SqlError("SET needs <variable> = <value>")
        name = name.strip().lower().lstrip("@").strip()
        if name not in self._vars:
            raise SqlError(f"unknown session variable {name!r}")
        sval = val.strip().strip("'\"").lower()
        try:
            iv = int(sval)
        except ValueError:
            iv = self._ENUM_VARS.get(name, {}).get(sval)
            if iv is None:
                iv = self._BOOL_WORDS.get(sval)
            if iv is None:
                raise SqlError(
                    f"bad value {val.strip()!r} for {name}") from None
        if name in self._ENUM_VARS and iv not in set(
                self._ENUM_VARS[name].values()):
            raise SqlError(f"bad value {val.strip()!r} for {name}")
        self._vars[name] = iv
        if name == "ob_enable_show_trace" and iv:
            # collection implies recording: a session asking for SHOW
            # TRACE needs spans in the ring regardless of the global flag
            self.db.tracer.enabled = True
        return ResultSet((), {})

    def _show_trace(self) -> ResultSet:
        if not self._vars.get("ob_enable_show_trace"):
            raise SqlError(
                "SHOW TRACE needs SET ob_enable_show_trace = 1 before the "
                "statement under diagnosis")
        tree = self.db.tracer.trace_tree(self._last_trace_id)
        names, nodes, elapsed, tags = [], [], [], []
        for depth, s in tree:
            names.append("  " * depth + s.name)
            nodes.append(str(s.tags.get("node", "")))
            elapsed.append(int(s.elapsed * 1e6))
            tags.append(", ".join(
                f"{k}={v}" for k, v in sorted(s.tags.items())
                if k != "node"
            ))
        return ResultSet(
            ("span_name", "node", "elapsed_us", "tags"),
            {"span_name": names, "node": nodes, "elapsed_us": elapsed,
             "tags": tags},
        )

    def _show(self, st: A.Show) -> ResultSet:
        if st.what == "trace":
            return self._show_trace()
        if st.what == "parameters":
            import fnmatch

            pat = st.like.replace("%", "*").replace("_", "?") if st.like else None
            names, values, types, scopes, infos = [], [], [], [], []
            for n, v, p in self.db.config.snapshot():
                if pat is not None and not fnmatch.fnmatch(n, pat):
                    continue
                names.append(n)
                values.append(str(v))
                types.append(p.type)
                scopes.append(p.scope)
                infos.append(p.info)
            return ResultSet(
                ("name", "value", "type", "scope", "info"),
                {"name": names, "value": values, "type": types,
                 "scope": scopes, "info": infos},
            )
        if st.what == "tables":
            names = sorted(set(self.db.tables) | set(self.db.catalog))
            return ResultSet(("table_name",), {"table_name": names})
        raise SqlError(f"unsupported SHOW {st.what}")

    # ------------------------------------------------------------ select
    _INDEX_ROUTE_MAX_ROWS = 4096

    def _index_route(self, ast: A.Select) -> dict[str, Table] | None:
        """DAS index/PK lookup analog (src/sql/das/iter): a single-table
        statement whose WHERE pins an index prefix (or the full primary
        key) with equality literals reads the few matching rows through the
        host index path instead of materializing the whole table to the
        device. Returns a statement-scoped {table: pruned Table} view, or
        None to fall back to the full-scan path. Autocommit reads only —
        in-tx statements keep their BEGIN-snapshot materialization."""
        if not isinstance(ast, A.Select) or len(ast.from_) != 1:
            return None
        tref = ast.from_[0]
        if not isinstance(tref, A.TableRef) or ast.ctes:
            return None
        from ..sql.planner import _contains_subquery

        if _contains_subquery(ast):
            return None
        ti = self.db.tables.get(tref.name)
        if ti is None or ast.where is None:
            return None
        alias = tref.alias or tref.name
        from ..sql.planner import split_ast_conjuncts

        eqs: dict[str, object] = {}
        for c in split_ast_conjuncts(ast.where):
            if not (isinstance(c, A.BinOp) and c.op == "="):
                continue
            lhs, rhs = c.left, c.right
            if not isinstance(lhs, A.Name):
                lhs, rhs = rhs, lhs
            if not isinstance(lhs, A.Name):
                continue
            parts = lhs.parts
            if len(parts) == 2 and parts[0] != alias:
                continue
            col = parts[-1]
            if col not in ti.schema:
                continue
            try:
                v = _eval_const(rhs)
            except SqlError:
                continue
            # encode without growing the dictionary: an unknown string
            # matches nothing (code -1 < every stored code)
            dt = ti.schema[col]
            if dt.kind is TypeKind.VARCHAR:
                d = ti.dicts.get(col)
                eqs[col] = d.encode_one(str(v), add=False) if d else -1
            else:
                try:
                    eqs[col] = _coerce(v, dt, None, col)
                except SqlError:
                    continue  # untypable literal: leave it to the engine

        if not eqs:
            return None
        snap = self.db.cluster.gts.current()
        rep = self.db._leader_replica(ti)
        rows: list[tuple] | None = None
        used_idx = None
        if set(ti.key_cols) <= set(eqs):
            pk = tuple(int(eqs[k]) for k in ti.key_cols)
            pls, ptab = ti.partition_for_key(pk)
            hit = self.db._leader_replica_ls(pls).tablets[ptab].get(pk, snap)
            rows = [hit[1]] if hit is not None else []
        else:
            best = None
            for idx in ti.indexes.values():
                if idx.status != "ready":
                    continue
                m = 0
                for c in idx.cols:
                    if c in eqs:
                        m += 1
                    else:
                        break
                if m and (best is None or m > best[1]):
                    best = (idx, m)
            if best is None:
                return None
            idx, m = best
            ranges = {
                c: (float(eqs[c]), float(eqs[c])) for c in idx.cols[:m]
            }
            idata = rep.tablets[idx.tablet_id].scan(snap, ranges=ranges)
            # ranges only PRUNE (zone maps; memtable rows come back whole):
            # apply the exact equality filter before fetching base rows
            if len(idata[idx.key_cols[0]]):
                m_ok = np.ones(len(idata[idx.key_cols[0]]), dtype=bool)
                for c in idx.cols[:m]:
                    m_ok &= idata[c] == eqs[c]
                idata = {c: a[m_ok] for c, a in idata.items()}
            pk_arrays = [idata[k] for k in ti.key_cols]
            npk = len(pk_arrays[0]) if pk_arrays else 0
            if npk > self._INDEX_ROUTE_MAX_ROWS:
                return None  # not selective enough: full scan wins
            rows = []
            for i in range(npk):
                pk = tuple(int(a[i]) for a in pk_arrays)
                pls, ptab = ti.partition_for_key(pk)
                hit = self.db._leader_replica_ls(pls).tablets[ptab].get(pk, snap)
                if hit is not None:
                    rows.append(hit[1])
            used_idx = idx
        names = ti.schema.names()
        data = {
            c: np.array([r[j] for r in rows], dtype=ti.schema[c].storage_np)
            for j, c in enumerate(names)
        }
        dicts = {}
        for col in ti.dicts:
            sd, remap = ti.sorted_dict(col)
            if len(data[col]):
                data[col] = remap[data[col]]
            dicts[col] = sd
        if used_idx is not None:
            used_idx.reads += 1
        if self.db.access.enabled:
            # workload heat: host-side DAS lookups are reads the device
            # scan path never sees
            self.db.access.record_das(tref.name, len(rows))
        return {tref.name: Table(tref.name, ti.schema, data, dicts)}

    def _follower_select(self, ast: A.Select, norm_key: str,
                         names) -> "ResultSet | None":
        """Serve a non-strong SELECT from follower replicas: statement-
        scoped views (TxCatalog.tx_scope, so the shared device-batch and
        fast-path caches never see replica state) at a snapshot provably
        within the session's staleness bound. None falls back to the
        leader path — which is also the `strong`-on-follower contract:
        identical routing, bit-identical rows."""
        db = self.db
        weak = self._vars["ob_read_consistency"] == 2
        fv = db.follower_read_views(
            names, self._vars.get("ob_max_read_stale_us", 0), weak=weak)
        if fv is None:
            return None
        views, snap, stale_us = fv
        # non-replicated tables in the statement (preloaded/external)
        # refresh through the normal shared-catalog path
        db.refresh_catalog([n for n in names if n not in views], tx=None)
        with db.catalog.tx_scope(views):
            rs = db.engine.run_ast(ast, norm_key)
        self._stmt_cache_hit = rs.plan_cache_hit
        self.last_follower_read = (snap, stale_us)
        db.metrics.add("follower read hits")
        return rs

    def _select_degraded(self, ast: A.Select, norm_key: str) -> ResultSet:
        """Device-OOM ladder rungs 2/3: re-drive the statement on a
        private degraded executor. "chunk" re-plans through the chunked
        path with a chunk size derived from the budget the governor has
        left; "host" compiles a fresh plan pinned to the host device
        (which cannot device-OOM). Both bypass the plan cache — the
        cached executable is exactly what just OOMed — and both return
        bit-identical rows to the undegraded plan."""
        import contextlib

        from ..engine.executor import Executor
        from ..engine.memory_governor import derive_chunk_rows

        db = self.db
        base = db.engine.executor
        if self._degrade_mode == "chunk":
            remaining = max(db.governor.remaining(), 1)
            ex = Executor(
                db.catalog, unique_keys=base.unique_keys, stats=base.stats,
                device_budget=remaining,
                chunk_rows=derive_chunk_rows(remaining, base.chunk_rows),
            )
            ctx = contextlib.nullcontext()
        else:  # host fallback
            ex = Executor(db.catalog, unique_keys=base.unique_keys,
                          stats=base.stats)
            ex.chunking_enabled = False
            ex.host_fallback = True
            try:
                import jax

                ctx = jax.default_device(jax.devices("cpu")[0])
            except Exception:  # no CPU device handle: backend IS the host
                ctx = contextlib.nullcontext()
        ex.timeline = base.timeline
        in_tx = self._tx is not None and self._tx.ctx is not None
        views = self._tx.views if in_tx else None
        with ctx, db.catalog.tx_scope(views):
            rs = db.engine.run_ast(ast, norm_key, use_cache=False,
                                   executor=ex)
        self._stmt_cache_hit = False
        return rs

    def _select(self, ast: A.Select, norm_key: str, fast_reg=None
                ) -> ResultSet:
        fb = _flashback_refs(ast)
        if fb:
            return self._select_flashback(ast, fb)
        raw_names = _tables_in_ast(ast)
        names = self.db.expand_views(set(raw_names))
        any_vt = self.db.refresh_virtual(names)
        self.last_follower_read = None
        if self._degrade_mode is not None and not any_vt:
            # device-OOM ladder rungs 2/3: re-plan on a private degraded
            # executor (chunked or host), bypassing PX and index routing
            self.db.refresh_catalog(names, tx=self._tx)
            return self._select_degraded(ast, norm_key)
        if (self._vars.get("ob_read_consistency", 0) != 0
                and self._tx is None and not any_vt
                and self._vars.get("ob_px_dop", 0) == 0
                and isinstance(ast, A.Select)):
            rs = self._follower_select(ast, norm_key, names)
            if rs is not None:
                return rs
            # bound unmet / no reachable follower: strong leader path below
        route = None
        if self._tx is None and not any_vt and isinstance(ast, A.Select):
            route = self._index_route(ast)
        if route is not None:
            self.db.refresh_catalog(
                [n for n in names if n not in route], tx=None
            )
            with self.db.catalog.tx_scope(route):
                rs = self.db.engine.run_ast(ast, norm_key)
            self._stmt_cache_hit = rs.plan_cache_hit
            return rs
        self.db.refresh_catalog(names, tx=self._tx)
        in_tx = self._tx is not None and self._tx.ctx is not None
        views = self._tx.views if in_tx else None
        # PX routing: non-virtual statements of a session with a DOP
        # variable run on the distributed executor. In-tx reads are safe:
        # the PX executor bypasses its shared input cache for tx-private
        # views (is_private), mirroring the single-chip isolation contract.
        px = None
        px_granted = 0
        if self._vars.get("ob_px_dop", 0) > 0 and not any_vt:
            # admission first (ObPxAdmission): hold a worker grant for the
            # whole distributed execution, released in the finally below
            px_granted = self._px_admit(self._vars["ob_px_dop"])
            px = self.db._px_executor()
        # fast-tier registration only from the plain route: no virtual
        # tables (use_cache is off anyway), no open tx (tx-private views
        # would leak across sessions), no PX (the compiled plan differs),
        # and no view expansion (the scan tables a fast hit privilege-
        # checks would diverge from what the user was granted)
        reg = (fast_reg if px is None and not any_vt and not in_tx
               and names == raw_names else None)
        try:
            with self.db.catalog.tx_scope(views):
                try:
                    rs = self.db.engine.run_ast(
                        ast, norm_key,
                        use_cache=False if any_vt else None,
                        executor=px,
                        fast_reg=reg,
                    )
                except Exception:
                    if px is None:
                        raise
                    # PX degradation: distributed compile/execute failures
                    # fall back to the single-chip path (genuine SQL
                    # errors re-raise identically from it)
                    self.db.metrics.add("px fallbacks")
                    rs = self.db.engine.run_ast(
                        ast, norm_key,
                        use_cache=False if any_vt else None,
                    )
            # surfaces in the audit record; for DML the qualification
            # scan's plan reuse IS the statement's plan-cache behavior
            self._stmt_cache_hit = rs.plan_cache_hit
            return rs
        finally:
            if px_granted:
                self.db._px_admission().release(px_granted)
            if any_vt:
                # virtual snapshots are per-statement: release them so they
                # neither pin memory nor appear as tables afterwards
                from .virtual_tables import PROVIDERS

                for n in names:
                    if n in PROVIDERS:
                        self.db.catalog.pop(n, None)
                        self.db._invalidate(n)

    def _new_trx_deadline(self) -> "_R.Deadline":
        """ob_trx_timeout deadline for a transaction opened now (BEGIN,
        XA START, or an autocommit DML's implicit tx)."""
        db = self.db
        return _R.Deadline.after(
            lambda: db.cluster.bus.now,
            self._vars["ob_trx_timeout"] / 1e6,
            label="ob_trx_timeout",
        )

    def _px_admit(self, dop: int) -> int:
        """Deadline-bounded PX admission: queue for a worker grant no
        longer than the statement deadline allows. An admission timeout is
        retryable (quota frees as peers finish) unless the deadline was
        the tighter bound, which surfaces as the statement's timeout."""
        adm = self.db._px_admission()
        wait_s = adm.queue_timeout_s
        d = _R.current_deadline()
        bounded = d is not None and d.tighter_than(wait_s)
        if bounded:
            wait_s = max(d.remaining(), 0.0)
        try:
            with self.db.metrics.waiting("px admission queue"):
                return adm.acquire(dop, timeout=wait_s)
        except RuntimeError as e:
            self.db.metrics.add("px admission timeouts")
            if bounded:
                self.db.metrics.add("statement timeouts")
                raise d._error() from e
            raise _R.PxAdmissionTimeout(str(e)) from e

    def _digest_of(self, text: str) -> str:
        """Memoized statement digest (same key the workload summary,
        host-tax ledger and flight recorder fold under)."""
        digest = self._digest_memo.get(text)
        if digest is None:
            if len(self._digest_memo) >= 256:
                self._digest_memo.clear()
            digest = self._digest_memo[text] = P.digest_text(text)
        return digest

    def _reserve_estimate(self, text: str) -> int:
        """Peak-device-bytes estimate for the admission reservation:
        the workload repository's measured per-digest peak when this
        statement has run before, else a conservative cold default for
        reads (ob_governor_cold_reserve). Non-reads reserve nothing —
        DML device work rides the read paths it triggers."""
        db = self.db
        low = text.lstrip().lower()
        if not low.startswith(("select", "with", "(")):
            return 0
        measured = db.stmt_summary.peak_estimate(self._digest_of(text))
        if measured > 0:
            return measured
        return int(db.config["ob_governor_cold_reserve"])

    def _reserve_device_memory(self, nbytes: int):
        """Deadline-bounded device-memory admission (mirrors _px_admit):
        wait on the governor's ledger no longer than the statement
        deadline allows. A reservation timeout is retryable (peers
        release as they finish) unless the deadline was the tighter
        bound, which surfaces as the statement's timeout."""
        db = self.db
        gov = db.governor
        wait_s = float(db.config["ob_governor_queue_timeout"])
        d = _R.current_deadline()
        bounded = d is not None and d.tighter_than(wait_s)
        if bounded:
            wait_s = max(d.remaining(), 0.0)
        with db.metrics.waiting("device memory reservation"):
            res = gov.reserve(db.tenant_name, nbytes, timeout_s=wait_s)
        if res is None:
            db.metrics.add("device memory rejects")
            if bounded:
                db.metrics.add("statement timeouts")
                raise d._error()
            raise _R.DeviceMemoryTimeout(
                f"device memory reservation of {nbytes} bytes timed out "
                f"after {wait_s:.3f}s (reserved {gov.reserved} of "
                f"{gov.effective_budget()} bytes)")
        return res

    # --------------------------------------------------------------- tx
    def _dml(self, body) -> ResultSet:
        # an expired deadline (ob_trx_timeout on an idle explicit tx) must
        # refuse new work up front — the session can still ROLLBACK, which
        # doesn't come through here
        _R.checkpoint_deadline()
        auto = self._tx is None
        if auto:
            self._tx = _OpenTx(self.db, deadline=self._new_trx_deadline())
        try:
            affected = body(self._tx)
        except Exception:
            if auto:
                self._end_tx(commit=False)
            raise
        if auto:
            self._end_tx(commit=True)
        return ResultSet((), {}, affected=affected,
                         plan_cache_hit=self._stmt_cache_hit)

    def _end_tx(self, commit: bool) -> None:
        tx = self._tx
        self._tx = None
        self._xa_id = None  # a finished tx sheds any XA association
        self._finish_tx(tx, commit)

    def _finish_tx(self, tx: "_OpenTx | None", commit: bool) -> None:
        """Drive a transaction to its decision and clean up — shared by
        COMMIT/ROLLBACK and the XA paths (where the tx may have been
        PREPARED by a different session)."""
        if tx is None or tx.ctx is None:
            return
        touched = tx.touched_tables
        committed_ok = False
        m = self.db.metrics
        tc0 = _time.perf_counter()
        try:
            if commit:
                try:
                    if touched:
                        # bound the palf commit wait by the statement
                        # deadline; an expired wait means the decision is
                        # in flight but unobserved -> CommitUnknown (the
                        # reference's OB_TRANS_UNKNOWN), never retried
                        max_wait = 30.0
                        d = _R.current_deadline()
                        if d is not None:
                            d.check()  # unwind before staging the decision
                            max_wait = min(max_wait, d.remaining())
                        with m.waiting("tx commit log sync"):
                            try:
                                self.db.cluster.commit_sync(
                                    tx.svc, tx.ctx, max_time=max_wait)
                            except TimeoutError as te:
                                raise _R.CommitUnknown(
                                    f"commit wait timed out: {te}"
                                ) from te
                    else:
                        tx.svc.commit(tx.ctx)  # empty tx: finishes immediately
                except Exception:
                    # commit failed before a decision was logged: abort so the
                    # staged rows don't stay undecided forever (which would
                    # block later writers and pin frozen memtables). A tx in
                    # COMMITTING has its decision in flight and must converge
                    # on its own; abort() refuses that case.
                    from ..tx.txn import TxState

                    if not tx.ctx.is_done and tx.ctx.state is not TxState.COMMITTING:
                        tx.svc.abort(tx.ctx)
                    raise
                committed_ok = True
            else:
                tx.svc.abort(tx.ctx)
        finally:
            if commit and committed_ok:
                m.add("tx commits")
                m.observe("tx commit", _time.perf_counter() - tc0)
            elif commit:
                m.add("tx commit failures")
            else:
                m.add("tx rollbacks")
            self._post_tx_cleanup(tx, committed_ok)

    def _post_tx_cleanup(self, tx: "_OpenTx", committed_ok: bool) -> None:
        """Shared decision epilogue: release locks, refresh table versions,
        note durably-logged dictionary growth, trigger maintenance."""
        touched = tx.touched_tables
        # locks hold through the commit decision, then release
        self.db.lock_mgr.release_all(tx.ctx.tx_id)
        by_tablet = {}
        for name in touched:
            ti = self.db.tables.get(name)
            if ti is not None:
                by_tablet[ti.tablet_id] = ti
                if committed_ok:
                    ti.data_version += 1
                ti.cached_data_version = -1
        if committed_ok:
            # the appends are durable now (committed_ok, NOT the commit
            # intent: a failed commit logged nothing): later commits
            # need not re-log them
            for tab_id, col, code, _s in tx.ctx.dict_appends:
                ti = by_tablet.get(tab_id)
                if ti is not None:
                    ti.logged_dict_len[col] = max(
                        ti.logged_dict_len.get(col, 0), code + 1
                    )
        if committed_ok and touched:
            # post-commit freeze/compaction check (the tenant freezer's
            # write-path trigger; cheap when under the memstore limit)
            self.db.run_maintenance()

    # --------------------------------------------------------------- DML
    @staticmethod
    def _note_dict_appends(tx: _OpenTx, ti: TableInfo) -> None:
        """Attach every not-yet-durably-logged dictionary entry to this tx
        (log self-description for CDC/PITR). Based on logged_dict_len, not
        statement-local growth: entries created by an earlier aborted tx or
        a concurrent open tx get (re-)logged by the next committer, so the
        committed log always covers every code it references."""
        for col, d in ti.dicts.items():
            n0 = ti.logged_dict_len.get(col, 0)
            if len(d) > n0:
                tx.ctx.dict_appends.extend(
                    (ti.tablet_id, col, code, d.decode_one(code))
                    for code in range(n0, len(d))
                )

    def _stage_all(self, tx: _OpenTx, ti: TableInfo,
                   muts: list[tuple[tuple, int, tuple | None]],
                   index_muts: list[tuple[int, tuple, int, tuple | None]] = (),
                   ) -> int:
        """Stage a fully-validated mutation batch (statement atomicity: no
        row reaches the memtable until the whole statement has resolved, so
        a failed statement inside an explicit tx leaves no partial writes).
        A WriteConflict during staging still aborts the whole tx — that is
        transaction, not statement, semantics (first-committer-wins).

        Rows route to their hash partition's tablet; a multi-partition
        statement stages on several LS leaders in one tx and commits with
        2PC — the parallel-DML shape (reference sql/engine/pdml). Index
        mutations ride the same tx on the first partition's log stream."""
        if muts or index_muts:
            from ..tx.tablelock import LockMode

            # implicit intention lock: DML conflicts with explicit
            # SHARE/EXCLUSIVE table locks held by other txs (tablelock)
            self.db.lock_mgr.lock(tx.ctx.tx_id, ti.tablet_id, LockMode.ROW_X)
            needed_ls = {ls for ls, _t, _k, _o, _v in muts}
            if index_muts:
                needed_ls.add(ti.ls_id)
            for ls in sorted(needed_ls):
                tx.ensure_leader(ls)
            for ls_id, tab_id, key, op, vals in muts:
                tx.svc.write(tx.ctx, ls_id, tab_id, key, op, vals)
            for tab_id, key, op, vals in index_muts:
                tx.svc.write(tx.ctx, ti.ls_id, tab_id, key, op, vals)
            tx.touched_tables.add(ti.name)
        return len(muts)

    @staticmethod
    def _index_entry(ti: TableInfo, idx: IndexInfo, vals: tuple):
        """(index key, index row values) of a base row's index entry."""
        vmap = {f.name: vals[i] for i, f in enumerate(ti.schema.fields)}
        ivals = tuple(vmap[c] for c in idx.schema.names())
        ikey = tuple(int(vmap[c]) for c in idx.key_cols)
        return ikey, ivals

    def _check_unique(self, tx: _OpenTx, ti: TableInfo, idx: IndexInfo,
                      ikey: tuple, own_pk: tuple | None = None) -> None:
        """Reject a committed conflicting entry for a UNIQUE index key.
        Concurrent in-flight writers of the same key are handled by the
        memtable's first-committer-wins staging conflict."""
        rep = tx.svc.replicas[ti.ls_id]
        hit = rep.tablets[idx.tablet_id].get(
            ikey, tx.ctx.read_snapshot, tx_id=tx.ctx.tx_id
        )
        if hit is None:
            return
        if own_pk is not None:
            names = idx.schema.names()
            hit_pk = tuple(
                int(hit[1][names.index(k)]) for k in ti.key_cols
            )
            if hit_pk == own_pk:
                return
        raise SqlError(
            f"unique index {idx.name} violation on {ikey} in {ti.name}"
        )

    # ------------------------------------------------------- trigger firing
    _MAX_TRIGGER_DEPTH = 8

    def _fire_triggers(self, table: str, event: str, timing: str,
                       rows: list, tx: _OpenTx) -> None:
        """Fire matching row triggers for each (new_map, old_map) in
        `rows`. SET NEW.x mutates new_map in place (BEFORE); DML actions
        substitute NEW/OLD as literals and run through the normal handlers
        INSIDE the same transaction."""
        trigs = self.db.triggers_for(table, event, timing)
        if not trigs:
            return
        from ..sql.trigger import TriggerError, substitute

        depth = getattr(self, "_trigger_depth", 0)
        if depth >= self._MAX_TRIGGER_DEPTH:
            raise SqlError(
                f"trigger recursion deeper than {self._MAX_TRIGGER_DEPTH}")
        self._trigger_depth = depth + 1
        try:
            for new_map, old_map in rows:
                for _name, acts in trigs:
                    for act in acts:
                        if act[0] == "setnew":
                            _k, col, expr = act
                            if new_map is None or col not in new_map:
                                raise SqlError(
                                    f"trigger SET NEW.{col}: no such column")
                            new_map[col] = _eval_const(
                                substitute(expr, new_map, old_map))
                        else:
                            st2 = substitute(act[1], new_map, old_map)
                            if isinstance(st2, A.Insert):
                                self._insert(st2, tx)
                            elif isinstance(st2, A.Update):
                                self._update(st2, tx)
                            else:
                                self._delete(st2, tx)
        except TriggerError as e:
            raise SqlError(str(e)) from None
        finally:
            self._trigger_depth = depth

    def _has_triggers(self, table: str, event: str) -> bool:
        return any(
            s["table"] == table and s["event"] == event
            for s in self.db._trigger_specs.values()
        )

    def _insert(self, st: A.Insert, tx: _OpenTx) -> int:
        ti = self.db.tables.get(st.table)
        if ti is None:
            raise SqlError(f"no such table {st.table}")
        names = list(st.columns) if st.columns else ti.schema.names()
        for n in names:
            if n not in ti.schema:
                raise SqlError(f"unknown column {n}")
        missing = [n for n in ti.schema.names() if n not in names]
        if missing:
            raise SqlError(f"insert must provide all columns (missing {missing})")

        if st.select is not None:
            rs = self._select(st.select, _norm_stmt(f"$ins:{st.table}", st.select))
            src = [rs.columns[c] for c in rs.names]
            py_rows = list(zip(*src)) if src else []
        else:
            py_rows = [tuple(_eval_const(e) for e in row) for row in st.rows]

        fire = self._has_triggers(st.table, "insert")
        new_maps: list[dict] = []
        if fire:
            for row in py_rows:  # arity must hold BEFORE dict(zip) truncates
                if len(row) != len(names):
                    raise SqlError("value count does not match column count")
            new_maps = [dict(zip(names, row)) for row in py_rows]
            self._fire_triggers(
                st.table, "insert", "before",
                [(m, None) for m in new_maps], tx)
            py_rows = [tuple(m[n] for n in names) for m in new_maps]

        order = [names.index(n) for n in ti.schema.names()]
        staged: list[tuple[int, int, tuple, tuple]] = []
        seen: set[tuple] = set()
        for row in py_rows:
            if len(row) != len(names):
                raise SqlError("value count does not match column count")
            vals = tuple(
                _coerce(row[order[i]], f.dtype, ti.dicts.get(f.name), f.name)
                for i, f in enumerate(ti.schema.fields)
            )
            key = tuple(int(vals[ti.schema.index(k)]) for k in ti.key_cols)
            if key in seen:
                raise SqlError(f"duplicate primary key {key} in {st.table}")
            seen.add(key)
            ls_id, tab_id = ti.partition_for_key(key)
            staged.append((ls_id, tab_id, key, vals))
        needed_ls = sorted({ls for ls, _t, _k, _v in staged})
        if ti.indexes:
            needed_ls = sorted(set(needed_ls) | {ti.ls_id})
        for ls in needed_ls:
            tx.ensure_leader(ls)
        muts: list[tuple[int, int, tuple, int, tuple | None]] = []
        for ls_id, tab_id, key, vals in staged:
            rep = tx.svc.replicas[ls_id]
            if rep.tablets[tab_id].get(
                key, tx.ctx.read_snapshot, tx_id=tx.ctx.tx_id
            ) is not None:
                raise SqlError(f"duplicate primary key {key} in {st.table}")
            muts.append((ls_id, tab_id, key, OP_PUT, vals))
        index_muts: list[tuple[int, tuple, int, tuple | None]] = []
        for idx in ti.indexes.values():
            seen_i: set[tuple] = set()
            for _ls, _t, key, _op, vals in muts:
                ikey, ivals = self._index_entry(ti, idx, vals)
                if idx.unique:
                    if ikey in seen_i:
                        raise SqlError(
                            f"unique index {idx.name} violation on {ikey}"
                        )
                    seen_i.add(ikey)
                    self._check_unique(tx, ti, idx, ikey)
                index_muts.append((idx.tablet_id, ikey, OP_PUT, ivals))
        self._note_dict_appends(tx, ti)
        n = self._stage_all(tx, ti, muts, index_muts)
        if fire:
            self._fire_triggers(
                st.table, "insert", "after",
                [(m, None) for m in new_maps], tx)
        return n

    def _qualify(self, st, ti: TableInfo, cols: list[str],
                 set_exprs: tuple[tuple[str, A.Node], ...] = ()) -> ResultSet:
        """Run the qualification scan for UPDATE/DELETE through the engine:
        SELECT <cols> [, set-exprs] FROM t WHERE <pred> — the rebuild
        analog of the DML operator's child scan."""
        items = [A.SelectItem(A.Name((ti.name, c)), c) for c in cols]
        for i, (_col, e) in enumerate(set_exprs):
            items.append(A.SelectItem(e, f"$set{i}"))
        sel = A.Select(
            items=tuple(items),
            from_=(A.TableRef(ti.name),),
            where=st.where,
        )
        return self._select(sel, _norm_stmt(f"$dml:{ti.name}", st))

    def _update(self, st: A.Update, tx: _OpenTx) -> int:
        ti = self.db.tables.get(st.table)
        if ti is None:
            raise SqlError(f"no such table {st.table}")
        for col, _ in st.assignments:
            if col not in ti.schema:
                raise SqlError(f"unknown column {col}")
            if col in ti.key_cols:
                raise SqlError(f"updating key column {col} not supported")
        # constant assignments evaluate on host (a bare string literal has
        # no device representation); computed ones ride the qualification
        # scan as extra projections
        const_sets: dict[str, object] = {}
        computed: list[tuple[str, A.Node]] = []
        for col, e in st.assignments:
            try:
                const_sets[col] = _eval_const(e)
            except SqlError:
                computed.append((col, e))
        rs = self._qualify(st, ti, ti.schema.names(), tuple(computed))
        set_cols = {col: rs.columns[f"$set{i}"]
                    for i, (col, _) in enumerate(computed)}
        if any(idx.unique for idx in ti.indexes.values()):
            # _check_unique below reads the local replica of the index LS;
            # become (or sync with) its leader first or a lagging follower
            # can miss committed entries and admit a UNIQUE violation
            # (mirrors _insert's ensure_leader-before-check ordering)
            tx.ensure_leader(ti.ls_id)
        muts: list[tuple[tuple, int, tuple | None]] = []
        index_muts: list[tuple[int, tuple, int, tuple | None]] = []
        # intra-statement duplicate guard (mirrors _insert's seen_i): two
        # rows updated to the same unique key both pass the committed-state
        # check, so the statement itself must catch the collision
        seen_i: dict[str, set[tuple]] = {
            idx.name: set() for idx in ti.indexes.values() if idx.unique
        }
        fire = self._has_triggers(st.table, "update")
        fired_rows: list[tuple] = []
        for r in range(rs.nrows):
            new_map = old_map = None
            if fire:
                old_map = {
                    f.name: rs.columns[f.name][r] for f in ti.schema.fields
                }
                new_map = {}
                for f in ti.schema.fields:
                    if f.name in const_sets:
                        new_map[f.name] = const_sets[f.name]
                    else:
                        src = set_cols.get(f.name)
                        new_map[f.name] = (
                            src[r] if src is not None else old_map[f.name]
                        )
                self._fire_triggers(
                    st.table, "update", "before", [(new_map, old_map)], tx)
                for k in ti.key_cols:
                    if new_map[k] != old_map[k]:
                        raise SqlError(
                            f"trigger changed key column {k}")
                fired_rows.append((new_map, old_map))
            vals = []
            old_vals = []
            for f in ti.schema.fields:
                ov = rs.columns[f.name][r]
                old_vals.append(_coerce(ov, f.dtype, ti.dicts.get(f.name), f.name))
                if new_map is not None:
                    v = new_map[f.name]
                elif f.name in const_sets:
                    v = const_sets[f.name]
                else:
                    src = set_cols.get(f.name)
                    v = src[r] if src is not None else ov
                vals.append(_coerce(v, f.dtype, ti.dicts.get(f.name), f.name))
            vals = tuple(vals)
            old_vals = tuple(old_vals)
            key = tuple(int(vals[ti.schema.index(k)]) for k in ti.key_cols)
            ls_id, tab_id = ti.partition_for_key(key)
            muts.append((ls_id, tab_id, key, OP_PUT, vals))
            for idx in ti.indexes.values():
                old_ik, _ = self._index_entry(ti, idx, old_vals)
                new_ik, new_iv = self._index_entry(ti, idx, vals)
                if idx.unique:
                    # an unchanged entry still occupies its key within this
                    # statement; record it so another row can't move onto it
                    if new_ik in seen_i[idx.name]:
                        raise SqlError(
                            f"unique index {idx.name} violation on {new_ik}"
                        )
                    seen_i[idx.name].add(new_ik)
                if old_ik == new_ik:
                    continue  # entry content (key cols + pk) unchanged
                if idx.unique:
                    self._check_unique(tx, ti, idx, new_ik, own_pk=key)
                index_muts.append((idx.tablet_id, old_ik, OP_DELETE, None))
                index_muts.append((idx.tablet_id, new_ik, OP_PUT, new_iv))
        self._note_dict_appends(tx, ti)
        n = self._stage_all(tx, ti, muts, index_muts)
        if fire:
            self._fire_triggers(st.table, "update", "after", fired_rows, tx)
        return n

    def _delete(self, st: A.Delete, tx: _OpenTx) -> int:
        ti = self.db.tables.get(st.table)
        if ti is None:
            raise SqlError(f"no such table {st.table}")
        # the qualification scan must surface every indexed column so the
        # old index entries can be tombstoned alongside the base rows
        # (plus the whole row when delete triggers need OLD.*)
        fire = self._has_triggers(st.table, "delete")
        cols = list(dict.fromkeys(
            list(ti.key_cols)
            + [c for idx in ti.indexes.values() for c in idx.key_cols]
            + (list(ti.schema.names()) if fire else [])
        ))
        rs = self._qualify(st, ti, cols)
        fired_rows: list[tuple] = []
        muts: list[tuple[tuple, int, tuple | None]] = []
        index_muts: list[tuple[int, tuple, int, tuple | None]] = []
        for r in range(rs.nrows):
            if fire:
                old_map = {c: rs.columns[c][r] for c in cols}
                self._fire_triggers(
                    st.table, "delete", "before", [(None, old_map)], tx)
                fired_rows.append((None, old_map))
            row = {
                c: _coerce(rs.columns[c][r], ti.schema[c], ti.dicts.get(c), c)
                for c in cols
            }
            key = tuple(int(row[k]) for k in ti.key_cols)
            ls_id, tab_id = ti.partition_for_key(key)
            muts.append((ls_id, tab_id, key, OP_DELETE, None))
            for idx in ti.indexes.values():
                ikey = tuple(int(row[c]) for c in idx.key_cols)
                index_muts.append((idx.tablet_id, ikey, OP_DELETE, None))
        n = self._stage_all(tx, ti, muts, index_muts)
        if fire:
            self._fire_triggers(st.table, "delete", "after", fired_rows, tx)
        return n


# ---- helpers ---------------------------------------------------------------

_LIT_MASK_RE = None


def _norm_stmt(tag: str, st) -> str:
    """Literal-normalized cache key for a generated DML qualification scan.

    Numeric/date literals become runtime parameters during parameterize(),
    so masking them here lets point UPDATE/DELETE loops share one compiled
    plan (string literals stay: they are baked and already key material)."""
    global _LIT_MASK_RE
    if _LIT_MASK_RE is None:
        import re

        _LIT_MASK_RE = re.compile(r"(NumberLit|DateLit)\(value='[^']*'\)")
    return tag + ":" + _LIT_MASK_RE.sub(r"\1(value='?')", repr(st))


def apply_dict_appends(by_tab: dict, dict_appends) -> None:
    """Re-apply logged dictionary growth onto TableInfos (idempotent:
    codes are dense and append-ordered). Shared by live record
    observation (_on_applied_record) and the standby tail (ha/standby)."""
    for tab_id, col, code, s in dict_appends:
        ti = by_tab.get(tab_id)
        if ti is None:
            continue
        d = ti.dicts.get(col)
        if d is None:
            continue
        if code == len(d):
            d.encode_one(s)
        ti.logged_dict_len[col] = max(
            ti.logged_dict_len.get(col, 0), code + 1
        )


def _eval_const(node: A.Node):
    """Evaluate a literal/constant VALUES expression on the host."""
    if isinstance(node, A.NumberLit):
        t = node.value
        return float(t) if ("." in t or "e" in t or "E" in t) else int(t)
    if isinstance(node, A.StringLit):
        return node.value
    if isinstance(node, A.DateLit):
        return node.value
    if isinstance(node, A.Name) and node.parts == ("null",):
        raise SqlError("NULL values not supported in DML yet")
    if isinstance(node, A.UnaryOp) and node.op == "-":
        return -_eval_const(node.operand)
    if isinstance(node, A.BinOp):
        l, r = _eval_const(node.left), _eval_const(node.right)
        if node.op == "+":
            return l + r
        if node.op == "-":
            return l - r
        if node.op == "*":
            return l * r
        if node.op == "/":
            if r == 0:
                raise SqlError("division by zero in VALUES expression")
            return l / r
    raise SqlError(f"unsupported VALUES expression {node!r}")


def _coerce(v, dt: DataType, d: Dictionary | None, col: str):
    """Host value -> storage representation for one column."""
    if v is None:
        raise SqlError(f"NULL for column {col} not supported in DML yet")
    if dt.kind is TypeKind.VARCHAR:
        assert d is not None
        return d.encode_one(str(v))
    if dt.kind is TypeKind.DATE:
        if isinstance(v, str):
            return int(np.datetime64(v, "D").astype(np.int64))
        return int(v)
    if dt.is_decimal:
        return int(round(float(v) * dt.decimal_factor))
    if dt.is_integer:
        iv = int(v)
        if iv != v:
            raise SqlError(f"non-integer value {v!r} for column {col}")
        return iv
    if dt.is_float:
        return float(v)
    if dt.kind is TypeKind.VECTOR:
        # '[f, f, ...]' literal -> (d,) float32 tuple (hashable so the
        # MVCC row path treats it like any other cell value)
        from ..expr.compile import bind_value

        return tuple(float(x) for x in bind_value(v, dt))
    raise SqlError(f"unsupported column type {dt} for DML")


def _flashback_refs(node, out=None) -> list:
    """(name, snapshot) pairs of AS OF SNAPSHOT references in the AST."""
    import dataclasses

    if out is None:
        out = []
    if isinstance(node, A.TableRef) and node.snapshot is not None:
        if (node.name, node.snapshot) not in out:
            out.append((node.name, node.snapshot))
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            _flashback_refs(getattr(node, f.name), out)
    elif isinstance(node, (tuple, list)):
        for x in node:
            _flashback_refs(x, out)
    return out


def _tables_in_ast(node) -> set[str]:
    """All table names referenced anywhere in a statement AST."""
    import dataclasses

    out: set[str] = set()

    def walk(n):
        if isinstance(n, A.TableRef):
            out.add(n.name)
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            for f in dataclasses.fields(n):
                walk(getattr(n, f.name))
        elif isinstance(n, (tuple, list)):
            for x in n:
                walk(x)

    walk(node)
    return out
