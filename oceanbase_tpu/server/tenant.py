"""Multitenancy: resource-isolated tenants over one shared cluster.

Reference surface: observer/omt — tenants as resource-isolated units
(ObTenant worker pools, ob_th_worker.cpp:313, multi-level queues, unit
configs) with per-tenant config (ob_tenant_config_mgr.h) and the MTL
per-tenant singleton registry.

The rebuild's mapping: one shared LocalCluster (nodes, log streams, GTS,
consensus) hosts N tenants; each tenant IS a Database in shared-cluster
mode — its own schema service, catalog, plan cache, diagnostics, config,
lock manager, and TenantUnit (worker quota, memory quota, PX quota).
Tablet-id ranges are disjoint per tenant, so storage, locks and logged
dictionary appends route cleanly; applied-record observation fans out to
every tenant, each ignoring tablets it does not own (the multi-data-
source consumer registry analog). MTL: `Tenant.mtl` is the per-tenant
singleton registry."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..rootserver import RootService
from ..share.schema_service import SchemaService
from .database import Database, SqlError, TenantUnit

# disjoint tablet-id ranges per tenant (schema isolation needs no shared
# id space, but storage/locks key on tablet ids cluster-wide)
_TENANT_ID_SPAN = 10_000_000


@dataclass
class Tenant:
    tenant_id: int
    name: str
    db: Database
    # MTL analog: per-tenant singleton registry (diag, caches, services)
    mtl: dict[str, object] = field(default_factory=dict)

    def session(self):
        return self.db.session()


class TenantManager:
    """Creates and owns tenants over one shared cluster (OMT analog)."""

    def __init__(self, n_nodes: int = 3, n_ls: int = 2):
        self.cluster, sys_rs = RootService.bootstrap(
            n_nodes, n_ls, finalize=False
        )
        # one dispatcher fans applied records to every tenant's observer
        for group in self.cluster.ls_groups.values():
            for rep in group.values():
                rep.on_record = self._dispatch_record
        self.cluster.finalize()
        self._next_tenant_id = 1
        self.tenants: dict[str, Tenant] = {}

    def _dispatch_record(self, rec) -> None:
        for f in self.cluster.record_observers:
            f(rec)

    def create_tenant(self, name: str, unit: TenantUnit | None = None) -> Tenant:
        if name in self.tenants:
            raise SqlError(f"tenant {name} already exists")
        tid = self._next_tenant_id
        self._next_tenant_id += 1
        rs = RootService(self.cluster, SchemaService())
        # disjoint tablet-id range per tenant
        rs.next_tablet_id = tid * _TENANT_ID_SPAN
        db = Database(
            cluster=self.cluster, rootservice=rs,
            tenant_name=name, unit=unit,
        )
        t = Tenant(tid, name, db)
        t.mtl.update(
            audit=db.audit, plan_monitor=db.plan_monitor, ash=db.ash,
            config=db.config, plan_cache=db.plan_cache,
            lock_mgr=db.lock_mgr,
            tracer=db.tracer, flight=db.flight, long_ops=db.long_ops,
            timeline=db.timeline, sentinel=db.sentinel,
        )
        self.tenants[name] = t
        return t

    def drop_tenant(self, name: str) -> None:
        t = self.tenants.pop(name, None)
        if t is None:
            raise SqlError(f"no such tenant {name}")
        # drop the tenant's tablets from every replica and detach its
        # record observer (the LS garbage-collection analog for units)
        own = t.db._own_tablet_ids()
        for group in self.cluster.ls_groups.values():
            for rep in group.values():
                for tid in own:
                    rep.tablets.pop(tid, None)
        try:
            self.cluster.record_observers.remove(t.db._on_applied_record)
        except ValueError:
            pass
