"""Health sentinel: typed rules watching the AWR snapshot stream.

The workload repository records what happened; nothing in PR 6 *watches*
it. This module evaluates a fixed set of typed rules over each pair of
consecutive workload snapshots (the reference's diagnostic-info/alarm
analog, scoped to what the rebuild can actually measure) and emits
deduplicated, severity-tagged alerts with the triggering evidence —
digest, metric deltas, snapshot ids — into a bounded ring surfaced as
__all_virtual_alert_history and rendered by tools/health_report.py.

Rules (all pure functions of two snapshots, deterministic — the tier-1
sentinel test replays a recorded pair and asserts the exact alert set):

  digest_latency_regression — a digest's window p99 vs its trailing
      cumulative baseline (first snapshot's histogram);
  error_spike / retry_spike — window failure/retry rate over the
      statement stream;
  compile_storm — compile interference events in the window's timeline
      buckets (or new compiled-plan census entries when no timeline);
  device_cache_pressure — plan/fast/block cache evictions in window;
  tenant_starvation — one tenant's admission wait diverging from its
      peers' (or repeated worker-queue rejections) in the QoS ledger;
  fastpath_collapse — warm fast-path hit rate falling off a healthy
      baseline;
  replica_unreachable — a node's replicas went dark inside the window
      (edge-triggered on the keepalive transition);
  device_memory_pressure — sustained governor reservation-wait p99 plus
      degraded executions (OOM retries / chunked / host fallbacks) in
      the window, edge-triggered like replica_unreachable;
  storage_corruption — checksum failures detected inside the window
      (scrubber or read path); critical when corruption is sitting
      UNREPAIRED at the window end, warn when every detection was
      repaired (quarantine + rewrite/rebuild/recompute);
  cardinality_misestimate — an operator's window-average actual
      cardinality diverged >= miss_ratio x from the optimizer's
      compile-time estimate (plan-profile calibration records);
      edge-triggered per (digest, node), critical when the
      misestimated operator also tops window device time.

Evaluating the same window twice never duplicates an alert: the dedup
key is (rule, subject key, window-ending snap_id).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


def _hist_quantile(bounds, counts, q: float) -> float:
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


@dataclass(frozen=True)
class SentinelConfig:
    """Rule thresholds. Defaults are deliberately conservative — a
    sentinel that cries on every window trains DBAs to ignore it."""

    regress_ratio: float = 2.0  # window p99 >= ratio * baseline p99
    regress_critical_ratio: float = 3.0
    regress_min_execs: int = 8  # window executions
    regress_min_baseline: int = 8  # baseline executions
    error_rate: float = 0.10
    error_min_stmts: int = 10
    retry_rate: float = 0.25
    compile_storm_events: int = 10
    cache_pressure_evictions: int = 16
    starve_wait_floor_s: float = 0.01  # absolute: below this, never starved
    starve_ratio: float = 5.0  # vs the best-served peer's avg wait
    starve_min_queued: int = 4  # rejections alone can prove starvation
    fastpath_floor: float = 0.5  # window hit rate at/below = collapse
    fastpath_baseline: float = 0.8  # only off a healthy baseline
    fastpath_min_stmts: int = 20
    # device_memory_pressure: reservation-wait p99 above the floor AND
    # degraded executions (OOM retries / chunked / host fallbacks) in
    # the window
    govr_wait_p99_s: float = 0.05
    govr_min_degraded: int = 1
    # storage_corruption: checksum failures in window to fire at all
    corruption_min_failures: int = 1
    # cardinality_misestimate: window miss factor + executions floor
    miss_ratio: float = 8.0
    miss_min_execs: int = 5


@dataclass
class Alert:
    alert_id: int
    ts: float
    rule: str
    severity: str  # warn | critical
    key: str  # subject (digest / tenant / "" for engine-wide)
    summary: str
    first_snap_id: int
    last_snap_id: int
    evidence: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "alert_id": self.alert_id, "ts": self.ts, "rule": self.rule,
            "severity": self.severity, "key": self.key,
            "summary": self.summary, "first_snap_id": self.first_snap_id,
            "last_snap_id": self.last_snap_id, "evidence": self.evidence,
        }


def _sys_delta(first: dict, last: dict, name: str) -> float:
    return (last.get("sysstat", {}).get(name, 0)
            - first.get("sysstat", {}).get(name, 0))


def _rule_digest_regression(first, last, cfg, out) -> None:
    f_by = {s["digest"]: s for s in first.get("summary", ())}
    for s in last.get("summary", ()):
        f = f_by.get(s["digest"])
        if f is None:
            continue
        base_execs = f.get("exec_count", 0)
        win_execs = s.get("exec_count", 0) - base_execs
        if (win_execs < cfg.regress_min_execs
                or base_execs < cfg.regress_min_baseline):
            continue
        bounds = s.get("hist_bounds", ())
        f_counts = f.get("hist_counts", ())
        base_p99 = _hist_quantile(f.get("hist_bounds", bounds),
                                  f_counts, 0.99)
        win_counts = [
            max(0, c - (f_counts[i] if i < len(f_counts) else 0))
            for i, c in enumerate(s.get("hist_counts", ()))
        ]
        win_p99 = _hist_quantile(bounds, win_counts, 0.99)
        if base_p99 <= 0.0 or win_p99 < cfg.regress_ratio * base_p99:
            continue
        ratio = win_p99 / base_p99
        out.append({
            "rule": "digest_latency_regression",
            "severity": ("critical"
                         if ratio >= cfg.regress_critical_ratio
                         else "warn"),
            "key": s["digest"],
            "summary": (f"p99 {base_p99 * 1e6:.0f}us -> "
                        f"{win_p99 * 1e6:.0f}us ({ratio:.1f}x) over "
                        f"{win_execs} window executions"),
            "evidence": {
                "digest": s["digest"],
                "baseline_p99_s": base_p99,
                "window_p99_s": win_p99,
                "ratio": round(ratio, 3),
                "window_execs": win_execs,
                "baseline_execs": base_execs,
            },
        })


def _rule_error_retry(first, last, cfg, out) -> None:
    stmts = _sys_delta(first, last, "sql statements")
    if stmts < cfg.error_min_stmts:
        return
    fails = _sys_delta(first, last, "sql fail count")
    rate = fails / stmts
    if rate >= cfg.error_rate:
        out.append({
            "rule": "error_spike",
            "severity": "critical" if rate >= 2 * cfg.error_rate else "warn",
            "key": "",
            "summary": (f"{fails:.0f}/{stmts:.0f} statements failed "
                        f"({100 * rate:.0f}%) in window"),
            "evidence": {"window_stmts": stmts, "window_fails": fails,
                         "fail_rate": round(rate, 4)},
        })
    f_by = {s["digest"]: s for s in first.get("summary", ())}
    retries = sum(
        max(0, s.get("retry_count", 0)
            - f_by.get(s["digest"], {}).get("retry_count", 0))
        for s in last.get("summary", ())
    )
    rrate = retries / stmts
    if rrate >= cfg.retry_rate:
        out.append({
            "rule": "retry_spike",
            "severity": "warn",
            "key": "",
            "summary": (f"{retries} statement retries over {stmts:.0f} "
                        f"statements ({100 * rrate:.0f}%) in window"),
            "evidence": {"window_stmts": stmts, "window_retries": retries,
                         "retry_rate": round(rrate, 4)},
        })


def _window_timeline(first, last) -> list[dict]:
    t0, t1 = first.get("ts", 0.0), last.get("ts", 0.0)
    # bucket ts is the floored bucket START: include the bucket the
    # window starts inside, else short windows see zero buckets
    bucket_s = last.get("timeline_meta", {}).get("bucket_s", 1.0)
    return [b for b in last.get("timeline", ())
            if t0 - bucket_s < b.get("ts", -1.0 - bucket_s) <= t1]


def _rule_compile_storm(first, last, cfg, out) -> None:
    buckets = _window_timeline(first, last)
    events = sum(b.get("compile_events", 0) for b in buckets)
    compile_s = sum(b.get("compile_s", 0.0) for b in buckets)
    if not buckets:
        # old dumps without a timeline: fall back to census churn
        f_plans = {r["name"] for r in first.get("census", ())
                   if r.get("kind") == "compiled_plan"}
        events = sum(1 for r in last.get("census", ())
                     if r.get("kind") == "compiled_plan"
                     and r["name"] not in f_plans)
    if events < cfg.compile_storm_events:
        return
    out.append({
        "rule": "compile_storm",
        "severity": "warn",
        "key": "",
        "summary": (f"{events} compile events "
                    f"({compile_s:.2f}s of XLA compiles) in window"),
        "evidence": {"compile_events": events,
                     "compile_s": round(compile_s, 4)},
    })


def _census_block_evictions(snap: dict) -> int:
    for r in snap.get("census", ()):
        if r.get("kind") == "block_cache":
            for part in str(r.get("detail", "")).split(","):
                if part.startswith("evictions="):
                    try:
                        return int(part.split("=", 1)[1])
                    except ValueError:
                        return 0
    return 0


def _rule_cache_pressure(first, last, cfg, out) -> None:
    ev = (_sys_delta(first, last, "plan cache eviction")
          + _sys_delta(first, last, "plan cache fast eviction"))
    bev = max(0, _census_block_evictions(last)
              - _census_block_evictions(first))
    total = ev + bev
    if total < cfg.cache_pressure_evictions:
        return
    out.append({
        "rule": "device_cache_pressure",
        "severity": "warn",
        "key": "",
        "summary": (f"{total:.0f} cache evictions in window "
                    f"(plan/fast {ev:.0f}, block {bev})"),
        "evidence": {"plan_evictions": ev, "block_evictions": bev},
    })


def _rule_tenant_starvation(first, last, cfg, out) -> None:
    q0, q1 = first.get("qos", {}), last.get("qos", {})
    win = {}
    for name, t1 in q1.items():
        t0 = q0.get(name, {})
        admitted = t1.get("admitted", 0) - t0.get("admitted", 0)
        rejected = t1.get("rejected", 0) - t0.get("rejected", 0)
        wait_s = t1.get("wait_s", 0.0) - t0.get("wait_s", 0.0)
        queued = admitted + rejected
        if queued <= 0:
            continue
        win[name] = (admitted, rejected, wait_s, wait_s / queued)
    if not win:
        return
    for name, (admitted, rejected, wait_s, avg_wait) in sorted(win.items()):
        peers = [w[3] for n, w in win.items() if n != name and w[0] > 0]
        starved_by_wait = (
            avg_wait >= cfg.starve_wait_floor_s
            and peers
            and avg_wait >= cfg.starve_ratio * max(min(peers), 1e-9)
        )
        starved_by_reject = rejected >= cfg.starve_min_queued
        if not (starved_by_wait or starved_by_reject):
            continue
        best_peer = min(peers) if peers else 0.0
        out.append({
            "rule": "tenant_starvation",
            "severity": ("critical" if starved_by_wait and starved_by_reject
                         else "warn"),
            "key": name,
            "summary": (f"tenant {name}: avg admission wait "
                        f"{avg_wait * 1e3:.1f}ms "
                        f"(best peer {best_peer * 1e3:.1f}ms), "
                        f"{rejected} rejections in window"),
            "evidence": {
                "tenant": name,
                "window_admitted": admitted,
                "window_rejected": rejected,
                "window_wait_s": round(wait_s, 6),
                "avg_wait_s": round(avg_wait, 6),
                "best_peer_avg_wait_s": round(best_peer, 6),
            },
        })


def _rule_fastpath_collapse(first, last, cfg, out) -> None:
    wh = _sys_delta(first, last, "plan cache fast hit")
    wm = _sys_delta(first, last, "plan cache fast miss")
    if wh + wm < cfg.fastpath_min_stmts:
        return
    s0 = first.get("sysstat", {})
    bh, bm = s0.get("plan cache fast hit", 0), s0.get(
        "plan cache fast miss", 0)
    if bh + bm < cfg.fastpath_min_stmts:
        return
    base_rate = bh / (bh + bm)
    win_rate = wh / (wh + wm)
    if base_rate < cfg.fastpath_baseline or win_rate > cfg.fastpath_floor:
        return
    out.append({
        "rule": "fastpath_collapse",
        "severity": "warn",
        "key": "",
        "summary": (f"fast-path hit rate {100 * win_rate:.0f}% in window "
                    f"(baseline {100 * base_rate:.0f}%)"),
        "evidence": {"window_hits": wh, "window_misses": wm,
                     "window_rate": round(win_rate, 4),
                     "baseline_rate": round(base_rate, 4)},
    })


def _rule_replica_unreachable(first, last, cfg, out) -> None:
    """A node's replicas went dark inside the window: the keepalive
    majority vote (cluster.unreachable_nodes, recorded per snapshot as
    ls_replica rows) flipped to unreachable between the two snapshots.
    Only the TRANSITION alerts — a node that stays down does not re-fire
    every window; recovery resets the edge so a flapping node alerts on
    each new outage."""
    rep0 = {(r["ls_id"], r["node"]): r for r in first.get("ls_replica", [])}
    down_nodes: dict[int, list[dict]] = {}
    for r in last.get("ls_replica", []):
        if not r.get("unreachable"):
            continue
        prev = rep0.get((r["ls_id"], r["node"]))
        if prev is not None and prev.get("unreachable"):
            continue  # was already down at the window start
        down_nodes.setdefault(r["node"], []).append(r)
    for node, reps in sorted(down_nodes.items()):
        led = sorted(r["ls_id"] for r in reps if r["role"] == "LEADER")
        out.append({
            "rule": "replica_unreachable",
            "severity": "critical" if led else "warn",
            "key": f"node{node}",
            "summary": (f"node {node} unreachable (keepalive majority "
                        f"vote); {len(reps)} replicas dark"
                        + (f", was leading ls {led}" if led else "")),
            "evidence": {
                "node": node,
                "ls_ids": sorted(r["ls_id"] for r in reps),
                "leader_ls_ids": led,
                "max_lag_us": max(r["lag_us"] for r in reps),
            },
        })


def _rule_device_memory_pressure(first, last, cfg, out) -> None:
    """Device memory stayed scarce across the window: the governor's
    reservation-wait p99 is over the floor at the window end AND
    statements actually degraded (OOM retries, chunked re-plans, host
    fallbacks or reservation rejects) inside it. Edge-triggered like
    replica_unreachable: a window that STARTS pressured doesn't re-fire
    — pressure must clear before the next alert."""
    g1 = last.get("governor") or {}
    if not g1:
        return
    degraded = int(
        _sys_delta(first, last, "device OOM retries")
        + _sys_delta(first, last, "stmt degraded chunked")
        + _sys_delta(first, last, "stmt degraded host")
        + _sys_delta(first, last, "device memory rejects"))

    def pressured(snap) -> bool:
        g = snap.get("governor") or {}
        return float(g.get("wait_p99_s", 0.0)) >= cfg.govr_wait_p99_s

    if not pressured(last) or degraded < cfg.govr_min_degraded:
        return
    if pressured(first):
        return  # was already pressured at the window start
    host = int(_sys_delta(first, last, "stmt degraded host"))
    out.append({
        "rule": "device_memory_pressure",
        "severity": "critical" if host else "warn",
        "key": "",
        "summary": (f"device memory pressure: reservation-wait p99 "
                    f"{g1.get('wait_p99_s', 0.0) * 1e3:.1f}ms, {degraded} "
                    f"degraded executions in window"),
        "evidence": {
            "wait_p99_s": g1.get("wait_p99_s", 0.0),
            "degraded": degraded,
            "oom_retries": int(_sys_delta(first, last,
                                          "device OOM retries")),
            "chunked": int(_sys_delta(first, last, "stmt degraded chunked")),
            "host": host,
            "rejects": int(_sys_delta(first, last, "device memory rejects")),
            "reserved": g1.get("reserved", 0),
            "effective_budget": g1.get("effective_budget", 0),
            "shrink": g1.get("shrink", 1.0),
        },
    })


def _rule_storage_corruption(first, last, cfg, out) -> None:
    """Checksum failures surfaced inside the window — from any verified
    read path or a scrub pass. Severity is the repair state at the
    window end: corruption that is sitting UNREPAIRED (a backup with no
    source to regenerate from, a replica that could not rebuild) is
    critical; fully-repaired detections (quarantine + rewrite/rebuild/
    recompute) warn. Edge-triggered by construction: the rule fires on
    the failure-count DELTA, so a window with no new detections is
    silent no matter how much history sysstat carries."""
    fails = int(_sys_delta(first, last, "checksum failures"))
    if fails < cfg.corruption_min_failures:
        return
    i0 = first.get("integrity") or {}
    i1 = last.get("integrity") or {}
    unrepaired = max(0, int(i1.get("unrepaired", 0))
                     - int(i0.get("unrepaired", 0)))
    quarantined = int(_sys_delta(first, last, "quarantined files"))
    repairs = int(_sys_delta(first, last, "replica repairs"))
    by_class = i1.get("by_class") or {}
    bad_classes = sorted(
        c for c, v in by_class.items() if v.get("failures", 0) > 0)
    out.append({
        "rule": "storage_corruption",
        "severity": "critical" if unrepaired else "warn",
        "key": "",
        "summary": (f"{fails} checksum failures in window "
                    f"({quarantined} quarantined, {repairs} replica "
                    f"repairs); "
                    + (f"{unrepaired} UNREPAIRED" if unrepaired
                       else "all repaired")),
        "evidence": {
            "window_failures": fails,
            "window_quarantined": quarantined,
            "window_replica_repairs": repairs,
            "unrepaired": unrepaired,
            "classes": bad_classes,
            "scrub_passes": int(i1.get("passes", 0)),
        },
    })


def _rule_cardinality_misestimate(first, last, cfg, out) -> None:
    """An operator's WINDOW-average actual cardinality diverged >=
    miss_ratio x from the optimizer's compile-time estimate, with
    enough window executions (profiled samples) to trust the average.
    Reads the plan-profile calibration records workload snapshots embed
    (engine/plan_profile.OperatorProfileStore.snapshot). Edge-triggered
    per (digest, node): a record that was ALREADY misestimated at the
    window start stays silent — one alert per divergence, not one per
    window — until a recompile/eviction resets its estimate. Critical
    when the misestimated operator also tops window device time: the
    worst estimate is sitting on the hottest operator."""
    from ..engine.plan_profile import miss_factor

    p0 = (first.get("plan_profile") or {}).get("digests") or {}
    p1 = (last.get("plan_profile") or {}).get("digests") or {}
    if not p1:
        return

    def window(digest, nid, rec):
        r0 = (p0.get(digest) or {}).get(nid) or {}
        execs = (int(rec.get("executions", 0))
                 - int(r0.get("executions", 0)))
        rows = int(rec.get("rows", 0)) - int(r0.get("rows", 0))
        dev = (float(rec.get("device_us", 0.0))
               - float(r0.get("device_us", 0.0)))
        return execs, rows, dev

    hot = None  # (digest, nid) with the most window device time
    hot_dev = 0.0
    cand = []
    for digest, nodes in p1.items():
        for nid, rec in nodes.items():
            execs, rows, dev = window(digest, nid, rec)
            if dev > hot_dev:
                hot_dev, hot = dev, (digest, nid)
            if execs < cfg.miss_min_execs:
                continue
            est = rec.get("est_rows", 0)
            avg = rows / execs
            mf = miss_factor(est, avg)
            if mf < cfg.miss_ratio:
                continue
            r0 = (p0.get(digest) or {}).get(nid)
            if (r0 is not None
                    and int(r0.get("executions", 0)) >= cfg.miss_min_execs
                    and miss_factor(r0.get("est_rows", 0),
                                    r0.get("avg_rows", 0.0))
                    >= cfg.miss_ratio):
                continue  # was already misestimated at the window start
            cand.append((digest, nid, rec, execs, est, avg, mf, dev))
    for digest, nid, rec, execs, est, avg, mf, dev in cand:
        tops = (digest, nid) == hot
        out.append({
            "rule": "cardinality_misestimate",
            "severity": "critical" if tops else "warn",
            "key": f"{digest}#{nid}",
            "summary": (
                f"node {nid} ({rec.get('op_kind', '?')}) of "
                f"{digest[:60]}: est {int(est)} vs actual {avg:.0f} rows "
                f"({mf:.1f}x miss over {execs} profiled execs)"
                + (", tops window device time" if tops else "")),
            "evidence": {
                "digest": digest,
                "node_id": int(nid) if str(nid).lstrip("-").isdigit()
                else nid,
                "op_kind": rec.get("op_kind", ""),
                "est_rows": int(est),
                "window_avg_rows": avg,
                "miss_factor": mf,
                "window_executions": execs,
                "window_device_us": dev,
                "tops_window_device_time": tops,
            },
        })


_RULES = (
    _rule_digest_regression,
    _rule_error_retry,
    _rule_compile_storm,
    _rule_cache_pressure,
    _rule_tenant_starvation,
    _rule_fastpath_collapse,
    _rule_replica_unreachable,
    _rule_device_memory_pressure,
    _rule_storage_corruption,
    _rule_cardinality_misestimate,
)


def evaluate_window(first: dict, last: dict,
                    config: SentinelConfig | None = None) -> list[dict]:
    """Pure rule pass over one snapshot pair. Returns plain alert dicts
    (no ids, no dedup) in deterministic order — tools/health_report.py
    replays recorded dumps through this offline."""
    cfg = config or SentinelConfig()
    out: list[dict] = []
    for rule in _RULES:
        rule(first, last, cfg, out)
    for a in out:
        a["first_snap_id"] = first.get("snap_id", 0)
        a["last_snap_id"] = last.get("snap_id", 0)
    return out


class HealthSentinel:
    """Bounded, deduplicating alert ring over the live snapshot stream.
    WorkloadRepository calls observe() after every capture."""

    def __init__(self, capacity: int = 256,
                 config: SentinelConfig | None = None, clock=time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self.config = config or SentinelConfig()
        self.capacity = max(int(capacity), 8)
        self._alerts: list[Alert] = []
        self._seen: set[tuple] = set()
        self._seen_order: list[tuple] = []
        self._next_id = 1
        self.enabled = True

    def observe(self, first: dict, last: dict) -> list[Alert]:
        """Evaluate one window; record and return only NEW alerts (the
        (rule, key, last snap) dedup makes re-evaluation idempotent)."""
        if not self.enabled or first is None or last is None:
            return []
        found = evaluate_window(first, last, self.config)
        fresh: list[Alert] = []
        now = self._clock()
        with self._lock:
            for a in found:
                dk = (a["rule"], a["key"], a["last_snap_id"])
                if dk in self._seen:
                    continue
                self._seen.add(dk)
                self._seen_order.append(dk)
                alert = Alert(
                    alert_id=self._next_id, ts=now, rule=a["rule"],
                    severity=a["severity"], key=a["key"],
                    summary=a["summary"],
                    first_snap_id=a["first_snap_id"],
                    last_snap_id=a["last_snap_id"],
                    evidence=a["evidence"],
                )
                self._next_id += 1
                self._alerts.append(alert)
                fresh.append(alert)
            while len(self._alerts) > self.capacity:
                self._alerts.pop(0)
            # the dedup memory is bounded too (it outlives the ring on
            # purpose — an alert evicted by ring pressure must not
            # resurrect on a re-evaluation of its window)
            while len(self._seen_order) > self.capacity * 4:
                self._seen.discard(self._seen_order.pop(0))
        return fresh

    def alerts(self) -> list[Alert]:
        with self._lock:
            return list(self._alerts)

    def set_capacity(self, n: int) -> None:
        with self._lock:
            self.capacity = max(int(n), 8)
            while len(self._alerts) > self.capacity:
                self._alerts.pop(0)
