"""Workload repository: digest-keyed statement summaries, table/column
access heat, device-residency census, and bounded AWR-style snapshots.

Reference surface: OceanBase's statement-summary / workload-repository
machinery (gv$sql_audit is a ring that evicts under load; the summary
tables aggregate per statement *digest* forever) plus Oracle-AWR-style
periodic snapshots that make "before vs. after a tuning change" a diff
instead of a guess.

Three collectors, one snapshot engine:

  * StatementSummaryRegistry — every completed statement folds into the
    per-digest rolling stats (exec/fail/retry counts, latency histogram,
    phase sums, transfer bytes). The digest is the kind-marked normalized
    text sql/parser.fast_normalize already produces for the fast path, so
    warm serving statements pay ZERO extra tokenization. Surfaced as
    __all_virtual_statement_summary.
  * TableAccessStats — per-table scan/row/DAS counts and per-column
    filter/join/group/sort reference counts, attributed at plan-compile
    time (Executor.prepare builds an access profile once per compiled
    plan; each execution folds the precomputed profile — no plan walks on
    the hot path). Surfaced as __all_virtual_table_access_stat.
  * device_census() — what actually lives on the device right now:
    per-table device-cache bytes, compiled-plan entries with hit counts
    and pow2 batch-bucket shapes, the fast text tier, and the block
    cache. Surfaced as __all_virtual_device_census.

WorkloadRepository captures all three plus a sysstat counter snapshot
into a bounded ring (SNAPSHOT WORKLOAD statement, or periodic via
workload_snapshot_interval on an injectable clock); tools/awr_report.py
diffs two snapshots into a report + machine-readable advisor block (the
data contract ROADMAP item 3's layout advisor consumes).

Hot-path discipline (same rules as server/diag.py): `enabled` early
returns, the per-statement step is one buffered tuple append under a
per-session lock (the real folding runs cache-hot in batch drains; see
_SessionFold), and the drain measures its own cost (stmt summary fold
ns) so the overhead is itself a sysstat line.
"""

from __future__ import annotations

import threading
import time
import weakref
from bisect import bisect_left
from dataclasses import dataclass, field

from ..share.metrics import DEFAULT_BUCKETS, Histogram

# all digest histograms share the default bucket bounds; accumulators
# bucket locally by index and merge counts on flush
_HIST_BOUNDS = DEFAULT_BUCKETS

# column-role indices in ColumnAccess.counts / access-profile entries
ROLE_FILTER, ROLE_JOIN, ROLE_GROUP, ROLE_SORT = 0, 1, 2, 3


@dataclass(slots=True)
class StatementSummary:
    """Rolling per-digest aggregate of every completed execution.

    exec/fail/retry counts and total/max elapsed are EXACT (folded per
    statement). The detail fields — row counts, hit counts, phase sums,
    transfer bytes, histogram — accumulate from the accumulators'
    sampled statements (sampled_count of them; digests with at
    most _SessionFold.SAMPLE_ALL consecutive executions are fully
    sampled and thus exact). as_dict() scales the sampled sums back to
    whole-population estimates by exec_count/sampled_count; histogram
    counts stay RAW because quantiles are scale-invariant and windowed
    deltas (awr_report) must subtract cleanly."""

    digest: str
    stmt_type: str = ""
    exec_count: int = 0
    fail_count: int = 0
    retry_count: int = 0
    sampled_count: int = 0
    rows_returned: int = 0
    affected_rows: int = 0
    fast_path_count: int = 0
    batched_count: int = 0
    cache_hit_count: int = 0
    total_elapsed_s: float = 0.0
    max_elapsed_s: float = 0.0
    hist: Histogram = None  # per-digest latency distribution (sampled)
    fastparse_s: float = 0.0
    bind_s: float = 0.0
    dispatch_s: float = 0.0
    fetch_s: float = 0.0
    compile_s: float = 0.0
    transfer_bytes: int = 0
    max_device_bytes: int = 0
    max_peak_bytes: int = 0
    first_seen: float = 0.0
    last_seen: float = 0.0
    # recency sequence for cold-digest eviction (cheaper than an
    # OrderedDict move_to_end on every fold)
    seq: int = 0

    def as_dict(self) -> dict:
        h = self.hist
        # scale sampled sums to whole-population estimates; k == 1.0
        # (fully sampled) for low-traffic digests, so those are exact
        k = (self.exec_count / self.sampled_count
             if self.sampled_count else 0.0)
        return {
            "digest": self.digest,
            "stmt_type": self.stmt_type,
            "exec_count": self.exec_count,
            "fail_count": self.fail_count,
            "retry_count": self.retry_count,
            "sampled_count": self.sampled_count,
            "rows_returned": round(self.rows_returned * k),
            "affected_rows": round(self.affected_rows * k),
            "fast_path_count": round(self.fast_path_count * k),
            "batched_count": round(self.batched_count * k),
            "cache_hit_count": round(self.cache_hit_count * k),
            "total_elapsed_s": self.total_elapsed_s,
            "max_elapsed_s": self.max_elapsed_s,
            "p50_s": h.p50, "p95_s": h.p95, "p99_s": h.p99,
            "hist_bounds": list(h.bounds),
            "hist_counts": list(h.counts),
            "fastparse_s": self.fastparse_s * k,
            "bind_s": self.bind_s * k,
            "dispatch_s": self.dispatch_s * k,
            "fetch_s": self.fetch_s * k,
            "compile_s": self.compile_s * k,
            "transfer_bytes": round(self.transfer_bytes * k),
            "max_device_bytes": self.max_device_bytes,
            "max_peak_bytes": self.max_peak_bytes,
            "first_seen": self.first_seen,
            "last_seen": self.last_seen,
        }


# session-local accumulation state: one plain list per digest, indexed
# by the _A_* slots below — list-index adds in the drain loop are
# cheaper than attribute writes on a stats object. The first block
# (through _A_RETRIES) is EXACT (every statement lands in it); the
# second block is fed by the sampled detail tuples and is scaled by
# exec/sampled on read (see StatementSummary.as_dict).
(_A_TYPE, _A_N, _A_ELAPSED, _A_MAX, _A_FAILS, _A_RETRIES,
 _A_SAMPLED, _A_BUCKETS, _A_ROWS, _A_AFFECTED, _A_CACHE, _A_FAST,
 _A_BATCHED, _A_FASTPARSE, _A_BIND, _A_DISPATCH, _A_FETCH, _A_COMPILE,
 _A_TRANSFER, _A_MAXDEV, _A_MAXPEAK) = range(21)


def _new_state(stmt_type: str) -> list:
    return [stmt_type, 0, 0.0, 0.0, 0, 0,
            0, {}, 0, 0, 0, 0, 0,
            0.0, 0.0, 0.0, 0.0, 0.0, 0, 0, 0]


class _SessionFold:
    """Per-session statement-summary accumulator — the serving hot path.

    Per statement, only what MUST be exact is folded inline: execution
    count, elapsed sum/max, failures, retries — a handful of adds on
    this one object, all under the session's own uncontended lock. The
    expensive detail (histogram bucket, row counts, hit flags, the
    profiler's phase sums) is recorded for a 1-in-(SAMPLE_MASK+1)
    SAMPLE of each digest run, buffered as a tuple and batch-folded every
    DRAIN_AT samples; readers scale the sampled sums back up by the
    exact execution count. Short runs (the first SAMPLE_ALL statements
    after a digest change) are always sampled, so low-traffic digests
    — DDL, one-off analytics, a failing statement under diagnosis —
    report exact detail, while the hot serving digest pays the sampled
    price: folding every statement's ~20 detail fields is what used to
    cost 3-4% of serving throughput, all of it cache-cold at statement
    completion because the statement's own work just evicted it.

    The drain folds into a session-LOCAL digest map (no shared lock);
    the shared registry is touched only when a reader forces a flush
    (snapshot / virtual table / workload capture), the local map
    outgrows its cap, or the session is garbage-collected. Exact
    counts are exact at every read point because readers flush all
    live accumulators first. Each sampled tuple keeps a reference to
    the statement's QueryProfile and reads the phase sums at drain
    time — profiles are per-statement objects, and late reads also
    catch fetch time the client spent on the result after
    completion."""

    __slots__ = ("_reg", "_lock", "_sample", "_reported", "digest",
                 "stmt_type", "n", "elapsed_sum", "elapsed_max", "fails",
                 "retries", "sampled", "_buf", "_states", "__weakref__")

    DRAIN_AT = 64       # buffered sample tuples per batch fold
    SAMPLE_ALL = 8      # first statements of a run are always sampled
    SAMPLE_MASK = 15    # then 1-in-(SAMPLE_MASK+1)
    MAX_LOCAL_DIGESTS = 128  # push to the shared map past this

    def __init__(self, reg: "StatementSummaryRegistry"):
        self._reg = reg
        self._lock = threading.Lock()
        self._sample = 0    # doubles as the exact lifetime fold count
        self._reported = 0  # folds already reported to sysstat
        self._buf = []     # sampled (digest, elapsed, rows, ...) tuples
        self._states = {}  # digest -> _new_state list
        self._zero_run("", "")

    def _zero_run(self, digest: str, stmt_type: str) -> None:
        self.digest = digest
        self.stmt_type = stmt_type
        self.n = 0
        self.elapsed_sum = 0.0
        self.elapsed_max = 0.0
        self.fails = 0
        self.retries = 0
        self.sampled = 0

    def fold(self, digest: str, stmt_type: str, elapsed_s: float, err: str,
             retry_cnt: int, rs, batched: bool, prof) -> None:
        with self._lock:
            if digest != self.digest:
                if self.n:
                    self._push_run()
                self._zero_run(digest, stmt_type)
            self.n = n = self.n + 1
            self.elapsed_sum += elapsed_s
            if elapsed_s > self.elapsed_max:
                self.elapsed_max = elapsed_s
            if err:
                self.fails += 1
            if retry_cnt:
                self.retries += retry_cnt
            self._sample = sn = self._sample + 1
            if n <= self.SAMPLE_ALL or (sn & self.SAMPLE_MASK) == 0:
                # the result-set reads (memoized nrows, two attributes)
                # happen only on sampled statements
                self.sampled += 1
                b = self._buf
                if rs is not None:
                    b.append((digest, elapsed_s, rs.nrows, rs.affected,
                              rs.plan_cache_hit, batched, prof))
                else:
                    b.append((digest, elapsed_s, 0, 0, False, batched,
                              prof))
                if len(b) >= self.DRAIN_AT:
                    self._drain()

    def _push_run(self) -> None:
        """Fold the current digest run's exact counters into the local
        state map. Caller holds self._lock."""
        states = self._states
        st = states.get(self.digest)
        if st is None:
            st = states[self.digest] = _new_state(self.stmt_type)
        elif not st[_A_TYPE]:
            # state was created by a drained sample tuple (which doesn't
            # carry the statement type) before the run itself landed
            st[_A_TYPE] = self.stmt_type
        st[_A_N] += self.n
        st[_A_ELAPSED] += self.elapsed_sum
        if self.elapsed_max > st[_A_MAX]:
            st[_A_MAX] = self.elapsed_max
        st[_A_FAILS] += self.fails
        st[_A_RETRIES] += self.retries
        st[_A_SAMPLED] += self.sampled

    def _drain(self) -> None:
        """Batch-fold the buffered sample tuples into the local digest
        map. Caller holds self._lock. Times itself (whole batch, two
        timer reads) into the `stmt summary fold ns` sysstat line."""
        buf = self._buf
        if not buf:
            if self._sample != self._reported:
                self._reg._note_drain(self._sample - self._reported, 0)
                self._reported = self._sample
            return
        t0 = time.perf_counter_ns()
        self._buf = []
        states = self._states
        for (digest, elapsed_s, rows, affected, cache_hit, batched,
             prof) in buf:
            st = states.get(digest)
            if st is None:
                st = states[digest] = _new_state("")
            bk = st[_A_BUCKETS]
            i = bisect_left(_HIST_BOUNDS, elapsed_s)
            bk[i] = bk.get(i, 0) + 1
            if rows:
                st[_A_ROWS] += rows
            if affected:
                st[_A_AFFECTED] += affected
            if cache_hit:
                st[_A_CACHE] += 1
            if batched:
                st[_A_BATCHED] += 1
            if prof is not None:
                if prof.fast_path_hit:
                    st[_A_FAST] += 1
                st[_A_FASTPARSE] += prof.fastparse_s
                st[_A_BIND] += prof.bind_s
                st[_A_DISPATCH] += prof.dispatch_s
                st[_A_FETCH] += prof.fetch_s
                st[_A_COMPILE] += prof.compile_s
                st[_A_TRANSFER] += prof.h2d_bytes + prof.d2h_bytes
                if prof.device_bytes > st[_A_MAXDEV]:
                    st[_A_MAXDEV] = prof.device_bytes
                if prof.peak_bytes > st[_A_MAXPEAK]:
                    st[_A_MAXPEAK] = prof.peak_bytes
        if len(states) > self.MAX_LOCAL_DIGESTS:
            self._states = {}
            self._reg._merge_states(states)
        folds = self._sample - self._reported
        self._reported = self._sample
        self._reg._note_drain(folds, time.perf_counter_ns() - t0)

    def flush(self) -> None:
        with self._lock:
            if self.n:
                self._push_run()
                self._zero_run(self.digest, self.stmt_type)
            self._drain()
            if self._states:
                states, self._states = self._states, {}
                self._reg._merge_states(states)

    def __del__(self):
        # a dropped session flushes its tail so no completed statement
        # is ever lost (guarded: interpreter teardown order is arbitrary)
        try:
            self.flush()
        except Exception:  # noqa: BLE001
            pass


class StatementSummaryRegistry:
    """Digest -> StatementSummary, bounded by ob_sql_stat_max_digests
    with cold-digest (least-recently-merged) eviction. Sessions fold
    through per-session accumulators (`session_acc`); every reader
    (snapshot / VT / workload capture) flushes live accumulators first,
    so reads are exact without a shared lock on the serving path."""

    def __init__(self, max_digests: int = 256, clock=time.time,
                 metrics=None):
        self._lock = threading.Lock()
        self._map: dict[str, StatementSummary] = {}
        self._accs: list = []  # weakrefs to live _SessionFold
        self._clock = clock
        self._metrics = metrics
        self._seq = 0
        self.max_digests = max_digests
        self.evictions = 0
        self.enabled = True

    def session_acc(self) -> _SessionFold:
        acc = _SessionFold(self)
        with self._lock:
            self._accs.append(weakref.ref(acc))
        return acc

    def _note_drain(self, n: int, ns: int) -> None:
        """Account one accumulator drain: n statements folded, ns spent.
        The drain self-meters as a whole batch — two timer reads per
        DRAIN_AT statements instead of two per statement."""
        m = self._metrics
        if m is not None and m.enabled:
            m.bulk(adds=(("stmt summary folds", n),
                         ("stmt summary fold ns", ns)))

    def _merge_states(self, states: dict) -> None:
        """Merge a session-local digest map into the shared one. Called
        by accumulators holding their own lock; lock order is always
        acc -> registry -> metrics."""
        evicted = 0
        with self._lock:
            now = self._clock()
            mp = self._map
            for digest, st in states.items():
                s = mp.get(digest)
                if s is None:
                    if len(mp) >= self.max_digests:
                        evicted += self._evict_cold()
                    s = mp[digest] = StatementSummary(
                        digest, stmt_type=st[_A_TYPE],
                        hist=Histogram(digest), first_seen=now)
                elif not s.stmt_type and st[_A_TYPE]:
                    s.stmt_type = st[_A_TYPE]
                s.last_seen = now
                self._seq += 1
                s.seq = self._seq
                s.exec_count += st[_A_N]
                s.total_elapsed_s += st[_A_ELAPSED]
                if st[_A_MAX] > s.max_elapsed_s:
                    s.max_elapsed_s = st[_A_MAX]
                nsamp = st[_A_SAMPLED]
                s.sampled_count += nsamp
                h = s.hist
                hc = h.counts
                for i, c in st[_A_BUCKETS].items():
                    hc[i] += c
                h.count += nsamp
                if st[_A_N]:
                    # sampled share of the exact elapsed sum (the drain
                    # doesn't keep a separate per-sample time sum)
                    h.sum_s += st[_A_ELAPSED] * nsamp / st[_A_N]
                s.rows_returned += st[_A_ROWS]
                s.affected_rows += st[_A_AFFECTED]
                s.fail_count += st[_A_FAILS]
                s.retry_count += st[_A_RETRIES]
                s.cache_hit_count += st[_A_CACHE]
                s.fast_path_count += st[_A_FAST]
                s.batched_count += st[_A_BATCHED]
                s.fastparse_s += st[_A_FASTPARSE]
                s.bind_s += st[_A_BIND]
                s.dispatch_s += st[_A_DISPATCH]
                s.fetch_s += st[_A_FETCH]
                s.compile_s += st[_A_COMPILE]
                s.transfer_bytes += st[_A_TRANSFER]
                if st[_A_MAXDEV] > s.max_device_bytes:
                    s.max_device_bytes = st[_A_MAXDEV]
                if st[_A_MAXPEAK] > s.max_peak_bytes:
                    s.max_peak_bytes = st[_A_MAXPEAK]
        if evicted:
            m = self._metrics
            if m is not None and m.enabled:
                m.add("stmt summary evictions", evicted)

    def flush_all(self) -> None:
        """Pull every live session accumulator into the digest map (and
        prune accumulators whose sessions were collected)."""
        with self._lock:
            refs = list(self._accs)
        dead = 0
        for r in refs:
            acc = r()
            if acc is None:
                dead += 1
                continue
            acc.flush()
        if dead:
            with self._lock:
                self._accs = [r for r in self._accs if r() is not None]

    def _evict_cold(self) -> int:
        """Drop least-recently-merged digests down to the cap (called
        under self._lock, only when a NEW digest arrives at capacity —
        rare, so an O(n) recency scan beats per-merge LRU bookkeeping)."""
        n = 0
        while len(self._map) >= self.max_digests:
            cold = min(self._map.values(), key=lambda s: s.seq)
            del self._map[cold.digest]
            n += 1
        self.evictions += n
        return n

    def set_max_digests(self, n: int) -> None:
        with self._lock:
            self.max_digests = n
            if len(self._map) > n:
                over = len(self._map) - n
                self.evictions += over
                for s in sorted(self._map.values(),
                                key=lambda s: s.seq)[:over]:
                    del self._map[s.digest]

    def peak_estimate(self, digest: str) -> int:
        """Measured peak device working set of a digest (bytes), 0 when
        the digest is cold. The memory governor sizes admission-time
        reservations from this — the feedback loop that turns measured
        QueryProfile peaks into next-execution estimates. Reads the
        merged map only (no accumulator flush): this sits on the
        admission path of every read, and an estimate that lags one
        drain window is still conservative enough — cold digests fall
        back to ob_governor_cold_reserve anyway."""
        with self._lock:
            s = self._map.get(digest)
            return int(s.max_peak_bytes) if s is not None else 0

    def snapshot(self) -> list[dict]:
        self.flush_all()
        with self._lock:
            return [s.as_dict() for s in self._map.values()]

    def reset(self) -> None:
        self.flush_all()  # pendings die with the map, not after it
        with self._lock:
            self._map.clear()


# --------------------------------------------------------------------------
# table/column access heat
# --------------------------------------------------------------------------


# stride-sample cap for key_evidence (same budget as share/stats.py)
_EVIDENCE_CAP = 1 << 16


@dataclass(slots=True)
class ColumnAccess:
    column: str
    # [filter, join, group, sort] reference counts (ROLE_* indices)
    counts: list = field(default_factory=lambda: [0, 0, 0, 0])
    # measured key-skew evidence (key_evidence): sampled distinct count and
    # the sample fraction held by the single heaviest value, cached against
    # the snapshot Table identity so a memtable flush re-measures
    ndv: float = 0.0
    top_frac: float = 0.0
    evidence_snap: object = None


@dataclass(slots=True)
class TableAccess:
    table: str
    scans: int = 0
    rows_read: int = 0
    das_lookups: int = 0
    das_rows: int = 0
    proj_hits: int = 0
    proj_misses: int = 0
    cols: dict = field(default_factory=dict)  # name -> ColumnAccess


@dataclass(slots=True)
class _ResolvedScan:
    """One scan of one prepared plan with its stat objects pre-resolved:
    the per-execution fold touches only these references (no dict/catalog
    lookups on the hot path)."""

    tstat: TableAccess
    rows: int
    has_proj: bool
    proj_hit: bool
    cols: tuple  # of (ColumnAccess, role_index)


class TableAccessStats:
    """Per-table/column access accounting, fed by two producers: compiled
    plans (Executor.prepare builds the profile, Session._execute_entry
    folds it per execution) and the host-side DAS index/PK route
    (record_das). `epoch` invalidates the per-prepared resolved memo
    after a reset."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tables: dict[str, TableAccess] = {}
        self.enabled = True
        self.epoch = 0

    def resolve(self, profile) -> tuple:
        """Map an access profile — tuple of (table, scan_rows, has_proj,
        proj_hit, ((col, role), ...)) — to live stat objects, creating
        them on first sight. Called once per (prepared plan, epoch)."""
        out = []
        with self._lock:
            for table, rows, has_proj, proj_hit, cols in profile:
                t = self._tables.get(table)
                if t is None:
                    t = self._tables[table] = TableAccess(table)
                rcols = []
                for col, role in cols:
                    c = t.cols.get(col)
                    if c is None:
                        c = t.cols[col] = ColumnAccess(col)
                    rcols.append((c, role))
                out.append(_ResolvedScan(t, rows, has_proj, proj_hit,
                                         tuple(rcols)))
        return tuple(out)

    def fold_resolved(self, resolved: tuple) -> None:
        with self._lock:
            for r in resolved:
                t = r.tstat
                t.scans += 1
                t.rows_read += r.rows
                if r.proj_hit:
                    t.proj_hits += 1
                elif r.has_proj:
                    t.proj_misses += 1
                for c, role in r.cols:
                    c.counts[role] += 1

    def record_das(self, table: str, rows: int) -> None:
        """Host-side DAS index/PK lookup (server _index_route): counted
        separately from device scans — the advisor treats a das-served
        table differently from one paying full device materialization."""
        if not self.enabled:
            return
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = TableAccess(table)
            t.das_lookups += 1
            t.das_rows += rows

    def key_evidence(self, table: str, col: str,
                     table_obj=None) -> tuple[float, float] | None:
        """Measured join-key skew evidence: (sampled NDV, fraction of the
        sample held by the single heaviest value) for `col` of `table`,
        from a stride sample of the live snapshot column. Returns None
        when the column is absent, non-numeric, or empty. Cached against
        the snapshot Table identity — a memtable flush installs a new
        Table object, so evidence re-measures exactly when data moved."""
        if table_obj is None:
            return None
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = TableAccess(table)
            c = t.cols.get(col)
            if c is None:
                c = t.cols[col] = ColumnAccess(col)
            if c.evidence_snap is table_obj:
                return (c.ndv, c.top_frac) if c.ndv > 0 else None
        import numpy as np

        ndv, top_frac = 0.0, 0.0
        arr = getattr(table_obj, "data", {}).get(col)
        if arr is not None and arr.dtype.kind in "iufb":
            nn = np.asarray(arr)
            valid = getattr(table_obj, "valid", {}).get(col)
            if valid is not None:
                nn = nn[np.asarray(valid, dtype=bool)]
            if len(nn) > _EVIDENCE_CAP:
                nn = nn[:: len(nn) // _EVIDENCE_CAP]
            if len(nn):
                _, counts = np.unique(nn, return_counts=True)
                ndv = float(len(counts))
                top_frac = float(counts.max()) / float(len(nn))
        with self._lock:
            c.ndv, c.top_frac, c.evidence_snap = ndv, top_frac, table_obj
        return (ndv, top_frac) if ndv > 0 else None

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "table": t.table,
                    "scans": t.scans,
                    "rows_read": t.rows_read,
                    "das_lookups": t.das_lookups,
                    "das_rows": t.das_rows,
                    "proj_hits": t.proj_hits,
                    "proj_misses": t.proj_misses,
                    "columns": [
                        {
                            "column": c.column,
                            "filter_count": c.counts[ROLE_FILTER],
                            "join_count": c.counts[ROLE_JOIN],
                            "group_count": c.counts[ROLE_GROUP],
                            "sort_count": c.counts[ROLE_SORT],
                        }
                        for c in t.cols.values()
                    ],
                }
                for t in self._tables.values()
            ]

    def reset(self) -> None:
        with self._lock:
            self._tables.clear()
            self.epoch += 1


# --------------------------------------------------------------------------
# device-residency and compile census
# --------------------------------------------------------------------------


def _dev_nbytes(o, depth: int = 0) -> int:
    """Best-effort device bytes of one batch-cache value: arrays report
    nbytes; tuples/dicts of arrays sum; ColumnBatch-shaped objects walk
    cols/valid/sel. Accounting must never fail a census."""
    if o is None or depth > 4:
        return 0
    nb = getattr(o, "nbytes", None)
    if nb is not None:
        try:
            return int(nb)
        except Exception:  # noqa: BLE001
            return 0
    if isinstance(o, (tuple, list)):
        return sum(_dev_nbytes(v, depth + 1) for v in o)
    if isinstance(o, dict):
        return sum(_dev_nbytes(v, depth + 1) for v in o.values())
    total = 0
    for attr in ("cols", "valid", "sel"):
        v = getattr(o, attr, None)
        if v is not None:
            total += _dev_nbytes(v, depth + 1)
    return total


def device_census(db) -> list[dict]:
    """What the executor/device currently holds, as flat rows of
    {kind, name, detail, entries, hits, bytes}:

      table_device  — per-table device-cache footprint (batch cache)
      compiled_plan — one row per logical plan-cache entry (hits, pow2
                      batch-bucket shapes, memoized input bytes)
      fast_text     — one row per text-tier entry (hits, stmt type)
      plan_cache    — tier totals + lifetime batched-compile count
      block_cache   — decoded-micro-block cache residency
    """
    rows: list[dict] = []
    ex = db.engine.executor
    by_table: dict[str, list] = {}
    for key, val in list(ex._batch_cache.items()):
        by_table.setdefault(key[0], [0, 0])
        acc = by_table[key[0]]
        acc[0] += 1
        acc[1] += _dev_nbytes(val)
    for name in sorted(by_table):
        entries, nbytes = by_table[name]
        rows.append({"kind": "table_device", "name": name, "detail": "",
                     "entries": entries, "hits": 0, "bytes": nbytes})
    logical, fast = db.plan_cache.census()
    tot_hits = 0
    for e in logical:
        tot_hits += e["hits"]
        detail = ""
        if e["buckets"]:
            detail = "buckets=" + ",".join(str(b) for b in e["buckets"])
        rows.append({"kind": "compiled_plan", "name": e["norm_key"][:120],
                     "detail": detail, "entries": 1, "hits": e["hits"],
                     "bytes": e["dev_bytes"]})
    for e in fast:
        rows.append({"kind": "fast_text", "name": e["text_key"][:120],
                     "detail": e["stmt_type"], "entries": 1,
                     "hits": e["hits"], "bytes": 0})
    rows.append({"kind": "plan_cache", "name": "totals",
                 "detail": f"batched_compiles={ex.batched_compiles}",
                 "entries": len(logical) + len(fast), "hits": tot_hits,
                 "bytes": sum(e["dev_bytes"] for e in logical)})
    bc = db.block_cache
    rows.append({"kind": "block_cache", "name": "block_cache",
                 "detail": (f"misses={bc.misses},"
                            f"evictions={bc.evictions},"
                            f"capacity={bc.capacity_bytes}"),
                 "entries": len(bc), "hits": bc.hits,
                 "bytes": bc.bytes_used})
    return rows


# --------------------------------------------------------------------------
# snapshot engine
# --------------------------------------------------------------------------


def ls_replica_health(db) -> list[dict]:
    """Per-replica reachability + apply-watermark lag, from the cluster
    keepalives (ha/detect.py) — the replica_unreachable sentinel rule's
    evidence. Empty when the cluster runs without keepalives (pure unit
    harnesses)."""
    cluster = getattr(db, "cluster", None)
    if cluster is None or not getattr(cluster, "keepalives", None):
        return []
    dead = cluster.unreachable_nodes()
    now_ts = cluster.gts.current()
    rows = []
    for ls_id, group in sorted(cluster.ls_groups.items()):
        for node, rep in sorted(group.items()):
            wm = rep.apply_watermark
            rows.append({
                "ls_id": ls_id, "node": node,
                "role": rep.palf.role.name,
                "unreachable": int(node in dead),
                "watermark": wm,
                "lag_us": max(0, now_ts - wm),
            })
    return rows


def build_snapshot(db, snap_id: int, ts: float) -> dict:
    tl = getattr(db, "timeline", None)
    return {
        "snap_id": snap_id,
        "ts": ts,
        "summary": db.stmt_summary.snapshot(),
        "access": db.access.snapshot(),
        "census": device_census(db),
        "sysstat": db.metrics.counters_snapshot(),
        # serving saturation view (share/timeline.py): time-sliced device
        # busy/queue buckets + the cumulative per-tenant QoS ledger — what
        # awr_report's saturation section and the health sentinel consume
        "timeline": tl.snapshot() if tl is not None else [],
        "timeline_meta": tl.meta() if tl is not None else {},
        "qos": tl.qos_totals() if tl is not None else {},
        # replica serving health (keepalive reachability + watermark lag):
        # the replica_unreachable sentinel rule's input
        "ls_replica": ls_replica_health(db),
        # device-memory governor ledger (reservation pressure + shrink
        # state): the device_memory_pressure sentinel rule's input
        "governor": (db.governor.stats()
                     if getattr(db, "governor", None) is not None else {}),
        # storage-scrub state (storage/scrub.py): pass/quarantine/repair
        # tallies — the storage_corruption sentinel rule's input
        "integrity": (db.scrubber.stats()
                      if getattr(db, "scrubber", None) is not None else {}),
        # host-tax ledger (share/gap_ledger.py): cumulative per-digest
        # phase walls + recent chip-idle windows — awr_report's "Host tax
        # (window)" section diffs two of these
        "host_tax": (db.host_tax.snapshot()
                     if getattr(db, "host_tax", None) is not None else {}),
        # operator calibration store (engine/plan_profile.py): cumulative
        # per-(digest, node) est-vs-actual records — awr_report's "Hot
        # operators (window)" section and the cardinality_misestimate
        # sentinel rule diff two of these
        "plan_profile": (db.plan_profiler.store.snapshot()
                         if getattr(db, "plan_profiler", None) is not None
                         else {}),
    }


class WorkloadRepository:
    """Bounded ring of workload snapshots. Triggered by the SNAPSHOT
    WORKLOAD statement or, when workload_snapshot_interval > 0, by the
    statement-completion path checking maybe_auto() (the clock is
    injectable, so tests drive periodic capture without sleeping)."""

    def __init__(self, capacity: int = 16, clock=time.time):
        self._lock = threading.Lock()
        self._snaps: list[dict] = []
        self._clock = clock
        self._next_id = 1
        self._last_auto: float | None = None
        self.capacity = capacity
        self.interval_s = 0.0  # 0 = periodic capture off
        # called with (previous snapshot, new snapshot) after each
        # capture — the health sentinel's evaluation hook. Exceptions are
        # swallowed: a watching rule must never fail the statement whose
        # completion triggered the capture.
        self.on_snapshot = None

    def take(self, db) -> dict:
        with self._lock:
            snap_id = self._next_id
            self._next_id += 1
        snap = build_snapshot(db, snap_id, self._clock())
        with self._lock:
            prev = self._snaps[-1] if self._snaps else None
            self._snaps.append(snap)
            while len(self._snaps) > self.capacity:
                self._snaps.pop(0)
        cb = self.on_snapshot
        if cb is not None and prev is not None:
            try:
                cb(prev, snap)
            except Exception:  # noqa: BLE001
                pass
        return snap

    def maybe_auto(self, db) -> dict | None:
        """Periodic capture: at most one snapshot per interval, stamped
        from the injected clock. Callers pre-check interval_s > 0 so the
        disabled path costs one attribute read."""
        now = self._clock()
        with self._lock:
            if (self._last_auto is not None
                    and now - self._last_auto < self.interval_s):
                return None
            self._last_auto = now
        return self.take(db)

    def snapshots(self) -> list[dict]:
        with self._lock:
            return list(self._snaps)

    def set_capacity(self, n: int) -> None:
        with self._lock:
            self.capacity = n
            while len(self._snaps) > n:
                self._snaps.pop(0)

    def dump(self, path: str) -> int:
        """Write every held snapshot as one JSON document (the
        tools/awr_report.py input format). Returns the snapshot count."""
        import json

        snaps = self.snapshots()
        with open(path, "w") as f:
            json.dump({"snapshots": snaps}, f)
        return len(snaps)
