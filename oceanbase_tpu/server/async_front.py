"""Async MySQL front door: every connection multiplexed on one event
loop, statement execution on a small bounded worker pool.

The threaded front end (mysql_front.MySqlFrontend) spends one OS thread
per connection — at hundreds of sessions the thread stacks, scheduler
churn and GIL handoffs become the serving ceiling long before the
device does. This server keeps the SAME protocol surface (it reuses
mysql_front's response builders payload-for-payload, so result sets are
byte-identical) but splits the work the way the reference's libeasy
network frontend splits it from the tenant worker pools:

  * protocol work — packet framing, greeting/login, TLS upgrade,
    COM_STMT_PREPARE/CLOSE/RESET bookkeeping, PING — runs on the
    asyncio event loop: O(connections) costs only file descriptors.
  * statement execution — COM_QUERY / COM_STMT_EXECUTE, the parts that
    parse, take locks, and dispatch to the device — runs on a bounded
    ThreadPoolExecutor (`mysql_async_workers` config), which is ALSO
    the statement concurrency the continuous-batching scheduler
    (server/batcher.py) sees: the pool pushes concurrent statements
    into the dispatch gate where they coalesce into batched device
    dispatches instead of 256 threads trampling each other.

Backpressure is end-to-end: a slow client parks its connection
coroutine in `await writer.drain()` (no worker held), and statements
beyond the pool width queue in the executor — surfaced by the batcher
queue-depth / gate-wait telemetry, not by thread explosion.

One detail is version-sensitive: Python 3.10 has no
StreamWriter.start_tls, so the mid-handshake SSLRequest upgrade uses
loop.start_tls on the raw transport and rewires the stream pair by
hand, mirroring what 3.11's start_tls does.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor

from .database import Database
from .mysql_front import (
    _err_packet,
    _ok_packet,
    build_greeting,
    check_login,
    is_ssl_request,
    make_salt,
    query_payloads,
    stmt_execute_payloads,
    stmt_prepare_payloads,
    stmt_reset_payload,
)


class AsyncMySqlFrontend:
    """Selector-loop MySQL listener: same wire surface as
    MySqlFrontend, connections no longer cost a thread each.

    The loop runs on one daemon thread (start() returns once the port
    is bound); `users` follows MySqlFrontend's contract (None = open
    door via the privilege manager, plaintext map reduced to stage-2
    hashes immediately)."""

    def __init__(self, db: Database, host: str = "127.0.0.1",
                 port: int = 0, users: dict[str, str] | None = None,
                 ssl_context=None, workers: int | None = None):
        self.db = db
        if users is not None:
            from ..share.privilege import stage2_hash

            users = {u: stage2_hash(p) for u, p in users.items()}
        self.users = users
        self.ssl_context = ssl_context
        self.host = host
        self._port_req = port
        self.port: int | None = None
        if workers is None:
            try:
                workers = int(db.config["mysql_async_workers"])
            except Exception:  # noqa: BLE001 — config-less Database stub
                workers = 8
        self.workers = max(int(workers), 1)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server = None
        self._pool: ThreadPoolExecutor | None = None
        self._thread: threading.Thread | None = None
        self._startup_err: BaseException | None = None
        # rolling-restart drain state: while _draining is set the
        # listener is closed and statements on surviving connections are
        # shed with a retryable ER_SERVER_SHUTDOWN instead of entering
        # the worker pool; _inflight counts statements already submitted
        # (those are allowed to finish — drain() waits on them)
        self._draining = threading.Event()
        self._flight_lock = threading.Lock()
        self._inflight = 0
        self.shed = 0

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "AsyncMySqlFrontend":
        ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(ready,), daemon=True,
            name="mysql-async-loop")
        self._thread.start()
        ready.wait()
        if self._startup_err is not None:
            raise self._startup_err
        return self

    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful drain for a zero-cold-start rolling restart: stop
        accepting connections (listener closed), let statements already
        in the worker pool finish, and shed anything newly queued with a
        retryable ER_SERVER_SHUTDOWN (1053) so the client's router
        redrives it on a peer. Returns {"inflight", "shed"}; resume()
        reopens the same port once the node is back."""
        import time

        self._draining.set()
        loop, srv = self._loop, self._server
        if loop is not None and srv is not None:
            try:
                loop.call_soon_threadsafe(srv.close)
            except RuntimeError:
                pass  # loop already closed
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._flight_lock:
                n = self._inflight
            if n == 0:
                break
            time.sleep(0.005)
        with self._flight_lock:
            n = self._inflight
        return {"inflight": n, "shed": self.shed}

    def resume(self) -> None:
        """Reopen the listener on the SAME port after a drain (the
        restarted node rejoins the serving set at its old address) and
        lift the statement gate."""
        loop = self._loop
        if loop is None or self.port is None:
            raise RuntimeError("resume() before start()")

        async def _reopen():
            self._server = await asyncio.start_server(
                self._serve, self.host, self.port, backlog=512)

        asyncio.run_coroutine_threadsafe(_reopen(), loop).result(timeout=10)
        self._draining.clear()

    def stop(self) -> None:
        loop, thread = self._loop, self._thread
        if loop is None or thread is None:
            return
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass  # loop already closed
        thread.join(timeout=10)
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def _run(self, ready: threading.Event) -> None:
        loop = self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="mysql-async")
        try:
            self._server = loop.run_until_complete(
                asyncio.start_server(self._serve, self.host,
                                     self._port_req, backlog=512))
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as e:  # noqa: BLE001 — surfaced by start()
            self._startup_err = e
            ready.set()
            loop.close()
            return
        ready.set()
        try:
            loop.run_forever()
        finally:
            try:
                self._server.close()
                loop.run_until_complete(self._server.wait_closed())
                tasks = asyncio.all_tasks(loop)
                for t in tasks:
                    t.cancel()
                if tasks:
                    loop.run_until_complete(
                        asyncio.gather(*tasks, return_exceptions=True))
            finally:
                loop.close()

    # ------------------------------------------------------------ protocol
    async def _execute(self, fn, *args):
        """Worker-pool dispatch behind the drain gate: a draining node
        sheds the statement (retryable 1053, no worker touched) instead
        of queueing work it has promised to finish."""
        if self._draining.is_set():
            self.shed += 1
            return [_err_packet(
                1053, "server shutting down: retry on a peer")]
        with self._flight_lock:
            self._inflight += 1
        import time as _t

        t0 = _t.perf_counter()

        def timed():
            # worker-pool handoff wait: wall between the event loop
            # posting the statement and a pool thread picking it up —
            # host tax the statement ledger (which opens inside fn)
            # cannot see. Folded post-hoc against the statement's digest
            # as frontend ingress ("wire read").
            queued_s = _t.perf_counter() - t0
            out = fn(*args)
            sess_obj = args[0] if args else None
            ht = getattr(self.db, "host_tax", None)
            dg = getattr(sess_obj, "_last_digest", "")
            if ht is not None and ht.enabled and dg and queued_s > 0.0:
                ht.fold_extra(dg, "wire read", queued_s)
            return out

        try:
            return await self._loop.run_in_executor(self._pool, timed)
        finally:
            with self._flight_lock:
                self._inflight -= 1

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        db, loop = self.db, self._loop
        sess = None
        seq = 0
        # id -> [pieces, nparams, last-bound param types]; the command
        # loop is sequential per connection, so loop-side PREPARE/RESET
        # and pool-side EXECUTE never race on this dict
        stmts: dict[int, list] = {}
        next_stmt = [1]

        async def read_packet() -> bytes:
            nonlocal seq
            head = await reader.readexactly(4)
            n = int.from_bytes(head[:3], "little")
            seq = (head[3] + 1) & 0xFF
            return await reader.readexactly(n) if n else b""

        def send(payloads) -> None:
            nonlocal seq
            buf = bytearray()
            for p in payloads:
                buf += len(p).to_bytes(3, "little")
                buf.append(seq)
                buf += p
                seq = (seq + 1) & 0xFF
            writer.write(bytes(buf))

        try:
            salt = make_salt()
            send([build_greeting(salt, self.ssl_context is not None)])
            await writer.drain()
            login = await read_packet()
            if self.ssl_context is not None and is_ssl_request(login):
                # mid-handshake TLS upgrade; 3.10 has no
                # StreamWriter.start_tls, so rewire like 3.11's does.
                # The packet sequence continues across the upgrade.
                await writer.drain()
                transport = writer.transport
                protocol = transport.get_protocol()
                new_tr = await loop.start_tls(
                    transport, protocol, self.ssl_context,
                    server_side=True)
                writer._transport = new_tr
                protocol._transport = new_tr
                login = await read_packet()
            user = check_login(db, self.users, login, salt)
            if user is None:
                send([_err_packet(1045,
                                  "Access denied (bad credentials)")])
                await writer.drain()
                return
            sess = db.session(user=user)
            send([_ok_packet()])
            await writer.drain()
            while True:
                seq = 0
                pkt = await read_packet()
                if not pkt:
                    return
                cmd = pkt[0]
                if cmd == 0x01:  # COM_QUIT
                    return
                if cmd in (0x0E, 0x02):  # COM_PING / COM_INIT_DB
                    send([_ok_packet()])
                elif cmd == 0x03:  # COM_QUERY -> worker pool
                    send(await self._execute(
                        query_payloads, sess, pkt[1:].decode()))
                elif cmd == 0x16:  # COM_STMT_PREPARE (protocol-only)
                    send(stmt_prepare_payloads(pkt[1:].decode(), stmts,
                                               next_stmt))
                elif cmd == 0x17:  # COM_STMT_EXECUTE -> worker pool
                    send(await self._execute(
                        stmt_execute_payloads, sess, pkt, stmts))
                elif cmd == 0x19:  # COM_STMT_CLOSE (no response)
                    if len(pkt) >= 5:
                        stmts.pop(int.from_bytes(pkt[1:5], "little"),
                                  None)
                    continue
                elif cmd == 0x1A:  # COM_STMT_RESET
                    send([stmt_reset_payload(pkt, stmts)])
                else:
                    send([_err_packet(1047, "unsupported command")])
                # write backpressure: a slow client parks THIS coroutine
                # here — no worker thread, no unbounded send buffer
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            # drop the engine session FIRST (rollback + workload-repo
            # flush on disconnect) — same contract as the threaded serve
            if sess is not None:
                try:
                    sess.close()
                except Exception:  # noqa: BLE001 — disconnect best-effort
                    pass
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
