"""MySQL wire-protocol front door: stock clients connect and run SQL.

Reference surface: the MySQL command layer — connection handshake and
COM_QUERY dispatch (src/observer/mysql/obmp_query.cpp:53, obmp_connect),
prepared statements (obmp_stmt_prepare.cpp / obmp_stmt_execute.cpp),
packet codecs (deps/oblib/src/rpc/obmysql). The rebuild speaks classic
protocol v10 / CLIENT_PROTOCOL_41:

  greeting -> login (mysql_native_password verified against the user
  table) -> OK
  COM_QUERY         -> text resultset (typed column defs, EOF, rows, EOF)
                       or OK (DML/DDL with affected-rows) or ERR
  COM_STMT_PREPARE  -> stmt id + param count ('?' placeholders)
  COM_STMT_EXECUTE  -> binary resultset (typed rows, NULL bitmap); bound
                       parameters substitute as literals and ride the
                       plan cache's parameterization, so re-executions
                       reuse the compiled XLA artifact
  COM_STMT_CLOSE / COM_PING / COM_INIT_DB / COM_QUIT

Each connection binds one DbSession (transactions span statements on the
same connection, like a real server thread). Column defs carry real
types (LONGLONG / DOUBLE / VAR_STRING) derived from the result arrays.
"""

from __future__ import annotations

import hashlib
import socket
import socketserver
import struct
import threading
import time as _time

import numpy as np

from .database import Database, SqlError

CLIENT_PROTOCOL_41 = 0x0200
CLIENT_CONNECT_WITH_DB = 0x0008
CLIENT_SSL = 0x0800
CLIENT_SECURE_CONNECTION = 0x8000

MYSQL_TYPE_LONGLONG = 8
MYSQL_TYPE_DOUBLE = 5
MYSQL_TYPE_VAR_STRING = 253


def native_password_scramble(password: str, salt: bytes) -> bytes:
    """mysql_native_password CLIENT side:
    SHA1(pw) XOR SHA1(salt + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def verify_native_password(stage2_hex: str, auth: bytes, salt: bytes) -> bool:
    """SERVER side: the stored credential is only the stage-2 hash
    SHA1(SHA1(pw)) (privilege.stage2_hash) — recover stage1 from the
    client scramble as auth XOR SHA1(salt + stage2) and check
    SHA1(stage1) == stage2. The plaintext never exists server-side."""
    import hmac

    if not stage2_hex:
        return len(auth) == 0
    if len(auth) != 20:
        return False
    h2 = bytes.fromhex(stage2_hex)
    h3 = hashlib.sha1(salt + h2).digest()
    h1 = bytes(a ^ b for a, b in zip(auth, h3))
    return hmac.compare_digest(hashlib.sha1(h1).digest(), h2)


def _lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + n.to_bytes(2, "little")
    if n < 1 << 24:
        return b"\xfd" + n.to_bytes(3, "little")
    return b"\xfe" + n.to_bytes(8, "little")


def _lenenc_str(s: bytes) -> bytes:
    return _lenenc_int(len(s)) + s


class _Conn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def read_packet(self) -> bytes:
        head = self._read_n(4)
        length = int.from_bytes(head[:3], "little")
        self.seq = (head[3] + 1) & 0xFF
        return self._read_n(length)

    def _read_n(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def send_packet(self, payload: bytes) -> None:
        head = len(payload).to_bytes(3, "little") + bytes([self.seq])
        self.seq = (self.seq + 1) & 0xFF
        self.sock.sendall(head + payload)

    def reset_seq(self) -> None:
        self.seq = 0


def _ok_packet(affected: int = 0, info: bytes = b"") -> bytes:
    return (
        b"\x00" + _lenenc_int(affected) + _lenenc_int(0)
        + (0x0002).to_bytes(2, "little")  # SERVER_STATUS_AUTOCOMMIT
        + (0).to_bytes(2, "little") + info
    )


def _eof_packet() -> bytes:
    return b"\xfe" + (0).to_bytes(2, "little") + (0x0002).to_bytes(2, "little")


def _err_packet(code: int, msg: str) -> bytes:
    return (
        b"\xff" + code.to_bytes(2, "little") + b"#HY000"
        + msg.encode()[:400]
    )


def _coldef(name: str, mysql_type: int = MYSQL_TYPE_VAR_STRING) -> bytes:
    return (
        _lenenc_str(b"def") + _lenenc_str(b"") + _lenenc_str(b"")
        + _lenenc_str(b"") + _lenenc_str(name.encode())
        + _lenenc_str(name.encode())
        + b"\x0c" + (33).to_bytes(2, "little")  # utf8
        + (255).to_bytes(4, "little")
        + bytes([mysql_type])
        + (0).to_bytes(2, "little") + b"\x00" + b"\x00\x00"
    )


def _col_mysql_type(col) -> int:
    """Real wire type from the host result array (the typed-resultset
    surface obmp_query builds from ObField types)."""
    a = np.asarray(col)
    if np.issubdtype(a.dtype, np.integer) or a.dtype == np.bool_:
        return MYSQL_TYPE_LONGLONG
    if np.issubdtype(a.dtype, np.floating):
        return MYSQL_TYPE_DOUBLE
    return MYSQL_TYPE_VAR_STRING


def _cell(v) -> bytes:
    if v is None:
        return b"\xfb"
    if isinstance(v, float) and v != v:  # NaN surfaces SQL NULL
        return b"\xfb"
    if isinstance(v, (np.floating, float)):
        return _lenenc_str(repr(float(v)).encode())
    if isinstance(v, (np.integer, int)):
        return _lenenc_str(str(int(v)).encode())
    return _lenenc_str(str(v).encode())


# --------------------------------------------------------------------------
# Response builders shared by BOTH front ends (threaded MySqlFrontend here,
# AsyncMySqlFrontend in async_front.py). Each returns the response as an
# ordered list of packet payloads — framing/sequencing belongs to the
# transport — so the two servers emit byte-identical result sets.

def _split_placeholders(sql: str) -> list[str]:
    """SQL split at '?' placeholders outside quoted regions ('...',
    "...", `...`) and comments (-- to EOL, /* */) — a '?' inside any
    of those is literal text, and miscounting here shifts every
    later COM_STMT_EXECUTE substitution by one."""
    pieces, cur = [], []
    quote = None  # "'", '"' or '`' while inside that quoted region
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if quote is not None:
            cur.append(ch)
            if ch == quote:
                # doubled-quote escape stays inside the region
                if i + 1 < n and sql[i + 1] == quote:
                    cur.append(quote)
                    i += 1
                else:
                    quote = None
        elif ch in ("'", '"', "`"):
            quote = ch
            cur.append(ch)
        elif ch == "-" and i + 1 < n and sql[i + 1] == "-" and (
            i + 2 >= n or sql[i + 2] in " \t\n"
        ):
            # MySQL comment syntax: '--' must be followed by
            # whitespace (or EOL) — `x=x--1` is double negation
            j = sql.find("\n", i)
            j = n if j < 0 else j
            cur.append(sql[i:j])
            i = j - 1
        elif ch == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            j = n if j < 0 else j + 2
            cur.append(sql[i:j])
            i = j - 1
        elif ch == "?":
            pieces.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    pieces.append("".join(cur))
    return pieces


def _decode_params(pkt: bytes, nparams: int,
                   prev_types: list[int] | None) -> tuple[list, list[int]]:
    """Binary parameter block of COM_STMT_EXECUTE. Returns
    (values, types); `prev_types` supplies the types when the driver
    sets new_params_bound_flag=0 (every re-execution)."""
    if nparams == 0:
        # bitmap/flag/types are OMITTED entirely for param-less stmts
        return [], []
    off = 1 + 4 + 1 + 4  # cmd, stmt id, flags, iteration count
    nb = (nparams + 7) // 8
    null_bitmap = pkt[off:off + nb]
    off += nb
    new_bound = pkt[off]
    off += 1
    types: list[int] = []
    if new_bound:
        for _ in range(nparams):
            types.append(pkt[off] | (pkt[off + 1] << 8))
            off += 2
    elif prev_types is not None:
        types = prev_types
    else:
        types = [MYSQL_TYPE_VAR_STRING] * nparams

    def lenenc():
        nonlocal off
        b0 = pkt[off]
        off += 1
        if b0 < 251:
            n = b0
        elif b0 == 0xFC:
            n = int.from_bytes(pkt[off:off + 2], "little")
            off += 2
        elif b0 == 0xFD:
            n = int.from_bytes(pkt[off:off + 3], "little")
            off += 3
        else:
            n = int.from_bytes(pkt[off:off + 8], "little")
            off += 8
        s = pkt[off:off + n]
        off += n
        return s

    out = []
    for i in range(nparams):
        if null_bitmap[i // 8] & (1 << (i % 8)):
            out.append(None)
            continue
        t = types[i] & 0xFF
        if t == 1:  # TINY
            out.append(int.from_bytes(
                pkt[off:off + 1], "little", signed=True))
            off += 1
        elif t == 2:  # SHORT
            out.append(int.from_bytes(
                pkt[off:off + 2], "little", signed=True))
            off += 2
        elif t == 3:  # LONG
            out.append(int.from_bytes(
                pkt[off:off + 4], "little", signed=True))
            off += 4
        elif t == 8:  # LONGLONG
            out.append(int.from_bytes(
                pkt[off:off + 8], "little", signed=True))
            off += 8
        elif t == 4:  # FLOAT
            out.append(struct.unpack_from("<f", pkt, off)[0])
            off += 4
        elif t == 5:  # DOUBLE
            out.append(struct.unpack_from("<d", pkt, off)[0])
            off += 8
        else:  # strings, decimals, dates: length-encoded text
            out.append(lenenc().decode())
    return out, types


def _literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, int):
        return str(v)
    s = str(v).replace("'", "''")
    return f"'{s}'"


def _fold_wire(sess, phase: str, seconds: float) -> None:
    """Host-tax attribution for wall spent OUTSIDE the statement ledger
    (the ledger closed when sess.sql returned): result encode / packet
    work rides the statement's digest aggregate via fold_extra, which
    adds to both the phase and the digest e2e so digest-level
    conservation still holds."""
    ht = getattr(sess.db, "host_tax", None)
    dg = getattr(sess, "_last_digest", "")
    if ht is not None and ht.enabled and dg and seconds > 0.0:
        ht.fold_extra(dg, phase, seconds)


def query_payloads(sess, sql: str) -> list[bytes]:
    """COM_QUERY: text resultset (typed column defs, EOF, rows, EOF),
    or OK (DML/DDL with affected-rows), or ERR."""
    try:
        rs = sess.sql(sql)
    except Exception as e:  # SqlError, parse errors, resolver errors
        return [_err_packet(
            getattr(e, "code", 1064), f"{type(e).__name__}: {e}")]
    if not rs.names:
        return [_ok_packet(affected=rs.affected)]
    tw = _time.perf_counter()
    cols = [rs.columns[n] for n in rs.names]
    out = [_lenenc_int(len(rs.names))]
    for n, c in zip(rs.names, cols):
        out.append(_coldef(n, _col_mysql_type(c)))
    out.append(_eof_packet())
    for i in range(rs.nrows):
        out.append(b"".join(_cell(c[i]) for c in cols))
    out.append(_eof_packet())
    _fold_wire(sess, "wire write", _time.perf_counter() - tw)
    return out


def stmt_prepare_payloads(sql: str, stmts: dict, next_stmt: list) -> list[bytes]:
    """COM_STMT_PREPARE: register the pieces under a fresh statement id
    and answer COM_STMT_PREPARE_OK (+ param defs when any)."""
    pieces = _split_placeholders(sql)
    nparams = len(pieces) - 1
    sid = next_stmt[0]
    next_stmt[0] += 1
    stmts[sid] = [pieces, nparams, None]
    # COM_STMT_PREPARE_OK: status, stmt id, 0 columns (deferred to
    # execute), param count, filler, warnings
    out = [
        b"\x00" + sid.to_bytes(4, "little")
        + (0).to_bytes(2, "little")
        + nparams.to_bytes(2, "little")
        + b"\x00" + (0).to_bytes(2, "little")
    ]
    for _ in range(nparams):
        out.append(_coldef("?", MYSQL_TYPE_VAR_STRING))
    if nparams:
        out.append(_eof_packet())
    return out


def stmt_execute_payloads(sess, pkt: bytes, stmts: dict) -> list[bytes]:
    """COM_STMT_EXECUTE: binary resultset (typed rows, NULL bitmap).
    Bound parameters substitute as literals and ride the plan cache's
    parameterization, so re-executions reuse the compiled artifact."""
    tr = _time.perf_counter()
    sid = int.from_bytes(pkt[1:5], "little")
    entry = stmts.get(sid)
    if entry is None:
        return [_err_packet(1243, "unknown statement id")]
    pieces, nparams, prev_types = entry
    try:
        params, types_used = _decode_params(pkt, nparams, prev_types)
    except (IndexError, struct.error):
        return [_err_packet(1210, "malformed execute packet")]
    entry[2] = types_used  # remembered for new_params_bound=0 rounds
    sql = "".join(
        p + (_literal(params[i]) if i < nparams else "")
        for i, p in enumerate(pieces)
    )
    wire_read_s = _time.perf_counter() - tr
    try:
        rs = sess.sql(sql)
    except Exception as e:
        return [_err_packet(
            getattr(e, "code", 1064), f"{type(e).__name__}: {e}")]
    # packet decode + literal substitution happened before the ledger
    # opened; attribute it now that the digest is known
    _fold_wire(sess, "wire read", wire_read_s)
    if not rs.names:
        return [_ok_packet(affected=rs.affected)]
    tw = _time.perf_counter()
    cols = [rs.columns[n] for n in rs.names]
    types = [_col_mysql_type(c) for c in cols]
    out = [_lenenc_int(len(rs.names))]
    for n, t in zip(rs.names, types):
        out.append(_coldef(n, t))
    out.append(_eof_packet())
    ncols = len(cols)
    nb = (ncols + 2 + 7) // 8
    for i in range(rs.nrows):
        bitmap = bytearray(nb)
        body = bytearray()
        for j, (c, t) in enumerate(zip(cols, types)):
            v = c[i]
            is_null = v is None or (
                isinstance(v, float) and v != v
            )
            if is_null:
                # binary-row NULL bitmap has a 2-bit offset
                bit = j + 2
                bitmap[bit // 8] |= 1 << (bit % 8)
                continue
            if t == MYSQL_TYPE_LONGLONG:
                body += int(v).to_bytes(8, "little", signed=True)
            elif t == MYSQL_TYPE_DOUBLE:
                body += struct.pack("<d", float(v))
            else:
                body += _lenenc_str(str(v).encode())
        out.append(b"\x00" + bytes(bitmap) + bytes(body))
    out.append(_eof_packet())
    _fold_wire(sess, "wire write", _time.perf_counter() - tw)
    return out


def stmt_reset_payload(pkt: bytes, stmts: dict) -> bytes:
    """COM_STMT_RESET: standard connectors send it between executes to
    drop accumulated long data / cursors. The rebuild holds neither —
    resetting forgets the remembered param types, so the next execute
    must send a fresh type block (new_params_bound=1, which compliant
    drivers do after a reset)."""
    if len(pkt) < 5:
        return _err_packet(1210, "malformed reset packet")
    entry = stmts.get(int.from_bytes(pkt[1:5], "little"))
    if entry is None:
        return _err_packet(1243, "unknown statement id")
    entry[2] = None
    return _ok_packet()


def build_greeting(salt: bytes, with_ssl: bool) -> bytes:
    """Protocol v10 greeting payload (the salt is the caller's: it must
    outlive the packet to verify the login scramble)."""
    caps = (
        CLIENT_PROTOCOL_41 | CLIENT_CONNECT_WITH_DB
        | CLIENT_SECURE_CONNECTION
    )
    if with_ssl:
        caps |= CLIENT_SSL
    return (
        b"\x0a" + b"5.7.0-oceanbase-tpu\x00"
        + (1).to_bytes(4, "little")
        + salt[:8] + b"\x00"
        + (caps & 0xFFFF).to_bytes(2, "little")
        + bytes([33])  # charset utf8
        + (0x0002).to_bytes(2, "little")
        + ((caps >> 16) & 0xFFFF).to_bytes(2, "little")
        + bytes([len(salt) + 1])
        + b"\x00" * 10
        + salt[8:] + b"\x00"
        + b"mysql_native_password\x00"
    )


def make_salt() -> bytes:
    import os

    return bytes(
        (b % 94) + 33 for b in os.urandom(20)  # printable, no NULs
    )


def is_ssl_request(login: bytes) -> bool:
    """SSLRequest = caps+maxpacket+charset+23 filler, no user name."""
    return len(login) < 36 and (
        len(login) >= 4
        and int.from_bytes(login[:4], "little") & CLIENT_SSL
    )


def check_login(db, users, login: bytes, salt: bytes) -> str | None:
    """Verified login user name, or None. With no explicit `users`
    map, accounts come from the database's privilege manager (root
    with empty password exists from bootstrap), so CREATE USER /
    GRANT govern the front door too."""
    if users is None:
        pm = getattr(db, "privileges", None)
        users = pm.authenticate_db() if pm is not None else None
    try:
        # HandshakeResponse41: caps u32, max packet u32, charset u8,
        # 23 reserved, user\0, lenenc auth response
        off = 4 + 4 + 1 + 23
        end = login.index(b"\x00", off)
        user = login[off:end].decode()
        off = end + 1
        alen = login[off]
        off += 1
        auth = login[off:off + alen]
    except (ValueError, IndexError):
        return None
    if users is None:
        return user or "root"  # open door (no privilege manager)
    if user not in users:
        return None
    # verify_native_password compares full SHA1 digests via
    # hmac.compare_digest — constant-time, stage2-only at rest.
    return user if verify_native_password(users[user], auth, salt) \
        else None


class MySqlFrontend:
    """TCP listener translating MySQL protocol to DbSessions.

    `users` maps user name -> password; None (default) keeps the open
    door for in-process tests. With users set, logins verify the
    mysql_native_password scramble against the salt."""

    def __init__(self, db: Database, host: str = "127.0.0.1", port: int = 0,
                 users: dict[str, str] | None = None,
                 ssl_context=None):
        self.db = db
        # An explicit `users` map arrives as plaintext (test/embedding
        # convenience) — reduce to stage-2 hashes immediately; the
        # frontend never holds plaintext credentials.
        if users is not None:
            from ..share.privilege import stage2_hash

            users = {u: stage2_hash(p) for u, p in users.items()}
        self.users = users
        # ssl.SSLContext (share/tls.server_context): advertise CLIENT_SSL
        # and upgrade the connection on an SSLRequest packet, per the
        # MySQL protocol's mid-handshake TLS negotiation
        self.ssl_context = ssl_context
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                outer._serve(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # a serving front door gets bursts of hundreds of connects
            # (bench ramp-up, reconnect storms); the socketserver
            # default backlog of 5 drops SYNs into multi-second
            # retransmit limbo
            request_queue_size = 256

        self.server = Server((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    def start(self) -> "MySqlFrontend":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    # ---------------------------------------------------------- protocol
    def _serve(self, sock: socket.socket) -> None:
        # a resultset is several small packets, each its own send():
        # without NODELAY, Nagle + delayed ACK stall every multi-packet
        # response ~40ms (the asyncio front end gets NODELAY by default)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        conn = _Conn(sock)
        # id -> [pieces, nparams, last-bound param types] (drivers send
        # types only on the FIRST execute; new_params_bound=0 reuses them)
        stmts: dict[int, list] = {}
        next_stmt = [1]
        sess = None
        try:
            salt = make_salt()
            conn.send_packet(
                build_greeting(salt, self.ssl_context is not None))
            login = conn.read_packet()
            if self.ssl_context is not None and is_ssl_request(login):
                # SSLRequest (caps+maxpacket+charset+23 filler, no user):
                # upgrade the socket, then read the real login over TLS.
                # The packet sequence number continues across the upgrade.
                conn.sock = self.ssl_context.wrap_socket(
                    conn.sock, server_side=True
                )
                sock = conn.sock  # the finally-close must close the TLS fd
                login = conn.read_packet()
            user = check_login(self.db, self.users, login, salt)
            if user is None:
                conn.send_packet(
                    _err_packet(1045, "Access denied (bad credentials)"))
                return
            sess = self.db.session(user=user)
            conn.send_packet(_ok_packet())
            while True:
                conn.reset_seq()
                pkt = conn.read_packet()
                if not pkt:
                    return
                cmd = pkt[0]
                if cmd == 0x01:  # COM_QUIT
                    return
                if cmd in (0x0E, 0x02):  # COM_PING / COM_INIT_DB
                    conn.send_packet(_ok_packet())
                    continue
                if cmd == 0x03:  # COM_QUERY
                    for p in query_payloads(sess, pkt[1:].decode()):
                        conn.send_packet(p)
                    continue
                if cmd == 0x16:  # COM_STMT_PREPARE
                    for p in stmt_prepare_payloads(pkt[1:].decode(),
                                                   stmts, next_stmt):
                        conn.send_packet(p)
                    continue
                if cmd == 0x17:  # COM_STMT_EXECUTE
                    for p in stmt_execute_payloads(sess, pkt, stmts):
                        conn.send_packet(p)
                    continue
                if cmd == 0x19:  # COM_STMT_CLOSE (no response)
                    if len(pkt) >= 5:
                        stmts.pop(int.from_bytes(pkt[1:5], "little"), None)
                    continue
                if cmd == 0x1A:  # COM_STMT_RESET
                    conn.send_packet(stmt_reset_payload(pkt, stmts))
                    continue
                conn.send_packet(_err_packet(1047, "unsupported command"))
        except (ConnectionError, OSError):
            pass
        finally:
            # drop the engine session FIRST: rolls back an open tx and
            # flushes the workload-repo accumulator NOW (digest counts
            # reconcile on disconnect, not at some later GC)
            if sess is not None:
                try:
                    sess.close()
                except Exception:  # noqa: BLE001 — disconnect is best-effort
                    pass
            try:
                sock.close()
            except OSError:
                pass
