"""MySQL wire-protocol front door: stock clients connect and run SQL.

Reference surface: the MySQL command layer — connection handshake and
COM_QUERY dispatch (src/observer/mysql/obmp_query.cpp:53, obmp_connect),
packet codecs (deps/oblib/src/rpc/obmysql). The rebuild speaks classic
protocol v10 / CLIENT_PROTOCOL_41 with the text resultset encoding:

  greeting -> login (any credentials accepted) -> OK
  COM_QUERY    -> resultset (column defs, EOF, text rows, EOF)
                  or OK (DML/DDL with affected-rows) or ERR
  COM_PING     -> OK,  COM_INIT_DB -> OK,  COM_QUIT -> close

Each connection binds one DbSession (transactions span statements on the
same connection, like a real server thread). Values travel as text; NULL
is the 0xFB marker — the lowest common denominator every client and
driver understands.
"""

from __future__ import annotations

import socket
import socketserver
import threading

import numpy as np

from .database import Database, SqlError

CLIENT_PROTOCOL_41 = 0x0200
CLIENT_CONNECT_WITH_DB = 0x0008
CLIENT_SECURE_CONNECTION = 0x8000

MYSQL_TYPE_VAR_STRING = 253


def _lenenc_int(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + n.to_bytes(2, "little")
    if n < 1 << 24:
        return b"\xfd" + n.to_bytes(3, "little")
    return b"\xfe" + n.to_bytes(8, "little")


def _lenenc_str(s: bytes) -> bytes:
    return _lenenc_int(len(s)) + s


class _Conn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def read_packet(self) -> bytes:
        head = self._read_n(4)
        length = int.from_bytes(head[:3], "little")
        self.seq = (head[3] + 1) & 0xFF
        return self._read_n(length)

    def _read_n(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def send_packet(self, payload: bytes) -> None:
        head = len(payload).to_bytes(3, "little") + bytes([self.seq])
        self.seq = (self.seq + 1) & 0xFF
        self.sock.sendall(head + payload)

    def reset_seq(self) -> None:
        self.seq = 0


def _ok_packet(affected: int = 0, info: bytes = b"") -> bytes:
    return (
        b"\x00" + _lenenc_int(affected) + _lenenc_int(0)
        + (0x0002).to_bytes(2, "little")  # SERVER_STATUS_AUTOCOMMIT
        + (0).to_bytes(2, "little") + info
    )


def _eof_packet() -> bytes:
    return b"\xfe" + (0).to_bytes(2, "little") + (0x0002).to_bytes(2, "little")


def _err_packet(code: int, msg: str) -> bytes:
    return (
        b"\xff" + code.to_bytes(2, "little") + b"#HY000"
        + msg.encode()[:400]
    )


def _coldef(name: str) -> bytes:
    return (
        _lenenc_str(b"def") + _lenenc_str(b"") + _lenenc_str(b"")
        + _lenenc_str(b"") + _lenenc_str(name.encode())
        + _lenenc_str(name.encode())
        + b"\x0c" + (33).to_bytes(2, "little")  # utf8
        + (255).to_bytes(4, "little")
        + bytes([MYSQL_TYPE_VAR_STRING])
        + (0).to_bytes(2, "little") + b"\x00" + b"\x00\x00"
    )


def _cell(v) -> bytes:
    if v is None:
        return b"\xfb"
    if isinstance(v, float) and v != v:  # NaN surfaces SQL NULL
        return b"\xfb"
    if isinstance(v, (np.floating, float)):
        return _lenenc_str(repr(float(v)).encode())
    if isinstance(v, (np.integer, int)):
        return _lenenc_str(str(int(v)).encode())
    return _lenenc_str(str(v).encode())


class MySqlFrontend:
    """TCP listener translating MySQL protocol to DbSessions."""

    def __init__(self, db: Database, host: str = "127.0.0.1", port: int = 0):
        self.db = db
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                outer._serve(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    def start(self) -> "MySqlFrontend":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    # ---------------------------------------------------------- protocol
    def _serve(self, sock: socket.socket) -> None:
        conn = _Conn(sock)
        sess = self.db.session()
        try:
            self._greet(conn)
            conn.read_packet()  # login request: all credentials accepted
            conn.send_packet(_ok_packet())
            while True:
                conn.reset_seq()
                pkt = conn.read_packet()
                if not pkt:
                    return
                cmd = pkt[0]
                if cmd == 0x01:  # COM_QUIT
                    return
                if cmd in (0x0E, 0x02):  # COM_PING / COM_INIT_DB
                    conn.send_packet(_ok_packet())
                    continue
                if cmd == 0x03:  # COM_QUERY
                    self._query(conn, sess, pkt[1:].decode())
                    continue
                conn.send_packet(_err_packet(1047, "unsupported command"))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _greet(self, conn: _Conn) -> None:
        caps = (
            CLIENT_PROTOCOL_41 | CLIENT_CONNECT_WITH_DB
            | CLIENT_SECURE_CONNECTION
        )
        salt = b"0123456789abcdefghij"
        payload = (
            b"\x0a" + b"5.7.0-oceanbase-tpu\x00"
            + (1).to_bytes(4, "little")
            + salt[:8] + b"\x00"
            + (caps & 0xFFFF).to_bytes(2, "little")
            + bytes([33])  # charset utf8
            + (0x0002).to_bytes(2, "little")
            + ((caps >> 16) & 0xFFFF).to_bytes(2, "little")
            + bytes([len(salt) + 1])
            + b"\x00" * 10
            + salt[8:] + b"\x00"
            + b"mysql_native_password\x00"
        )
        conn.send_packet(payload)

    def _query(self, conn: _Conn, sess, sql: str) -> None:
        try:
            rs = sess.sql(sql)
        except Exception as e:  # SqlError, parse errors, resolver errors
            conn.send_packet(_err_packet(1064, f"{type(e).__name__}: {e}"))
            return
        if not rs.names:
            conn.send_packet(_ok_packet(affected=rs.affected))
            return
        conn.send_packet(_lenenc_int(len(rs.names)))
        for n in rs.names:
            conn.send_packet(_coldef(n))
        conn.send_packet(_eof_packet())
        cols = [rs.columns[n] for n in rs.names]
        for i in range(rs.nrows):
            conn.send_packet(b"".join(_cell(c[i]) for c in cols))
        conn.send_packet(_eof_packet())
