"""Diagnostics: full-link tracing, sql_audit, plan monitor, ASH sampler.

Reference surface:
  * ObTrace full-link tracing with spans flowing through the request path
    (deps/oblib/src/lib/trace/ob_trace.h);
  * sql_audit request ring buffer (observer/mysql/ob_mysql_request_manager.h)
    surfaced as __all_virtual_sql_audit;
  * per-operator plan monitor (ObMonitorNode,
    share/diagnosis/ob_sql_plan_monitor_node_list.h) -> GV$SQL_PLAN_MONITOR;
  * ASH active-session sampling (lib/ash/ob_active_session_guard.h).

TPU redesign note: a plan executes as ONE fused XLA program, so the
reference's per-operator rdtsc windows have no physical analog on device —
the honest monitoring unit is the plan run (compile time, device time,
rows, overflow retries) plus host-side phase spans (parse/plan/compile),
which is what the trace + plan monitor record here.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field


# ---- full-link tracing ------------------------------------------------------


@dataclass(slots=True)
class Span:
    trace_id: int
    span_id: int
    parent_id: int
    name: str
    start: float
    end: float = 0.0
    tags: dict = field(default_factory=dict)
    # the owning tracer's clock: a live span's elapsed must tick on the
    # SAME timebase as start/end, or injected-clock tests read nonsense
    clock: object = None

    @property
    def elapsed(self) -> float:
        end = self.end or (self.clock or time.perf_counter)()
        return end - self.start


class _SpanGuard:
    """Hand-rolled context manager for Tracer.span. The serving hot path
    enters two spans per statement; a generator-based contextmanager
    costs several times as much per enter/exit, and the finished-span
    ring is a deque whose append is atomic under the GIL — no lock."""

    __slots__ = ("_tracer", "_stack", "_span", "_record")

    def __init__(self, tracer, stack, span, record):
        self._tracer = tracer
        self._stack = stack
        self._span = span
        self._record = record

    def __enter__(self):
        self._stack.append(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        s = self._span
        if exc is not None:
            # failed statements must stay findable in the span ring
            # (__all_virtual_trace_span filters on error != '')
            s.tags["error"] = repr(exc)
        if self._record:
            s.end = self._tracer._clock()
        self._stack.pop()
        if self._record:
            self._tracer._done.append(s)
        return False


class Tracer:
    """Per-database tracer: thread-local span stacks, finished-span ring.

    Two recording shapes:
      * `span()` — a contextmanager for work on the CURRENT thread; nests
        via the thread-local stack (or an explicit `ctx=` parent when the
        logical parent lives on another thread, e.g. a DAG task running a
        statement-initiated compaction);
      * `record_span()` — a retrospective finished span for work measured
        on a DIFFERENT clock/thread (palf replication rounds timed on the
        bus virtual clock), stitched into a trace via an explicit
        (trace_id, parent_span_id) context captured at submit time.
    """

    def __init__(self, capacity: int = 4096, clock=time.perf_counter):
        self._ids = itertools.count(1)
        self._clock = clock
        self._local = threading.local()
        self._done: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = True

    def _stack(self) -> list[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def span(self, name: str, ctx: tuple | None = None, **tags):
        st = self._stack()
        parent = st[-1] if st else None
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif ctx:
            # adopt a propagated (trace_id, parent_span_id) — task
            # dispatch across threads / bus hops carries this explicitly
            # because thread-locals do not travel
            trace_id, parent_id = int(ctx[0]), int(ctx[1])
        else:
            trace_id, parent_id = next(self._ids), 0
        # the span goes on the stack even when disabled: nested spans must
        # inherit the parent's trace_id either way, or callers that stash
        # current_trace_id() get ids that differ by flag state. Only the
        # RING write (the allocation that costs memory) is gated — and on
        # the disabled path the clock reads and the tag-dict copy go too
        # (hot-path overhead diet: a disabled span is id bookkeeping only).
        record = self.enabled
        s = Span(
            trace_id=trace_id,
            span_id=next(self._ids),
            parent_id=parent_id,
            name=name,
            start=self._clock() if record else 0.0,
            tags=dict(tags) if record else tags,
            clock=self._clock,
        )
        return _SpanGuard(self, st, s, record)

    def current_trace_id(self) -> int:
        st = self._stack()
        return st[-1].trace_id if st else 0

    def current_ctx(self) -> tuple[int, int] | None:
        """(trace_id, span_id) of the active span — the propagation
        context stamped onto bus messages and background-task dispatch."""
        st = self._stack()
        return (st[-1].trace_id, st[-1].span_id) if st else None

    def record_span(self, name: str, ctx: tuple | None, start: float,
                    end: float, **tags) -> Span | None:
        """Append an already-finished span measured elsewhere (bus virtual
        clock, another node). `ctx` is the propagated parent context; a
        missing one mints a fresh trace so the span is still findable."""
        if not self.enabled:
            return None
        if ctx:
            trace_id, parent_id = int(ctx[0]), int(ctx[1])
        else:
            trace_id, parent_id = next(self._ids), 0
        s = Span(
            trace_id=trace_id,
            span_id=next(self._ids),
            parent_id=parent_id,
            name=name,
            start=start,
            end=end,
            tags=dict(tags),
            clock=self._clock,
        )
        with self._lock:
            self._done.append(s)
        return s

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._done)

    def trace_tree(self, trace_id: int) -> list[tuple[int, Span]]:
        """Spans of one trace as a depth-first (depth, span) walk — the
        rendering order of SHOW TRACE. Orphans (parent fell off the ring
        or lives on another tenant's tracer) surface at depth 0."""
        spans = [s for s in self.spans() if s.trace_id == trace_id]
        by_parent: dict[int, list[Span]] = {}
        ids = {s.span_id for s in spans}
        for s in spans:
            pid = s.parent_id if s.parent_id in ids else 0
            by_parent.setdefault(pid, []).append(s)
        for v in by_parent.values():
            v.sort(key=lambda s: (s.start, s.span_id))
        out: list[tuple[int, Span]] = []

        def walk(pid: int, depth: int) -> None:
            for s in by_parent.get(pid, ()):
                out.append((depth, s))
                walk(s.span_id, depth + 1)

        walk(0, 0)
        return out


# ---- sql_audit --------------------------------------------------------------


@dataclass(slots=True)
class AuditRecord:
    request_id: int
    session_id: int
    trace_id: int
    sql: str
    stmt_type: str
    elapsed_s: float
    rows: int
    affected: int
    plan_cache_hit: bool
    error: str = ""
    ts: float = 0.0
    # per-query resource profile (QueryProfile): compile + data-movement
    # attribution, the accelerator analog of sql_audit's rpc/io columns
    compile_s: float = 0.0
    device_bytes: int = 0
    transfer_bytes: int = 0
    peak_bytes: int = 0
    # statement retry controller (ObQueryRetryCtrl): how many times the
    # statement was transparently redriven and why ("reason xN; ...")
    retry_cnt: int = 0
    retry_info: str = ""
    # statement fast path: serving-phase breakdown at record time. For a
    # lazy result set fetch_us covers only the completion sync (ovf+nrows);
    # column transfers the client performs later accrue to the in-place
    # QueryProfile, not to this snapshot.
    fastparse_us: int = 0
    bind_us: int = 0
    dispatch_us: int = 0
    fetch_us: int = 0
    is_fast_path: bool = False
    # cross-session micro-batching (server/batcher.py): statements that
    # rode a shared batched dispatch carry the batch id (join lanes of
    # one launch) and the time spent in the group-commit window
    is_batched: bool = False
    batch_id: int = 0
    batch_wait_us: int = 0
    # host-tax gap ledger (share/gap_ledger.py): time the chip sat idle
    # during this statement's wall, and the wall the ledger could not
    # attribute to any named phase (the conservation residual)
    chip_idle_us: int = 0
    unattributed_us: int = 0


class SqlAudit:
    """Fixed-capacity ring of per-statement records (ob_mysql_request_manager
    keeps a memory-bounded ring; entry count is the proxy here). The
    timestamp clock is injectable so virtual-clock tests get deterministic
    `ts` values (live servers keep wall time)."""

    def __init__(self, capacity: int = 10000, clock=time.time):
        self._ring: deque[AuditRecord] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._clock = clock
        self.enabled = True

    def record(self, **kw) -> None:
        if not self.enabled:
            return
        # itertools.count and deque.append are both atomic under the GIL:
        # one audit record per statement appends lock-free. (A record
        # racing set_capacity's ring swap may land in the retired ring —
        # an accepted loss, capacity changes are a rare admin action.)
        self._ring.append(
            AuditRecord(request_id=next(self._ids), ts=self._clock(), **kw)
        )

    def records(self) -> list[AuditRecord]:
        with self._lock:
            return list(self._ring)

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=capacity)


# ---- plan monitor -----------------------------------------------------------


@dataclass
class PlanMonitorEntry:
    """Per compiled plan (the TPU monitoring unit — one XLA executable)."""

    plan_id: int
    sql: str
    compile_s: float = 0.0
    runs: int = 0
    total_exec_s: float = 0.0
    last_rows: int = 0
    overflow_retries: int = 0
    # QueryProfile accumulation across runs of this plan: data movement
    # and working-set footprint per compiled executable
    total_transfer_bytes: int = 0
    last_device_bytes: int = 0
    peak_bytes: int = 0
    # mesh-SPMD plans: cumulative XLA collectives dispatched / their byte
    # capacity, plus a compact per-collective layout ("all_to_all:2,psum:1")
    px_collective_ops: int = 0
    px_collective_bytes: int = 0
    px_exchanges: str = ""
    # streaming pipeline (engine/pipeline.py): chunks streamed through
    # this plan, last run's H2D/compute overlap fraction, and grace-hash
    # partitions spilled to host segments
    stream_chunks: int = 0
    h2d_overlap_pct: float = 0.0
    spill_partitions: int = 0

    @property
    def avg_exec_s(self) -> float:
        return self.total_exec_s / self.runs if self.runs else 0.0


class PlanMonitor:
    def __init__(self, capacity: int = 1024):
        self._entries: deque[PlanMonitorEntry] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self.enabled = True

    def register(self, sql: str, compile_s: float) -> PlanMonitorEntry:
        e = PlanMonitorEntry(next(self._ids), sql, compile_s=compile_s)
        with self._lock:
            self._entries.append(e)
        return e

    def entries(self) -> list[PlanMonitorEntry]:
        with self._lock:
            return list(self._entries)


# ---- ASH (active session history) ------------------------------------------


@dataclass(slots=True)
class AshSample:
    ts: float
    session_id: int
    activity: str
    sql: str
    trace_id: int


class _ActivityGuard:
    """Hand-rolled context manager for AshSampler.activity — one per
    statement on the serving hot path."""

    __slots__ = ("_active", "_sid")

    def __init__(self, active, sid):
        self._active = active
        self._sid = sid

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        self._active.pop(self._sid, None)
        return False


class AshSampler:
    """Samples what every active session is doing.

    Sessions publish their current activity via `activity()` guards; the
    sampler snapshots all active entries — on a timer thread in live
    deployments (`start`), or on demand (`sample_once`) in deterministic
    tests. History is a bounded ring like the reference's ASH buffer."""

    def __init__(self, capacity: int = 90000, interval_s: float = 1.0,
                 clock=time.time):
        self._active: dict[int, tuple[str, str, int]] = {}
        self._ring: deque[AshSample] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._interval = interval_s
        self._clock = clock
        self._timer: threading.Timer | None = None

    def activity(self, session_id: int, activity: str, sql: str = "",
                 trace_id: int = 0):
        # dict store/pop on a per-session key are atomic under the GIL;
        # taking the sampler lock twice per statement made this the most
        # contended point of the serving hot path under many sessions.
        # sample_once snapshots via list(...) so it never iterates a
        # dict being mutated by session threads.
        self._active[session_id] = (activity, sql, trace_id)
        return _ActivityGuard(self._active, session_id)

    def sample_once(self, now: float | None = None) -> int:
        ts = self._clock() if now is None else now
        snap = list(self._active.items())
        with self._lock:
            for sid, (act, sql, tid) in snap:
                self._ring.append(AshSample(ts, sid, act, sql, tid))
        return len(snap)

    def start(self) -> None:
        def tick():
            self.sample_once()
            with self._lock:
                if self._timer is not None:
                    self._timer = threading.Timer(self._interval, tick)
                    self._timer.daemon = True
                    self._timer.start()

        with self._lock:
            if self._timer is None:
                self._timer = threading.Timer(self._interval, tick)
                self._timer.daemon = True
                self._timer.start()

    def stop(self) -> None:
        with self._lock:
            t, self._timer = self._timer, None
        if t is not None:
            t.cancel()

    def samples(self) -> list[AshSample]:
        with self._lock:
            return list(self._ring)


# ---- per-query resource profile ---------------------------------------------


@dataclass(slots=True)
class QueryProfile:
    """TPU cost attribution for ONE statement execution.

    The unit economics of an accelerator engine are compile time, bytes
    moved across the host<->device boundary, and device-resident working
    set (PAPERS.md: Tailwind's accounting prerequisite). All numbers are
    host-observed: array `nbytes` at the operator boundaries (input
    batches, parameter upload, result fetch) — nothing here runs inside
    traced code."""

    compile_hit: bool = False  # plan cache served the XLA executable
    compile_s: float = 0.0  # trace + XLA compile seconds (0 on hit)
    h2d_bytes: int = 0  # host->device: new batch uploads + parameters
    d2h_bytes: int = 0  # device->host: bytes ACTUALLY fetched (lazy
    # results grow this in place as the cursor transfers columns)
    device_bytes: int = 0  # device-resident input + output footprint
    peak_bytes: int = 0  # working-set estimate (inputs+outputs+exchanges)
    # serving-path phase breakdown (statement fast path): where the host
    # microseconds go once the kernel is no longer the bottleneck
    fastparse_s: float = 0.0  # tokenize + text-tier lookup + literal bind
    bind_s: float = 0.0  # parameter pack (one int64 vector upload)
    dispatch_s: float = 0.0  # async XLA dispatch (enqueue, no sync)
    fetch_s: float = 0.0  # device->host syncs: ovf/nrows + column fetches
    fast_path_hit: bool = False  # statement skipped parse/resolve/plan

    @property
    def transfer_bytes(self) -> int:
        return self.h2d_bytes + self.d2h_bytes

    def as_dict(self) -> dict:
        return {
            "compile_hit": self.compile_hit,
            "compile_us": int(self.compile_s * 1e6),
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
            "transfer_bytes": self.transfer_bytes,
            "device_bytes": self.device_bytes,
            "peak_bytes": self.peak_bytes,
            "fastparse_us": int(self.fastparse_s * 1e6),
            "bind_us": int(self.bind_s * 1e6),
            "dispatch_us": int(self.dispatch_s * 1e6),
            "fetch_us": int(self.fetch_s * 1e6),
            "is_fast_path": self.fast_path_hit,
        }


# ---- long-running operations ------------------------------------------------


@dataclass
class LongOp:
    """One background job's progress row (__all_virtual_long_ops analog:
    the reference surfaces index build / migration / compaction progress
    through ob_all_virtual_long_ops_status)."""

    op_id: int
    name: str  # e.g. "mini_compaction", "index_backfill", "ha_migration"
    target: str  # what it operates on (tablet/table/ls identity)
    total: int = 0  # work units expected (0 = unknown)
    done: int = 0
    status: str = "RUNNING"  # RUNNING | DONE | FAILED
    trace_id: int = 0  # initiating statement's trace (0 = autonomous)
    start_ts: float = 0.0
    end_ts: float = 0.0
    message: str = ""

    @property
    def percent(self) -> float:
        if self.status == "DONE":
            return 100.0
        return 100.0 * self.done / self.total if self.total else 0.0


class LongOps:
    """Registry of running + recently-finished background jobs. Handles
    are plain LongOp rows the owning job mutates through the registry
    (update/finish), so readers always see a consistent snapshot."""

    def __init__(self, capacity: int = 256, clock=time.perf_counter):
        self._ids = itertools.count(1)
        self._active: dict[int, LongOp] = {}
        self._finished: deque[LongOp] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._clock = clock

    def start(self, name: str, target: str = "", total: int = 0,
              trace_id: int = 0) -> LongOp:
        op = LongOp(next(self._ids), name, target, total=total,
                    trace_id=trace_id, start_ts=self._clock())
        with self._lock:
            self._active[op.op_id] = op
        return op

    def update(self, op: LongOp, done: int | None = None,
               message: str = "") -> None:
        with self._lock:
            if done is not None:
                op.done = done
            if message:
                op.message = message

    def finish(self, op: LongOp, ok: bool = True, message: str = "") -> None:
        with self._lock:
            if self._active.pop(op.op_id, None) is None:
                return  # double-finish: first decision wins
            op.status = "DONE" if ok else "FAILED"
            op.end_ts = self._clock()
            if ok and op.total:
                op.done = op.total
            if message:
                op.message = message
            self._finished.append(op)

    def ops(self) -> list[LongOp]:
        with self._lock:
            return list(self._finished) + sorted(
                self._active.values(), key=lambda o: o.op_id
            )


# ---- slow-query flight recorder ---------------------------------------------


class FlightRecorder:
    """Bounded ring of diagnostic bundles for statements that crossed the
    trace_log_slow_query_watermark — evidence captured AT the moment the
    slow statement finished, not reconstructed later (the obdiag 'gather'
    pain point: by the time anyone runs it, sysstat moved on).

    The metrics-delta baseline advances on every recorded bundle: each
    bundle's `metrics_delta` covers the window since the previous bundle
    (or process start) at zero per-statement cost — snapshotting counters
    around EVERY statement would show up in the overhead bench."""

    def __init__(self, capacity: int = 64, watermark_s: float = 1.0):
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._baseline: dict[str, float] = {}
        self._ids = itertools.count(1)
        self.watermark_s = watermark_s
        self.enabled = True

    def should_record(self, elapsed_s: float) -> bool:
        return self.enabled and elapsed_s >= self.watermark_s

    def record(self, bundle: dict, counters: dict | None = None) -> dict:
        """Store one bundle; when a counters snapshot is provided, attach
        the delta vs the previous bundle's baseline."""
        with self._lock:
            bundle = dict(bundle)
            bundle["bundle_id"] = next(self._ids)
            if counters is not None:
                delta = {
                    k: v - self._baseline.get(k, 0)
                    for k, v in counters.items()
                    if v != self._baseline.get(k, 0)
                }
                bundle["metrics_delta"] = delta
                self._baseline = dict(counters)
            self._ring.append(bundle)
            return bundle

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=capacity)
