"""Diagnostics: full-link tracing, sql_audit, plan monitor, ASH sampler.

Reference surface:
  * ObTrace full-link tracing with spans flowing through the request path
    (deps/oblib/src/lib/trace/ob_trace.h);
  * sql_audit request ring buffer (observer/mysql/ob_mysql_request_manager.h)
    surfaced as __all_virtual_sql_audit;
  * per-operator plan monitor (ObMonitorNode,
    share/diagnosis/ob_sql_plan_monitor_node_list.h) -> GV$SQL_PLAN_MONITOR;
  * ASH active-session sampling (lib/ash/ob_active_session_guard.h).

TPU redesign note: a plan executes as ONE fused XLA program, so the
reference's per-operator rdtsc windows have no physical analog on device —
the honest monitoring unit is the plan run (compile time, device time,
rows, overflow retries) plus host-side phase spans (parse/plan/compile),
which is what the trace + plan monitor record here.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


# ---- full-link tracing ------------------------------------------------------


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_id: int
    name: str
    start: float
    end: float = 0.0
    tags: dict = field(default_factory=dict)
    # the owning tracer's clock: a live span's elapsed must tick on the
    # SAME timebase as start/end, or injected-clock tests read nonsense
    clock: object = None

    @property
    def elapsed(self) -> float:
        end = self.end or (self.clock or time.perf_counter)()
        return end - self.start


class Tracer:
    """Per-database tracer: thread-local span stacks, finished-span ring."""

    def __init__(self, capacity: int = 4096, clock=time.perf_counter):
        self._ids = itertools.count(1)
        self._clock = clock
        self._local = threading.local()
        self._done: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = True

    def _stack(self) -> list[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str, **tags):
        st = self._stack()
        parent = st[-1] if st else None
        s = Span(
            trace_id=parent.trace_id if parent else next(self._ids),
            span_id=next(self._ids),
            parent_id=parent.span_id if parent else 0,
            name=name,
            start=self._clock(),
            tags=dict(tags),
            clock=self._clock,
        )
        if not self.enabled:
            # still hand out a span (callers read trace_id) but record
            # nothing — the zero-overhead path the bench compares against
            yield s
            return
        st.append(s)
        try:
            yield s
        except BaseException as exc:
            # failed statements must stay findable in the span ring
            # (__all_virtual_trace_span filters on error != '')
            s.tags["error"] = repr(exc)
            raise
        finally:
            s.end = self._clock()
            st.pop()
            with self._lock:
                self._done.append(s)

    def current_trace_id(self) -> int:
        st = self._stack()
        return st[-1].trace_id if st else 0

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._done)


# ---- sql_audit --------------------------------------------------------------


@dataclass
class AuditRecord:
    request_id: int
    session_id: int
    trace_id: int
    sql: str
    stmt_type: str
    elapsed_s: float
    rows: int
    affected: int
    plan_cache_hit: bool
    error: str = ""
    ts: float = 0.0


class SqlAudit:
    """Fixed-capacity ring of per-statement records (ob_mysql_request_manager
    keeps a memory-bounded ring; entry count is the proxy here)."""

    def __init__(self, capacity: int = 10000):
        self._ring: deque[AuditRecord] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self.enabled = True

    def record(self, **kw) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(
                AuditRecord(request_id=next(self._ids), ts=time.time(), **kw)
            )

    def records(self) -> list[AuditRecord]:
        with self._lock:
            return list(self._ring)

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=capacity)


# ---- plan monitor -----------------------------------------------------------


@dataclass
class PlanMonitorEntry:
    """Per compiled plan (the TPU monitoring unit — one XLA executable)."""

    plan_id: int
    sql: str
    compile_s: float = 0.0
    runs: int = 0
    total_exec_s: float = 0.0
    last_rows: int = 0
    overflow_retries: int = 0

    @property
    def avg_exec_s(self) -> float:
        return self.total_exec_s / self.runs if self.runs else 0.0


class PlanMonitor:
    def __init__(self, capacity: int = 1024):
        self._entries: deque[PlanMonitorEntry] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self.enabled = True

    def register(self, sql: str, compile_s: float) -> PlanMonitorEntry:
        e = PlanMonitorEntry(next(self._ids), sql, compile_s=compile_s)
        with self._lock:
            self._entries.append(e)
        return e

    def entries(self) -> list[PlanMonitorEntry]:
        with self._lock:
            return list(self._entries)


# ---- ASH (active session history) ------------------------------------------


@dataclass
class AshSample:
    ts: float
    session_id: int
    activity: str
    sql: str
    trace_id: int


class AshSampler:
    """Samples what every active session is doing.

    Sessions publish their current activity via `activity()` guards; the
    sampler snapshots all active entries — on a timer thread in live
    deployments (`start`), or on demand (`sample_once`) in deterministic
    tests. History is a bounded ring like the reference's ASH buffer."""

    def __init__(self, capacity: int = 90000, interval_s: float = 1.0):
        self._active: dict[int, tuple[str, str, int]] = {}
        self._ring: deque[AshSample] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._interval = interval_s
        self._timer: threading.Timer | None = None

    @contextmanager
    def activity(self, session_id: int, activity: str, sql: str = "",
                 trace_id: int = 0):
        with self._lock:
            self._active[session_id] = (activity, sql, trace_id)
        try:
            yield
        finally:
            with self._lock:
                self._active.pop(session_id, None)

    def sample_once(self, now: float | None = None) -> int:
        ts = time.time() if now is None else now
        with self._lock:
            for sid, (act, sql, tid) in self._active.items():
                self._ring.append(AshSample(ts, sid, act, sql, tid))
            return len(self._active)

    def start(self) -> None:
        def tick():
            self.sample_once()
            with self._lock:
                if self._timer is not None:
                    self._timer = threading.Timer(self._interval, tick)
                    self._timer.daemon = True
                    self._timer.start()

        with self._lock:
            if self._timer is None:
                self._timer = threading.Timer(self._interval, tick)
                self._timer.daemon = True
                self._timer.start()

    def stop(self) -> None:
        with self._lock:
            t, self._timer = self._timer, None
        if t is not None:
            t.cancel()

    def samples(self) -> list[AshSample]:
        with self._lock:
            return list(self._ring)
