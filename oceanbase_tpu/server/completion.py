"""Completion drain: statement accounting moved off the serving path.

The gap ledger (PR 16) shows a warm fast-path statement spending a
measurable slice of its end-to-end wall inside the completion finally
block — sql_audit record assembly, statement-summary and host-tax folds,
metrics bulk, timeline record — all of it host work the CLIENT has no
reason to wait for. With ob_enable_completion_drain on, the serving
thread snapshots what those folds need (plain values: the ledger is
re-armed in place for the session's next statement) and hands a closure
to this bounded drain; the wire write happens first, the accounting
lands a moment later.

Exactly-once, no drops: a full queue (or a closed drain) runs the
closure INLINE on the submitting thread — backpressure degrades latency,
never accounting. flush() is the read-your-own-accounting barrier for
tools and tests; virtual-table materialization calls it so
`SELECT ... FROM sql_audit` still observes every prior statement."""

from __future__ import annotations

import threading
from collections import deque


class CompletionDrain:
    """One daemon worker over a bounded deque of zero-arg closures."""

    def __init__(self, depth: int = 256, metrics=None):
        self.depth = int(depth)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._work: deque = deque()
        self._wake = threading.Condition(self._lock)
        self._thread = None
        self._closed = False
        # a generation counter + drained-count pair lets flush() wait for
        # "everything submitted before now" without tracking identities
        self.submitted = 0
        self.drained = 0
        self.inline = 0
        self.errors = 0

    # ------------------------------------------------------------ submit
    def submit(self, fn) -> None:
        """Run `fn` exactly once: queued to the worker normally, inline
        on this thread when the drain is full or closed."""
        with self._lock:
            if not self._closed and len(self._work) < self.depth:
                self._work.append(fn)
                self.submitted += 1
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="completion-drain",
                        daemon=True)
                    self._thread.start()
                self._wake.notify()
                return
            self.inline += 1
        self._call(fn)

    def _call(self, fn) -> None:
        try:
            fn()
        except Exception:
            self.errors += 1
            m = self.metrics
            if m is not None and m.enabled:
                m.add("completion drain errors")

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._work and not self._closed:
                    self._wake.wait()
                if not self._work and self._closed:
                    return
                fn = self._work.popleft()
            self._call(fn)
            with self._lock:
                self.drained += 1
                self._wake.notify_all()

    # ----------------------------------------------------------- barrier
    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every closure submitted before this call has run.
        Returns False on timeout (the worker is wedged — accounting will
        still land, just later)."""
        import time as _time

        with self._lock:
            target = self.submitted
            deadline = _time.monotonic() + timeout
            while self.drained < target:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                self._wake.wait(left)
        return True

    def close(self) -> None:
        """Stop accepting queued work and drain the backlog INLINE (the
        worker may already be gone at interpreter shutdown; accounting
        must still land exactly once)."""
        with self._lock:
            self._closed = True
            backlog = list(self._work)
            self._work.clear()
            self.drained += len(backlog)
            self._wake.notify_all()
        for fn in backlog:
            self._call(fn)

    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "drained": self.drained,
                "inline": self.inline,
                "errors": self.errors,
                "queued": len(self._work),
            }
