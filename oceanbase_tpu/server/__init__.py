"""Server layer: the observer analog.

Reference surface: src/observer — the process that binds the SQL engine,
storage, transactions and replication into one service: statement dispatch
(ObMPQuery::process, observer/mysql/obmp_query.cpp:53), DDL, and sessions.

database.py  Database/DbSession: full-statement SQL (DDL + DML + SELECT)
             over a replicated LocalCluster, with the analytic engine
             reading MVCC snapshots marshalled to the device.
"""

from .database import Database, DbSession

__all__ = ["Database", "DbSession"]
