"""Cross-session continuous-batching statement scheduler.

PR 4's fast path made ONE session cheap; PR 5 folded concurrent hits on
the SAME cached statement into one vmapped dispatch — but with a
group-commit window protocol: the first arrival became a leader and
held the window open for `ob_batch_max_wait_us` even when the device
sat idle, and the window went cold between cohorts. This module keeps
the lane-packing + batched-dispatch machinery (packed qparam vectors
stacked into a [B, nslots] block riding ONE
engine.executor.PreparedPlan.run_batched_host execution) but replaces
the window protocol with CONTINUOUS BATCHING, the discipline inference
stacks use to keep an accelerator saturated:

  * a cluster-wide DispatchGate counts in-flight dispatches. A
    statement that finds the gate idle runs the solo fast path
    IMMEDIATELY — no fixed leader wait on an idle device.
  * while anything is in flight, arrivals coalesce into per-(text_key,
    entry) groups queued behind it — across DIFFERENT cached plans, so
    the dispatch queue stays warm from one plan's cohort to the next.
  * every finished dispatch (batched or tracked solo) hands its gate
    token to the next queued group: batches emerge exactly when the
    device is the bottleneck, sized by how much traffic accumulated
    behind the previous dispatch.
  * admission across tenant queues is a weighted smooth-deficit
    round-robin seeded from TenantUnit.weight — a noisy tenant's
    backlog cannot starve a quiet tenant's cohort.
  * tenant QoS goes beyond dispatch ORDER: every gated statement also
    holds one of `ob_tenant_admission_slots` running permits, allotted
    by weight share. A flooding tenant saturates only its own share
    (it may borrow idle headroom, but an ACTIVE tenant's reserved
    share is untouchable) — so a quiet tenant's latency stays near its
    solo profile even when the contention is upstream of the device,
    in CPU time across session threads. Single-tenant clusters bypass
    the permit entirely.

Backpressure surfaces on the existing wait events: a queued leader's
gate wait lands on "stmt batch window" (the PR-5 window event — same
meaning: time a cohort waited before its dispatch), and worker-pool
admission stays on "tenant worker queue" upstream in DbSession.sql.

Token contract (the one invariant everything hangs on): every
execute() call that returns None leaves EXACTLY ONE gate busy token
held for the caller's solo fast-path run; the caller must bracket that
run with solo_done() (DbSession._fast_select does), which hands the
token to the next queued group. A returned ResultSet carries no token
— its dispatch already released one.

Every degradation is graceful and counted: a non-batchable plan (no
parameter slots / legacy tuple ABI) bypasses, a full per-tenant queue
sheds to solo, a leader admitted alone runs solo, a follower that
outwaits `ob_batch_follower_timeout` pulls its lane OUT of the batch
under the lock (neither device-executed nor counted) and re-executes
solo, a batch whose dispatch raised sends every lane back to the solo
path, and shutdown() fails every forming group to solo.

Privilege re-checks stay PER SESSION in DbSession._fast_select, before
the batcher is ever consulted — a REVOKE between repeats bites batched
entries the same as solo ones.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..ops.hashing import next_pow2
from ..share import gap_ledger as _gl


class BatcherShutdown(RuntimeError):
    """Parked on forming groups when shutdown() fails them to solo."""


# sentinel error for "group degenerated to one lane — run it solo"
_SOLO = RuntimeError("solo")


# Fused-pair executables for bucket-shape coalescing: two DIFFERENT
# plans' vmapped cohorts inlined into ONE jitted program (one dispatch,
# one D2H). Keyed by the identity of each plan's live jitted callable
# plus the pow2 buckets — a recompile swaps the callable, so its old
# combos simply stop matching and age out of the bounded LRU. The cache
# value pins both callables: an id() key must never alias a recycled id
# after the originals are garbage-collected.
_COMBO_CACHE: OrderedDict = OrderedDict()
_COMBO_CAP = 16


def _combo_run(pa, pb, qa: np.ndarray, qb: np.ndarray):
    """ONE device dispatch for two different plans' batched cohorts.
    Returns a pair of run_batched_host-shaped host tuples
    ((hcols, hvalid, hsel, schema, dicts) x2), or None when the pair
    cannot fuse (untraceable executable, trace/dispatch failure, or
    capacity overflow on either plan — the fallback path owns the
    bump/recompile loop)."""
    import jax

    from ..engine.executor import _BATCH_COMPILE_LOCK

    if not (getattr(pa, "_traceable", False)
            and getattr(pb, "_traceable", False)):
        return None
    ba = next_pow2(int(qa.shape[0]))
    bb = next_pow2(int(qb.shape[0]))
    if ba > qa.shape[0]:
        qa = np.concatenate(
            [qa, np.repeat(qa[:1], ba - qa.shape[0], axis=0)])
    if bb > qb.shape[0]:
        qb = np.concatenate(
            [qb, np.repeat(qb[:1], bb - qb.shape[0], axis=0)])
    fa, fb = pa.jitted, pb.jitted
    key = (id(fa), id(fb), ba, bb)
    try:
        hit = _COMBO_CACHE.get(key)
        if hit is not None:
            _COMBO_CACHE.move_to_end(key)
            outs = hit[0](pa._inputs(), qa, pb._inputs(), qb)
        else:
            # build + first-trace under the batch compile lock: tracing
            # re-enters plan emission's process-global parameter frame,
            # exactly like the single-plan buckets
            with _BATCH_COMPILE_LOCK:
                hit = _COMBO_CACHE.get(key)
                if hit is None:
                    def run(ia, qva, ib, qvb, _fa=fa, _fb=fb):
                        return (
                            jax.vmap(_fa, in_axes=(None, 0))(ia, qva),
                            jax.vmap(_fb, in_axes=(None, 0))(ib, qvb),
                        )

                    fn = jax.jit(run)
                    outs = fn(pa._inputs(), qa, pb._inputs(), qb)
                    _COMBO_CACHE[key] = (fn, fa, fb)
                    while len(_COMBO_CACHE) > _COMBO_CAP:
                        _COMBO_CACHE.popitem(last=False)
                else:
                    outs = hit[0](pa._inputs(), qa, pb._inputs(), qb)
        (outa, ovfa), (outb, ovfb) = outs
        hovfa, hca, hva, hsa, hovfb, hcb, hvb, hsb = jax.device_get(
            (ovfa, outa.cols, outa.valid, outa.sel,
             ovfb, outb.cols, outb.valid, outb.sel))
    except Exception:  # noqa: BLE001 — the pair degrades, never fails
        return None
    if pa._overflows(np.asarray(hovfa).max(axis=0)):
        return None
    if pb._overflows(np.asarray(hovfb).max(axis=0)):
        return None
    return ((hca, hva, hsa, outa.schema, outa.dicts),
            (hcb, hvb, hsb, outb.schema, outb.dicts))


class _Batch:
    """One forming / in-flight group of same-entry fast-path hits."""

    __slots__ = ("key", "entry", "tenant", "rows", "dead", "max_size",
                 "batch_id", "closed", "queued", "admitted", "dispatching",
                 "adopted", "full", "done", "results", "error",
                 "dispatch_s", "d2h_bytes", "nlanes")

    def __init__(self, key, entry, tenant: str, batch_id: int,
                 max_size: int):
        self.key = key
        self.entry = entry  # sql.plan_cache.CacheEntry (pins the plan)
        self.tenant = tenant
        self.rows: list[np.ndarray] = []  # packed qparam vector per lane
        self.dead: set[int] = set()  # lanes whose follower gave up
        self.max_size = max_size  # the LEADER's clamp governs the batch
        self.batch_id = batch_id
        self.closed = False  # no more joiners (filled/dispatching)
        self.queued = False  # sitting in its tenant's gate queue
        self.admitted = False  # gate handed this group a busy token
        self.dispatching = False  # lanes frozen; device execution begun
        self.adopted = False  # riding another leader's fused pair dispatch
        self.full = threading.Event()  # admission/fill/shutdown wake
        self.done = threading.Event()  # results scattered (or error set)
        self.results: list | None = None  # ResultSet per ORIGINAL lane
        self.error: Exception | None = None
        self.dispatch_s = 0.0
        self.d2h_bytes = 0
        self.nlanes = 0  # alive lanes actually dispatched


class DispatchGate:
    """Cluster-wide continuous-batching gate: the in-flight dispatch
    count plus per-tenant queues of forming groups with weighted
    smooth-deficit round-robin admission. ONE gate per cluster, shared
    by every tenant's StatementBatcher the way cluster._timeline is
    shared — cross-tenant fairness only exists inside one ledger.

    Everything below register() is called with self.lock HELD: the
    tenant batchers adopt this lock as their own so group formation and
    queue movement are one atomic domain."""

    def __init__(self):
        self.lock = threading.Lock()
        self.busy = 0  # in-flight dispatches (batched + tracked solo)
        self._queues: dict[str, deque] = {}
        self._weights: dict[str, float] = {}
        self._credits: dict[str, float] = {}
        self.queued_groups = 0
        self.depth_hwm = 0
        self.admissions = 0
        # test seam: when a list, every admission appends its tenant
        self.admit_log: list | None = None
        # weighted admission slots (ob_tenant_admission_slots): dispatch
        # ORDER alone cannot protect a quiet tenant when the contention
        # is upstream of the device (CPU time across hundreds of session
        # threads), so gated statements also hold one of `slots` running
        # permits, allocated by weight share. Single-tenant clusters
        # bypass the whole mechanism.
        self.slots = 8
        self._running: dict[str, int] = {}
        self._adm_waiting: dict[str, int] = {}
        self._adm_cv = threading.Condition(self.lock)

    def register(self, tenant: str, weight: float = 1.0) -> None:
        with self.lock:
            self._ensure(tenant, weight)
            self._weights[tenant] = max(float(weight), 1e-3)

    # ---------------------------------------- weighted admission slots
    def _share(self, tenant: str) -> int:
        # floor, not ceil: a flooding tenant must not ROUND UP into
        # capacity its weight doesn't buy; min 1 guarantees progress
        total_w = sum(self._weights.values())
        return max(1, int(self.slots * self._weights[tenant] // total_w))

    def _can_run(self, tenant: str) -> bool:
        if sum(self._running.values()) >= self.slots:
            return False
        if self._running[tenant] < self._share(tenant):
            return True
        # over its share: borrow free headroom only while every OTHER
        # tenant is fully idle — an ACTIVE tenant keeps its reserved
        # share even when it is not using all of it yet
        return all(self._running[o] == 0 and self._adm_waiting[o] == 0
                   for o in self._weights if o != tenant)

    def acquire_slot(self, tenant: str, valve_s: float = 5.0) -> float:
        """Take one running permit for a gated statement; returns the
        seconds waited (0.0 = admitted immediately). The wait releases
        the gate lock (Condition), so a throttled flood parks GIL-free.
        `valve_s` bounds the wait — after it the statement runs anyway
        (admission is QoS, not correctness; a missed release must not
        wedge serving)."""
        with self._adm_cv:
            self._ensure(tenant)
            if len(self._weights) < 2 or self._can_run(tenant):
                self._running[tenant] += 1
                return 0.0
            t0 = time.perf_counter()
            deadline = t0 + valve_s
            self._adm_waiting[tenant] += 1
            try:
                while not self._can_run(tenant):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._adm_cv.wait(remaining)
            finally:
                self._adm_waiting[tenant] -= 1
            self._running[tenant] += 1
            return time.perf_counter() - t0

    def release_slot(self, tenant: str) -> None:
        with self._adm_cv:
            n = self._running.get(tenant, 0)
            self._running[tenant] = n - 1 if n > 0 else 0
            # wake only when some waiter is actually eligible — with a
            # pinned flood, MOST releases (the quiet tenant's) change
            # nothing for the waiters, and waking a herd of throttled
            # threads just to re-sleep burns the very CPU the throttle
            # protects. (A waiter that would miss a wake from a config
            # bump self-heals on its bounded wait.)
            if any(w > 0 and self._can_run(t)
                   for t, w in self._adm_waiting.items()):
                self._adm_cv.notify_all()

    # ---------------------------------------------- lock-held interface
    def _ensure(self, tenant: str, weight: float = 1.0) -> None:
        if tenant not in self._weights:
            self._weights[tenant] = max(float(weight), 1e-3)
            self._queues[tenant] = deque()
            self._credits[tenant] = 0.0
            self._running[tenant] = 0
            self._adm_waiting[tenant] = 0

    def queue_len(self, tenant: str) -> int:
        q = self._queues.get(tenant)
        return len(q) if q is not None else 0

    def enqueue(self, b: _Batch) -> None:
        self._ensure(b.tenant)
        self._queues[b.tenant].append(b)
        b.queued = True
        self.queued_groups += 1
        if self.queued_groups > self.depth_hwm:
            self.depth_hwm = self.queued_groups

    def remove(self, b: _Batch) -> None:
        if not b.queued:
            return
        b.queued = False
        q = self._queues.get(b.tenant)
        if q is None:
            return
        try:
            q.remove(b)
        except ValueError:
            return
        self.queued_groups -= 1

    def admit_next(self) -> _Batch | None:
        """Weighted smooth-deficit pick across the non-empty tenant
        queues; transfers the caller's busy token to the admitted group
        and wakes its leader. None when nothing waits."""
        waiting = [t for t, q in self._queues.items() if q]
        if not waiting:
            return None
        for t in waiting:
            self._credits[t] += self._weights[t]
        pick = max(waiting, key=lambda t: (self._credits[t], t))
        total = sum(self._weights[t] for t in waiting)
        self._credits[pick] -= total
        # bound credit drift for tenants that drift in and out of the
        # waiting set — a long absence must not bank unbounded priority
        for t in waiting:
            c = self._credits[t]
            if c > total:
                self._credits[t] = total
            elif c < -total:
                self._credits[t] = -total
        b = self._queues[pick].popleft()
        b.queued = False
        self.queued_groups -= 1
        b.admitted = True
        self.admissions += 1
        if self.admit_log is not None:
            self.admit_log.append(pick)
        b.full.set()
        return b

    def release(self) -> None:
        """One in-flight dispatch finished: hand its token to the next
        queued group, else go idle."""
        if self.admit_next() is None:
            self.busy -= 1


class StatementBatcher:
    """Collects concurrent fast-path hits into batched device
    dispatches behind a shared DispatchGate. One instance per Database
    (tenant); gates/queues are cluster-shared; safe for any number of
    session threads."""

    def __init__(self, metrics=None, gate: DispatchGate | None = None,
                 tenant: str = "sys"):
        self.gate = gate if gate is not None else DispatchGate()
        # group formation and queue movement share ONE lock domain
        self._lock = self.gate.lock
        self._forming: dict[tuple, _Batch] = {}
        self._ids = itertools.count(1)
        self.metrics = metrics
        self.tenant = tenant
        self.gate.register(tenant)
        # hook: share/timeline.ServingTimeline — each cohort's ONE device
        # dispatch plus its lane-occupancy land on the serving timeline
        self.timeline = None
        # A/B switch (latency_bench --sessions: batching on vs off)
        self.enabled = True
        # bucket-shape coalescing (ob_enable_batch_coalesce): a leader
        # about to dispatch adopts ONE queued group of a DIFFERENT plan
        # whose alive cohort pads to the same pow2 bucket — two cohorts,
        # one fused device program, one D2H
        self.coalesce_enabled = True
        # config-derived degradation bounds (ob_batch_follower_timeout /
        # ob_batch_queue_depth); Database re-seeds these on hot reload
        self.follower_timeout_s = 10.0
        self.queue_depth = 32
        # hook: engine/memory_governor.MemoryGovernor — while the device
        # ledger is under pressure, wide batches (one dispatch holding
        # many lanes' working sets at once) are exactly the wrong shape;
        # execute() clamps the cohort width until pressure clears
        self.governor = None

    # ------------------------------------------------------------ public
    def execute(self, hit, max_size: int, wait_us: int):
        """Run one fast-path hit through the continuous-batching gate.

        Returns the lane's ResultSet — with `rs.batch_info = (batch_id,
        batch_size, wait_us, dispatch_s, d2h_share)` attached for the
        audit/profile plumbing — or None when the statement should
        degrade to the plain solo fast path (idle gate, ineligible
        plan, follower timeout, dispatch error, shutdown). EVERY None
        return leaves one gate busy token held for that solo run: the
        caller must bracket it with solo_done()."""
        m = self.metrics
        gate = self.gate
        entry = hit.entry
        prepared = entry.prepared
        gov = self.governor
        if gov is not None and max_size > 2 and gov.under_pressure():
            # device memory pressure: narrow the cohort so one batched
            # dispatch can't concentrate the working sets the governor
            # is busy queueing individual statements over
            max_size = 2
            if m is not None and m.enabled:
                m.add("stmt batch memory clamp")
        if not self.enabled or max_size <= 1:
            return self._solo_token()
        if not getattr(prepared, "batchable", False):
            if m is not None and m.enabled:
                m.bulk(adds=(("stmt batch bypass", 1),
                             ("stmt batch bypass: not batchable", 1)))
            return self._solo_token()
        qrow = prepared.bind(hit.values, entry.dtypes)
        if not isinstance(qrow, np.ndarray):
            # legacy tuple ABI (should not happen when batchable): bypass
            if m is not None and m.enabled:
                m.bulk(adds=(("stmt batch bypass", 1),
                             ("stmt batch bypass: unpacked params", 1)))
            return self._solo_token()

        key = (hit.text_key, id(entry))
        t0 = time.perf_counter()
        with self._lock:
            b = self._forming.get(key)
            if b is not None and not b.closed:
                lane = len(b.rows)
                b.rows.append(qrow)
                leader = False
                if len(b.rows) >= b.max_size:
                    # this joiner filled the batch: dispatch NOW — pull
                    # the group off the queue and wake its leader
                    b.closed = True
                    self._forming.pop(key, None)
                    gate.remove(b)
                    b.full.set()
            elif gate.busy == 0 and gate.queued_groups == 0:
                # idle device, empty queue: the solo fast path dispatches
                # IMMEDIATELY — no fixed leader wait. Taking the busy
                # token is what makes the scheduler continuous: arrivals
                # during this solo flight coalesce behind it.
                gate.busy += 1
                if m is not None and m.enabled:
                    m.add("stmt batch solo")
                return None
            elif gate.queue_len(self.tenant) >= self.queue_depth:
                # per-tenant queue bound: shed to solo instead of
                # growing the backlog without bound
                gate.busy += 1
                if m is not None and m.enabled:
                    m.bulk(adds=(("stmt batch bypass", 1),
                                 ("stmt batch bypass: queue full", 1)))
                return None
            else:
                b = _Batch(key, entry, self.tenant, next(self._ids),
                           max_size)
                b.rows.append(qrow)
                lane = 0
                self._forming[key] = b
                gate.enqueue(b)
                leader = True

        if leader:
            if not self._lead(b, wait_us, m):
                return None
        elif not self._follow(b, lane, wait_us, m):
            return None
        rs = b.results[lane]
        rs.batch_info = (
            b.batch_id,
            b.nlanes,
            int((time.perf_counter() - t0
                 - (b.dispatch_s if leader else 0.0)) * 1e6),
            b.dispatch_s,
            b.d2h_bytes // max(b.nlanes, 1),
        )
        return rs

    def admit(self) -> None:
        """Weighted tenant admission for one gated statement: take a
        running permit from the shared gate (DbSession._fast_select
        brackets the whole gated execution with admit()/admit_done()).
        A tenant within its weight share never waits; a flooding tenant
        over its share parks here — on the "tenant admission" wait
        event — while other tenants are active."""
        waited = self.gate.acquire_slot(self.tenant)
        if waited > 0.0:
            m = self.metrics
            if m is not None and m.enabled:
                m.add("stmt admission throttled")
                m.wait("tenant admission", waited)
            led = _gl.current()
            if led is not None:
                # host-tax: the statement's thread parked here
                led.add("tenant permit", waited)

    def admit_done(self) -> None:
        self.gate.release_slot(self.tenant)

    def solo_done(self) -> None:
        """Release the busy token a None-returning execute() left held,
        AFTER the caller's solo fast path finished — handing it to the
        next queued group (one admission per completed dispatch is what
        keeps the queue draining)."""
        with self._lock:
            self.gate.release()

    def shutdown(self) -> None:
        """Refuse new batches and fail every forming group to the solo
        path (Database.close): queued leaders and waiting followers
        wake immediately and re-execute solo."""
        with self._lock:
            self.enabled = False
            for b in list(self._forming.values()):
                b.error = BatcherShutdown("batcher shutdown")
                self.gate.remove(b)
                b.full.set()
                b.done.set()
            self._forming.clear()

    # ----------------------------------------------------------- private
    def _solo_token(self):
        with self._lock:
            self.gate.busy += 1
        return None

    def _lead(self, b: _Batch, wait_us: int, m) -> bool:
        """Leader half: wait for gate admission (or fill / shutdown),
        then dispatch the surviving lanes. True = results scattered;
        False = degrade to solo with the busy token held."""
        gate = self.gate
        # The admission wait IS the backpressure surface — it lands on
        # the PR-5 "stmt batch window" wait event (same meaning: time a
        # cohort waited before its dispatch). Bounded at 2x the follower
        # bound so a wedged gate degrades followers first (they shrink
        # the batch) and the leader eventually dispatches regardless.
        bound = wait_us / 1e6 + 2.0 * self.follower_timeout_s
        t0 = time.perf_counter()
        b.full.wait(bound)
        waited = time.perf_counter() - t0
        if m is not None and m.enabled:
            m.wait("stmt batch window", waited)
        led = _gl.current()
        if led is not None:
            # host-tax hint on the LEADER's ledger: its group-commit
            # window wait (the dispatch is added separately, once)
            led.add("batch window", waited)
        rider = None
        with self._lock:
            b.closed = True
            if self._forming.get(b.key) is b:
                del self._forming[b.key]
            gate.remove(b)
            adopted = b.adopted
            if not b.admitted and not adopted:
                # filled before admission, gate wedged, or shutdown:
                # dispatch on a fresh token (a filled batch must not
                # keep waiting on an unrelated dispatch)
                gate.busy += 1
            if b.error is not None:  # shutdown raced in
                if adopted:
                    gate.busy += 1  # an adopted group holds no token
                b.done.set()
                return False
            if not adopted:
                alive = [i for i in range(len(b.rows))
                         if i not in b.dead]
                b.dispatching = True
                if self.coalesce_enabled and len(alive) >= 2:
                    rider = self._adopt_rider(b, next_pow2(len(alive)))
            depth = gate.queued_groups
        if adopted:
            # another leader's fused pair dispatch carries this cohort:
            # wait for its scatter instead of dispatching (and holding a
            # token) ourselves
            return self._ride(b, m)
        tl = self.timeline
        if tl is not None and tl.enabled:
            tl.record_gate(waited, queued=depth)
        if len(alive) == 1:
            # nobody (left) to share with: the solo fast path is
            # strictly cheaper than a padded 2-lane batch (and compiles
            # nothing new); keep the token for it
            b.error = _SOLO
            b.done.set()
            if m is not None and m.enabled:
                m.add("stmt batch solo")
            return False
        if rider is not None:
            rb, ralive = rider
            if not self._dispatch_pair(b, alive, rb, ralive, depth):
                # the pair couldn't fuse: two separate dispatches on the
                # one token (the rider's lanes are parked on rb.done and
                # complete either way)
                self._dispatch(rb, ralive, depth)
                self._dispatch(b, alive, depth)
        else:
            self._dispatch(b, alive, depth)
        if b.error is not None:
            return False  # token kept for the leader's own solo re-run
        with self._lock:
            gate.release()
        return True

    def _ride(self, b: _Batch, m) -> bool:
        """Adopted leader half: the adopting leader dispatches and
        scatters for us. On its error — or a timeout with the adopter
        wedged — take a fresh token (adopted groups hold none) and
        degrade this lane to solo; followers degrade themselves off
        b.error exactly as after a failed dispatch."""
        ok = b.done.wait(2.0 * self.follower_timeout_s)
        if ok and b.error is None:
            if m is not None and m.enabled:
                m.add("stmt batch coalesced rider")
            return True
        with self._lock:
            self.gate.busy += 1
        if m is not None and m.enabled:
            m.add("stmt batch coalesced degrade")
        return False

    def _adopt_rider(self, b: _Batch, bucket: int):
        """Called with the gate lock HELD by a leader about to dispatch
        `b`: pick ONE queued group — any tenant, own queue first — whose
        alive cohort pads to the same pow2 bucket, freeze it, and pull
        it out of the queue as a rider on this dispatch. Returns
        (rider_batch, rider_alive_lanes) or None. The rider's leader
        wakes on full (sees adopted=True, skips its token take) and its
        followers ride the dispatch out because dispatching is set."""
        gate = self.gate
        queues = [gate._queues.get(self.tenant)]
        queues += [q for t, q in gate._queues.items()
                   if t != self.tenant]
        for q in queues:
            if not q:
                continue
            for rb in q:
                if rb is b or rb.error is not None or rb.dispatching:
                    continue
                if not getattr(rb.entry.prepared, "_traceable", False):
                    continue
                ralive = [i for i in range(len(rb.rows))
                          if i not in rb.dead]
                if len(ralive) < 2 or next_pow2(len(ralive)) != bucket:
                    continue
                rb.closed = True
                rb.dispatching = True
                rb.adopted = True
                gate.remove(rb)
                # same-tenant riders share this batcher's forming map;
                # a cross-tenant rider's leader cleans its own up
                if self._forming.get(rb.key) is rb:
                    del self._forming[rb.key]
                rb.full.set()
                return rb, ralive
        return None

    def _follow(self, b: _Batch, lane: int, wait_us: int, m) -> bool:
        """Follower half: wait for the leader's scatter. On timeout
        BEFORE the dispatch froze the lanes, pull our lane out of the
        batch under the lock — it is neither device-executed nor
        counted — and re-execute solo on a fresh token."""
        bound = wait_us / 1e6 + self.follower_timeout_s
        tw = time.perf_counter()
        try:
            return self._follow_inner(b, lane, bound, m)
        finally:
            led = _gl.current()
            if led is not None:
                # host-tax hint: a FOLLOWER attributes its whole wait
                # (window + the leader's dispatch it rode out) as batch
                # window — the cohort's device busy is the leader's to
                # count, exactly once
                led.add("batch window", time.perf_counter() - tw)

    def _follow_inner(self, b: _Batch, lane: int, bound: float, m) -> bool:
        ok = b.done.wait(bound)
        if not ok:
            with self._lock:
                if not b.dispatching and not b.done.is_set():
                    b.dead.add(lane)
                    self.gate.busy += 1
                    if m is not None and m.enabled:
                        m.add("stmt batch follower timeouts")
                    return False
            # the dispatch already froze the lanes when the timer fired:
            # our row IS in the device batch — ride the dispatch out
            ok = b.done.wait(self.follower_timeout_s)
            if not ok:
                # leader died mid-dispatch: re-execute solo
                with self._lock:
                    self.gate.busy += 1
                if m is not None and m.enabled:
                    m.add("stmt batch follower timeouts")
                return False
        if b.error is not None:
            with self._lock:
                self.gate.busy += 1
            return False
        return True

    def _scatter(self, b: _Batch, alive: list[int], hcols, hvalid, hsel,
                 schema, dicts) -> None:
        """Slice the padded device block down to the alive cohort and
        scatter per-lane ResultSets back to their ORIGINAL lane slots
        (one vectorized gather for the whole batch, not nb per-lane
        gathers). Shared by the single-plan and fused-pair dispatches."""
        from ..core.column import host_rows_batched
        from ..engine.session import ResultSet

        b.d2h_bytes = sum(
            int(getattr(a, "nbytes", 0))
            for d in (hcols, hvalid) for a in d.values()
        ) + int(getattr(hsel, "nbytes", 0))
        names = b.entry.output_names
        nb = len(alive)
        b.nlanes = nb
        lanes = host_rows_batched(
            schema, dicts,
            {n: a[:nb] for n, a in hcols.items()},
            {n: a[:nb] for n, a in hvalid.items()},
            hsel[:nb],
        )
        results: list = [None] * len(b.rows)
        for j, i in enumerate(alive):
            lane = lanes[j]
            results[i] = ResultSet(
                names, {n: lane[n] for n in names},
                plan_cache_hit=True, fast_path_hit=True)
        b.results = results

    def _dispatch_pair(self, b: _Batch, alive: list[int], rb: _Batch,
                       ralive: list[int], depth: int) -> bool:
        """Bucket-shape coalescing: ONE fused device program carrying
        TWO different plans' cohorts (same pow2 bucket) — both vmapped
        executables inlined into a single jit, one dispatch, one
        device_get for every lane of both. True = both groups scattered
        and done. False = the pair couldn't fuse; NOTHING is half-done
        on that path (no done events, no results) — the caller falls
        back to two separate dispatches."""
        m = self.metrics
        t0 = time.perf_counter()
        try:
            qa = np.stack([b.rows[i] for i in alive])
            qb = np.stack([rb.rows[i] for i in ralive])
            res = _combo_run(b.entry.prepared, rb.entry.prepared, qa, qb)
            if res is None:
                return False
            dispatch_s = time.perf_counter() - t0
            led = _gl.current()
            if led is not None:
                # ONE device execution on the ADOPTING leader's ledger;
                # the rider's lanes hint only their window wait — same
                # exactly-once discipline as the single-plan dispatch
                led.add("device dispatch", dispatch_s)
                led.device(dispatch_s)
            b.dispatch_s = rb.dispatch_s = dispatch_s
            (ha, hva, hsa, sca, dca), (hb, hvb, hsb, scb, dcb) = res
            self._scatter(b, alive, ha, hva, hsa, sca, dca)
            self._scatter(rb, ralive, hb, hvb, hsb, scb, dcb)
        except Exception:  # noqa: BLE001 — fall back to two dispatches
            return False
        na, nr = len(alive), len(ralive)
        if m is not None and m.enabled:
            m.bulk(adds=(
                ("stmt batched dispatches", 1),
                ("stmt batched statements", na + nr),
                (f"stmt batch size {next_pow2(na)}", 1),
                ("stmt batch coalesced dispatches", 1),
                ("stmt batch coalesced lanes", na + nr),
            ))
            m.gauge_max("stmt sched queue depth hwm", depth)
        tl = self.timeline
        if tl is not None and tl.enabled:
            # one fused dispatch carrying both cohorts' lanes
            tl.record_batch(dispatch_s, na + nr, queued=depth)
        b.done.set()
        rb.done.set()
        return True

    def _dispatch(self, b: _Batch, alive: list[int], depth: int) -> None:
        """Leader half: stack the ALIVE lanes, ONE batched device
        execution, scatter per-lane ResultSets back to their original
        lane slots. Any failure parks the error and sends every lane
        back to the solo path."""
        m = self.metrics
        t0 = time.perf_counter()
        try:
            qblock = np.stack([b.rows[i] for i in alive])
            prepared = b.entry.prepared
            hcols, hvalid, hsel, schema, dicts = (
                prepared.run_batched_host(qblock))
            b.dispatch_s = time.perf_counter() - t0
            led = _gl.current()
            if led is not None:
                # _dispatch runs on the leader's thread: the cohort's ONE
                # batched device execution lands on the LEADER's ledger
                # (followers hint only their window wait) — the double-
                # count regression test anchors here
                led.add("device dispatch", b.dispatch_s)
                led.device(b.dispatch_s)
            self._scatter(b, alive, hcols, hvalid, hsel, schema, dicts)
            nb = b.nlanes
            if m is not None and m.enabled:
                # batch-size histogram as per-pow2-bucket counters (the
                # latency Histogram's bounds are seconds, not lanes)
                m.bulk(adds=(
                    ("stmt batched dispatches", 1),
                    ("stmt batched statements", nb),
                    (f"stmt batch size {next_pow2(nb)}", 1),
                ))
                m.gauge_max("stmt sched queue depth hwm", depth)
            tl = self.timeline
            if tl is not None and tl.enabled:
                # the cohort's single dispatch (lanes here never reach
                # the engine's solo record_exec — no double counting)
                tl.record_batch(b.dispatch_s, nb, queued=depth)
        except Exception as e:  # noqa: BLE001 — lanes degrade to solo
            b.error = e
            if m is not None and m.enabled:
                m.add("stmt batch dispatch errors")
        finally:
            b.done.set()
