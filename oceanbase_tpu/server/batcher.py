"""Cross-session statement micro-batcher.

PR 4's fast path made ONE session cheap; under concurrent traffic every
statement still paid its own device dispatch — 64 concurrent point reads
over the same cached plan cost 64 XLA launches. This module amortizes
them the way palf amortizes fsyncs (group commit) and inference stacks
amortize forward passes (continuous batching): concurrent fast-path hits
that rebind the SAME FastEntry (same plan, same param slots — different
literal values) stack their packed parameter vectors into a [B, nslots]
block and ride ONE batched device execution
(engine.executor.PreparedPlan.run_batched_host), whose per-lane results
scatter back to the waiting sessions.

Window protocol (group-commit style): the first session to arrive for a
(text_key, entry) key becomes the batch LEADER and holds the window open
for `ob_batch_max_wait_us`; followers join until `ob_batch_max_size`
lanes fill (which cuts the window short) or the leader's timer fires.
The leader dispatches, scatters, and wakes the followers. Every
degradation is graceful and counted: a non-batchable plan (no parameter
slots / legacy tuple ABI) bypasses, a leader left alone after the window
runs the plain solo fast path, a follower that outwaits a wedged leader
re-executes solo, and a batch whose dispatch raised sends every lane
back to the solo path — which surfaces the real error and invalidates
the text entry exactly as before.

Privilege re-checks stay PER SESSION in DbSession._fast_select, before
the batcher is ever consulted — a REVOKE between repeats bites batched
entries the same as solo ones.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from ..ops.hashing import next_pow2


class _Batch:
    """One forming / in-flight group of same-entry fast-path hits."""

    __slots__ = ("key", "entry", "rows", "max_size", "batch_id", "closed",
                 "full", "done", "results", "error", "dispatch_s",
                 "d2h_bytes")

    def __init__(self, key, entry, batch_id: int, max_size: int):
        self.key = key
        self.entry = entry  # sql.plan_cache.CacheEntry (pins the plan)
        self.rows: list[np.ndarray] = []  # packed qparam vector per lane
        self.max_size = max_size  # the LEADER's clamp governs the batch
        self.batch_id = batch_id
        self.closed = False  # no more joiners (filled or window expired)
        self.full = threading.Event()  # wakes the leader early on fill
        self.done = threading.Event()  # results scattered (or error set)
        self.results: list | None = None  # ResultSet per lane
        self.error: Exception | None = None
        self.dispatch_s = 0.0
        self.d2h_bytes = 0


class StatementBatcher:
    """Collects concurrent same-plan fast-path hits into batched device
    dispatches. One instance per Database (tenant); safe for any number
    of session threads."""

    def __init__(self, metrics=None):
        self._lock = threading.Lock()
        self._forming: dict[tuple, _Batch] = {}
        self._ids = itertools.count(1)
        self.metrics = metrics
        # hook: share/timeline.ServingTimeline — each cohort's ONE device
        # dispatch plus its lane-occupancy land on the serving timeline
        self.timeline = None
        # A/B switch (latency_bench --sessions: batching on vs off)
        self.enabled = True

    # ------------------------------------------------------------ public
    def execute(self, hit, max_size: int, wait_us: int):
        """Run one fast-path hit through the batching window.

        Returns the lane's ResultSet — with `rs.batch_info = (batch_id,
        batch_size, wait_us, dispatch_s, d2h_share)` attached for the
        audit/profile plumbing — or None when the statement should
        degrade to the plain solo fast path (ineligible plan, leader left
        alone, follower timeout, dispatch error)."""
        m = self.metrics
        entry = hit.entry
        prepared = entry.prepared
        if not self.enabled or max_size <= 1:
            return None
        if not getattr(prepared, "batchable", False):
            if m is not None and m.enabled:
                m.bulk(adds=(("stmt batch bypass", 1),
                             ("stmt batch bypass: not batchable", 1)))
            return None
        qrow = prepared.bind(hit.values, entry.dtypes)
        if not isinstance(qrow, np.ndarray):
            # legacy tuple ABI (should not happen when batchable): bypass
            if m is not None and m.enabled:
                m.bulk(adds=(("stmt batch bypass", 1),
                             ("stmt batch bypass: unpacked params", 1)))
            return None

        key = (hit.text_key, id(entry))
        t0 = time.perf_counter()
        with self._lock:
            b = self._forming.get(key)
            if b is not None and not b.closed:
                lane = len(b.rows)
                b.rows.append(qrow)
                if len(b.rows) >= b.max_size:
                    # this joiner filled the batch: cut the window short
                    b.closed = True
                    self._forming.pop(key, None)
                    b.full.set()
                leader = False
            else:
                b = _Batch(key, entry, next(self._ids), max_size)
                b.rows.append(qrow)
                lane = 0
                self._forming[key] = b
                leader = True

        if leader:
            if wait_us > 0 and b.max_size > 1:
                if m is not None and m.enabled:
                    with m.waiting("stmt batch window"):
                        b.full.wait(wait_us / 1e6)
                else:
                    b.full.wait(wait_us / 1e6)
            with self._lock:
                b.closed = True
                if self._forming.get(key) is b:
                    del self._forming[key]
            if len(b.rows) == 1:
                # nobody joined: the solo fast path is strictly cheaper
                # than a padded 2-lane batch (and compiles nothing new)
                b.error = RuntimeError("solo")
                b.done.set()
                if m is not None and m.enabled:
                    m.add("stmt batch solo")
                return None
            self._dispatch(b)
        else:
            # generous upper bound: the leader dispatches at most one
            # window + one batched execution after we joined; a miss here
            # means it died mid-flight and we re-execute solo
            ok = b.done.wait(wait_us / 1e6 + 30.0)
            if not ok:
                if m is not None and m.enabled:
                    m.add("stmt batch follower timeouts")
                return None
        if b.error is not None:
            return None
        rs = b.results[lane]
        rs.batch_info = (
            b.batch_id,
            len(b.rows),
            int((time.perf_counter() - t0 - (b.dispatch_s if leader else 0.0))
                * 1e6),
            b.dispatch_s,
            b.d2h_bytes // max(len(b.rows), 1),
        )
        return rs

    # ----------------------------------------------------------- private
    def _dispatch(self, b: _Batch) -> None:
        """Leader half: stack lanes, ONE batched device execution,
        scatter per-lane ResultSets. Any failure parks the error and
        sends every lane back to the solo path."""
        from ..core.column import host_rows_batched
        from ..engine.session import ResultSet

        m = self.metrics
        t0 = time.perf_counter()
        try:
            qblock = np.stack(b.rows)
            prepared = b.entry.prepared
            hcols, hvalid, hsel, schema, dicts = (
                prepared.run_batched_host(qblock))
            b.dispatch_s = time.perf_counter() - t0
            b.d2h_bytes = sum(
                int(getattr(a, "nbytes", 0))
                for d in (hcols, hvalid) for a in d.values()
            ) + int(getattr(hsel, "nbytes", 0))
            names = b.entry.output_names
            nb = len(b.rows)
            # one vectorized scatter for the whole batch (pad lanes
            # sliced off first) instead of nb per-lane gathers
            lanes = host_rows_batched(
                schema, dicts,
                {n: a[:nb] for n, a in hcols.items()},
                {n: a[:nb] for n, a in hvalid.items()},
                hsel[:nb],
            )
            b.results = [
                ResultSet(names, {n: lane[n] for n in names},
                          plan_cache_hit=True, fast_path_hit=True)
                for lane in lanes
            ]
            if m is not None and m.enabled:
                # batch-size histogram as per-pow2-bucket counters (the
                # latency Histogram's bounds are seconds, not lanes)
                m.bulk(adds=(
                    ("stmt batched dispatches", 1),
                    ("stmt batched statements", nb),
                    (f"stmt batch size {next_pow2(nb)}", 1),
                ))
            tl = self.timeline
            if tl is not None and tl.enabled:
                # the cohort's single dispatch (lanes here never reach
                # the engine's solo record_exec — no double counting)
                tl.record_batch(b.dispatch_s, nb)
        except Exception as e:  # noqa: BLE001 — lanes degrade to solo
            b.error = e
            if m is not None and m.enabled:
                m.add("stmt batch dispatch errors")
        finally:
            b.done.set()
