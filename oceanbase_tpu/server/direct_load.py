"""Direct load: the bulk-ingest bypass path.

Reference surface: observer/table_load (ObTableLoadService,
ob_table_load_service.h:35) + storage/direct_load — bulk loads skip the
memtable/redo path entirely: rows are externally sorted by rowkey and
written straight into sstables, which are then installed on the tablet
(and replicated by data movement rather than redo).

The rebuild mirrors that: vectorized host coercion (no per-row staging),
one np.lexsort by rowkey, one sstable build, installed as a delta on every
replica at a single load version. Dictionary growth is NOT marked durable
here — the log carries no record of this load, so the next regular commit
re-logs any new dictionary entries (see TableInfo.logged_dict_len), and
point-in-time recovery of direct-loaded data requires a backup taken after
the load, exactly like the reference.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import TypeKind
from ..storage.sstable import SSTable, write_sstable


class DirectLoadError(Exception):
    pass


def _bulk_encode(d, arr: np.ndarray) -> np.ndarray:
    """Vectorized append-dictionary encode: one encode_one per UNIQUE
    string, inverse-mapped to rows."""
    arr = np.asarray(arr)
    if arr.dtype.kind not in ("U", "S"):
        arr = arr.astype(str)
    uniq, inv = np.unique(arr, return_inverse=True)
    codes = np.fromiter(
        (d.encode_one(str(s)) for s in uniq), dtype=np.int32, count=len(uniq)
    )
    return codes[inv]


def direct_load(db, table_name: str, data: dict[str, object]) -> int:
    """Bulk-load rows into a table; returns rows loaded.

    `data` maps every column name to an array-like. Primary keys must be
    unique within the batch AND not collide with existing rows."""
    ti = db.tables.get(table_name)
    if ti is None:
        raise DirectLoadError(f"no such table {table_name}")
    names = ti.schema.names()
    missing = [c for c in names if c not in data]
    if missing:
        raise DirectLoadError(f"missing columns {missing}")

    cols: dict[str, np.ndarray] = {}
    n = None
    for f in ti.schema.fields:
        a = data[f.name]
        if f.dtype.kind is TypeKind.VARCHAR:
            v = _bulk_encode(ti.dicts[f.name], a)
        elif f.dtype.kind is TypeKind.DATE:
            arr = np.asarray(a)
            if arr.dtype.kind in ("U", "S"):
                v = arr.astype("datetime64[D]").astype(np.int64)
            else:
                v = arr.astype(np.int64)
            v = v.astype(f.dtype.storage_np)
        elif f.dtype.is_decimal:
            arr = np.asarray(a)
            if np.issubdtype(arr.dtype, np.floating):
                arr = np.round(arr * f.dtype.decimal_factor)
            v = arr.astype(f.dtype.storage_np)
        else:
            v = np.asarray(a, dtype=f.dtype.storage_np)
        if n is None:
            n = len(v)
        elif len(v) != n:
            raise DirectLoadError(f"column {f.name} length mismatch")
        cols[f.name] = v
    if not n:
        return 0

    # rowkey sort (the external-sort stage; np.lexsort is the in-memory
    # fast path, ops/spill.external_sort the beyond-memory one)
    key_arrays = [cols[k].astype(np.int64) for k in ti.key_cols]
    order = np.lexsort(tuple(reversed(key_arrays)))
    cols = {c: v[order] for c, v in cols.items()}
    keys2d = np.stack([cols[k].astype(np.int64) for k in ti.key_cols], axis=1)
    dup = (keys2d[1:] == keys2d[:-1]).all(axis=1)
    if dup.any():
        raise DirectLoadError(
            f"duplicate primary key in batch: {tuple(keys2d[1:][dup][0])}"
        )

    # partition routing: each hash partition gets its own sorted sstable
    # (the parallel direct-load shape — per-partition sort + install)
    part_ids = np.array(
        [0] * n if ti.part_col is None or len(ti.all_partitions()) == 1
        else [
            _part_route(keys2d[i], ti) for i in range(n)
        ],
        dtype=np.int64,
    )
    version = db.cluster.gts.next_ts()
    for p_idx, (pls, ptab) in enumerate(ti.all_partitions()):
        m = part_ids == p_idx
        if not m.any():
            continue
        pcols = {c: v[m] for c, v in cols.items()}
        pk2d = keys2d[m]
        # existing-key collision check through the tablet's read path
        rep = db._leader_replica_ls(pls)
        tablet = rep.tablets[ptab]
        if tablet.nrows_estimate:
            maybe = np.zeros(len(pk2d), dtype=bool)
            for st in ([tablet.base] if tablet.base else []) + list(tablet.deltas):
                maybe |= st.may_contain_keys(pk2d)
            for mt in [tablet.active] + list(tablet.frozen):
                if mt.nkeys:
                    for i in np.flatnonzero(~maybe):
                        if mt.get(tuple(pk2d[i]), 2**62) is not None:
                            maybe[i] = True
            for i in np.flatnonzero(maybe):
                if tablet.get(tuple(pk2d[i]), 2**62) is not None:
                    raise DirectLoadError(
                        f"primary key {tuple(pk2d[i])} already exists"
                    )
        blob = write_sstable(
            ti.schema, ti.key_cols, pcols,
            versions=np.full(int(m.sum()), version, np.int64),
            ops=np.zeros(int(m.sum()), np.int8),
            base_version=0, end_version=version,
        )
        # install on every replica (the data-movement replication analog)
        for r in db.cluster.ls_groups[pls].values():
            t = r.tablets[ptab]
            with t._meta_lock:
                t.deltas.append(
                    SSTable(blob, ti.schema, ti.key_cols, cache=db.block_cache)
                )
    ti.data_version += 1
    ti.cached_data_version = -1
    return int(n)


def _part_route(key_row: np.ndarray, ti) -> int:
    from .database import _part_of

    v = key_row[ti.key_cols.index(ti.part_col)]
    return _part_of(int(v), len(ti.all_partitions()))
