"""Virtual observability tables, queryable through the SQL engine.

Reference surface: the ~240 __all_virtual_* tables implemented under
src/observer/virtual_table (sql_audit, plan_cache_stat, ASH, trace,
parameters, ls/tablet info...). The rebuild materializes each on demand as
a host Table the moment a statement references it, so the full SQL surface
(filters, joins, aggregates — on the device engine) works over
observability data exactly like user data.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import DataType, Field, Schema
from ..core.table import Table


def _t(name: str, cols: list[tuple[str, DataType, list]]) -> Table:
    schema = Schema(tuple(Field(n, dt) for n, dt, _ in cols))
    return Table.from_pydict(name, schema, {n: v for n, _dt, v in cols})


def _parameters(db) -> Table:
    snap = db.config.snapshot()
    return _t("__all_virtual_parameters", [
        ("name", DataType.varchar(), [n for n, _, _ in snap]),
        ("value", DataType.varchar(), [str(v) for _, v, _ in snap]),
        ("type", DataType.varchar(), [p.type for _, _, p in snap]),
        ("scope", DataType.varchar(), [p.scope for _, _, p in snap]),
        ("dynamic", DataType.int32(), [int(p.dynamic) for _, _, p in snap]),
        ("info", DataType.varchar(), [p.info for _, _, p in snap]),
    ])


def _tables(db) -> Table:
    tis = [db.tables[n] for n in sorted(db.tables)]
    return _t("__all_virtual_table", [
        ("table_name", DataType.varchar(), [ti.name for ti in tis]),
        ("ls_id", DataType.int64(), [ti.ls_id for ti in tis]),
        ("tablet_id", DataType.int64(), [ti.tablet_id for ti in tis]),
        ("schema_version", DataType.int64(), [ti.schema_version for ti in tis]),
        ("data_version", DataType.int64(), [ti.data_version for ti in tis]),
        ("columns", DataType.int64(), [len(ti.schema.fields) for ti in tis]),
    ])


def _plan_cache_stat(db) -> Table:
    st = db.plan_cache.stats
    return _t("__all_virtual_plan_cache_stat", [
        ("hits", DataType.int64(), [st.hits]),
        ("misses", DataType.int64(), [st.misses]),
        ("evictions", DataType.int64(), [st.evictions]),
        ("entries", DataType.int64(), [len(db.plan_cache)]),
        ("hit_rate_pct", DataType.float64(), [100.0 * st.hit_rate]),
    ])


def _sql_audit(db) -> Table:
    recs = db.audit.records()
    return _t("__all_virtual_sql_audit", [
        ("request_id", DataType.int64(), [r.request_id for r in recs]),
        ("session_id", DataType.int64(), [r.session_id for r in recs]),
        ("trace_id", DataType.int64(), [r.trace_id for r in recs]),
        ("stmt_type", DataType.varchar(), [r.stmt_type for r in recs]),
        ("query_sql", DataType.varchar(), [r.sql for r in recs]),
        ("elapsed_us", DataType.int64(),
         [int(r.elapsed_s * 1e6) for r in recs]),
        ("return_rows", DataType.int64(), [r.rows for r in recs]),
        ("affected_rows", DataType.int64(), [r.affected for r in recs]),
        ("is_hit_plan", DataType.int32(),
         [int(r.plan_cache_hit) for r in recs]),
        ("error", DataType.varchar(), [r.error for r in recs]),
        # per-query TPU resource profile (QueryProfile): the accelerator
        # analog of the reference's rpc/io cost columns
        ("compile_time_us", DataType.int64(),
         [int(r.compile_s * 1e6) for r in recs]),
        ("device_bytes", DataType.int64(), [r.device_bytes for r in recs]),
        ("transfer_bytes", DataType.int64(),
         [r.transfer_bytes for r in recs]),
        ("peak_bytes", DataType.int64(), [r.peak_bytes for r in recs]),
        # statement retry controller: redrive count + classified reasons
        ("retry_cnt", DataType.int64(), [r.retry_cnt for r in recs]),
        ("retry_info", DataType.varchar(), [r.retry_info for r in recs]),
        # statement fast path: serving-phase breakdown (fastparse = the
        # literal-extracting tokenizer, bind = literal re-bind + qparam
        # pack, dispatch = async XLA enqueue, fetch = completion sync)
        ("fastparse_us", DataType.int64(), [r.fastparse_us for r in recs]),
        ("bind_us", DataType.int64(), [r.bind_us for r in recs]),
        ("dispatch_us", DataType.int64(), [r.dispatch_us for r in recs]),
        ("fetch_us", DataType.int64(), [r.fetch_us for r in recs]),
        ("is_fast_path", DataType.int32(),
         [int(r.is_fast_path) for r in recs]),
        # cross-session micro-batching: lanes of one batched dispatch
        # share a batch_id; batch_wait_us is the group-commit window time
        ("is_batched", DataType.int32(),
         [int(r.is_batched) for r in recs]),
        ("batch_id", DataType.int64(), [r.batch_id for r in recs]),
        ("batch_wait_us", DataType.int64(),
         [r.batch_wait_us for r in recs]),
        # host-tax gap ledger: chip-idle wall + the conservation residual
        # (e2e minus every attributed phase) — see __all_virtual_host_tax
        # for the per-digest phase breakdown
        ("chip_idle_us", DataType.int64(),
         [r.chip_idle_us for r in recs]),
        ("unattributed_us", DataType.int64(),
         [r.unattributed_us for r in recs]),
    ])


def _host_tax(db) -> Table:
    """Per-digest host-tax breakdown (share/gap_ledger.py): where every
    second of e2e wall went, phase by phase, with the residual named
    instead of silently absorbed — the standing surface for ROADMAP
    item 2 ("crush the host tax")."""
    import json

    rows = db.host_tax.rows()

    def top(ph: dict):
        if not ph:
            return "", 0
        k, v = max(ph.items(), key=lambda kv: kv[1])
        return k, int(v * 1e6)

    tops = [top(r["phases"]) for r in rows]
    return _t("__all_virtual_host_tax", [
        ("digest", DataType.varchar(), [str(r["digest"]) for r in rows]),
        ("executions", DataType.int64(), [r["count"] for r in rows]),
        ("e2e_us", DataType.int64(),
         [int(r["e2e_s"] * 1e6) for r in rows]),
        ("device_us", DataType.int64(),
         [int(r["device_s"] * 1e6) for r in rows]),
        ("chip_idle_pct", DataType.float64(),
         [r["chip_idle_pct"] for r in rows]),
        ("unattributed_us", DataType.int64(),
         [int(r["unattributed_s"] * 1e6) for r in rows]),
        ("unattributed_pct", DataType.float64(),
         [r["unattributed_pct"] for r in rows]),
        ("top_phase", DataType.varchar(), [t[0] for t in tops]),
        ("top_phase_us", DataType.int64(), [t[1] for t in tops]),
        ("phases_json", DataType.varchar(),
         [json.dumps({k: round(v, 9) for k, v in sorted(
             r["phases"].items())}) for r in rows]),
    ])


def _result_cache(db) -> Table:
    """Device-resident result cache, entry by entry (LRU -> MRU):
    which tables each cached narrowed frame reads, how many live rows
    it answers with, its byte charge against the tenant unit, and how
    many repeats it has served. Aggregate hit/miss/put/eviction
    counters live in __all_virtual_sysstat (`result cache *`)."""
    rows = db.result_cache.rows()
    return _t("__all_virtual_result_cache", [
        ("tables", DataType.varchar(), [r[0] for r in rows]),
        ("result_rows", DataType.int64(), [r[1] for r in rows]),
        ("nbytes", DataType.int64(), [r[2] for r in rows]),
        ("hits", DataType.int64(), [r[3] for r in rows]),
    ])


def _plan_monitor(db) -> Table:
    """Plan monitor, reworked per-operator: every PlanMonitorEntry keeps
    its plan-level row (node_id = -1, operator columns zeroed), and every
    profiled plan additionally emits ONE ROW PER OPERATOR from the
    calibration store (engine/plan_profile.py) — node_id, op_kind,
    est_rows vs actual_rows with the misestimation factor, fenced device
    time and output bytes, keyed by the statement digest in query_sql."""
    rows: list[dict] = []
    for e in db.plan_monitor.entries():
        rows.append({
            "plan_id": e.plan_id, "query_sql": e.sql,
            "node_id": -1, "op_kind": "",
            "compile_us": int(e.compile_s * 1e6), "executions": e.runs,
            "total_exec_us": int(e.total_exec_s * 1e6),
            "avg_exec_us": int(e.avg_exec_s * 1e6),
            "last_rows": e.last_rows,
            "overflow_retries": e.overflow_retries,
            "total_transfer_bytes": e.total_transfer_bytes,
            "last_device_bytes": e.last_device_bytes,
            "peak_bytes": e.peak_bytes,
            "px_collective_ops": e.px_collective_ops,
            "px_collective_bytes": e.px_collective_bytes,
            "px_exchanges": e.px_exchanges,
            "stream_chunks": e.stream_chunks,
            "h2d_overlap_pct": round(e.h2d_overlap_pct, 3),
            "spill_partitions": e.spill_partitions,
            "est_rows": 0, "actual_rows": 0, "miss_factor": 0.0,
            "device_us": 0, "out_bytes": 0, "op_executions": 0,
        })
    pp = getattr(db, "plan_profiler", None)
    if pp is not None:
        for r in pp.store.rows():
            rows.append({
                "plan_id": r["plan_id"], "query_sql": r["digest"],
                "node_id": r["node_id"], "op_kind": r["op_kind"],
                "compile_us": 0, "executions": r["executions"],
                "total_exec_us": 0, "avg_exec_us": 0,
                "last_rows": r["last_rows"], "overflow_retries": 0,
                "total_transfer_bytes": 0, "last_device_bytes": 0,
                "peak_bytes": 0, "px_collective_ops": 0,
                "px_collective_bytes": 0, "px_exchanges": "",
                "stream_chunks": 0, "h2d_overlap_pct": 0.0,
                "spill_partitions": 0,
                "est_rows": r["est_rows"],
                "actual_rows": int(round(r["avg_rows"])),
                "miss_factor": round(r["miss_factor"], 3),
                "device_us": int(r["device_us"]),
                "out_bytes": int(r["out_bytes"]),
                "op_executions": r["executions"],
            })
    spec = [
        ("plan_id", DataType.int64()),
        ("query_sql", DataType.varchar()),
        # per-operator identity: -1/"" on plan-level rows
        ("node_id", DataType.int64()),
        ("op_kind", DataType.varchar()),
        ("compile_us", DataType.int64()),
        ("executions", DataType.int64()),
        ("total_exec_us", DataType.int64()),
        ("avg_exec_us", DataType.int64()),
        ("last_rows", DataType.int64()),
        ("overflow_retries", DataType.int64()),
        ("total_transfer_bytes", DataType.int64()),
        ("last_device_bytes", DataType.int64()),
        ("peak_bytes", DataType.int64()),
        # mesh-SPMD plans: how many XLA collectives each execution
        # dispatches, their byte capacity, and the exchange layout
        # ("all_to_all:2,psum:1"); zeros/empty for single-chip plans
        ("px_collective_ops", DataType.int64()),
        ("px_collective_bytes", DataType.int64()),
        ("px_exchanges", DataType.varchar()),
        # streaming pipeline (engine/pipeline.py): chunks streamed through
        # the plan, last run's H2D/compute overlap percentage, grace-hash
        # partitions spilled; zeros for resident plans
        ("stream_chunks", DataType.int64()),
        ("h2d_overlap_pct", DataType.float64()),
        ("spill_partitions", DataType.int64()),
        # operator calibration columns (engine/plan_profile.py):
        # estimate vs measured cardinality + fenced device time
        ("est_rows", DataType.int64()),
        ("actual_rows", DataType.int64()),
        ("miss_factor", DataType.float64()),
        ("device_us", DataType.int64()),
        ("out_bytes", DataType.int64()),
        ("op_executions", DataType.int64()),
    ]
    return _t("__all_virtual_sql_plan_monitor", [
        (name, dt, [r[name] for r in rows]) for name, dt in spec
    ])


def _ash(db) -> Table:
    ss = db.ash.samples()
    return _t("__all_virtual_ash", [
        ("sample_ts", DataType.float64(), [s.ts for s in ss]),
        ("session_id", DataType.int64(), [s.session_id for s in ss]),
        ("activity", DataType.varchar(), [s.activity for s in ss]),
        ("query_sql", DataType.varchar(), [s.sql for s in ss]),
        ("trace_id", DataType.int64(), [s.trace_id for s in ss]),
    ])


def _trace(db) -> Table:
    sp = db.tracer.spans()
    return _t("__all_virtual_trace_span", [
        ("trace_id", DataType.int64(), [s.trace_id for s in sp]),
        ("span_id", DataType.int64(), [s.span_id for s in sp]),
        ("parent_id", DataType.int64(), [s.parent_id for s in sp]),
        ("span_name", DataType.varchar(), [s.name for s in sp]),
        ("elapsed_us", DataType.int64(), [int(s.elapsed * 1e6) for s in sp]),
        ("node", DataType.varchar(),
         [str(s.tags.get("node", "")) for s in sp]),
        ("tags", DataType.varchar(),
         [",".join(f"{k}={v}" for k, v in sorted(s.tags.items())
                   if k != "node") for s in sp]),
        ("error", DataType.varchar(),
         [str(s.tags.get("error", "")) for s in sp]),
    ])


def _long_ops(db) -> Table:
    """__all_virtual_long_ops analog: background-job progress tracking."""
    ops = db.long_ops.ops()
    return _t("__all_virtual_long_ops", [
        ("op_id", DataType.int64(), [o.op_id for o in ops]),
        ("op_name", DataType.varchar(), [o.name for o in ops]),
        ("target", DataType.varchar(), [o.target for o in ops]),
        ("total", DataType.int64(), [o.total for o in ops]),
        ("done", DataType.int64(), [o.done for o in ops]),
        ("percent", DataType.int64(), [int(o.percent) for o in ops]),
        ("status", DataType.varchar(), [o.status for o in ops]),
        ("trace_id", DataType.int64(), [o.trace_id for o in ops]),
        ("message", DataType.varchar(), [o.message for o in ops]),
    ])


def _sysstat(db) -> Table:
    """GV$SYSSTAT analog: every counter and gauge in the tenant registry."""
    cs = db.metrics.counters_snapshot()
    gs = db.metrics.gauges_snapshot()
    rows = sorted(
        [(n, float(v), "counter") for n, v in cs.items()]
        + [(n, float(v), "gauge") for n, v in gs.items()]
    )
    return _t("__all_virtual_sysstat", [
        ("name", DataType.varchar(), [r[0] for r in rows]),
        ("value", DataType.int64(), [int(r[1]) for r in rows]),
        ("stat_class", DataType.varchar(), [r[2] for r in rows]),
    ])


def _system_event(db) -> Table:
    """GV$SYSTEM_EVENT analog: wait classes with count/total/max/avg."""
    ws = sorted(db.metrics.waits_snapshot(), key=lambda w: w.event)
    return _t("__all_virtual_system_event", [
        ("event", DataType.varchar(), [w.event for w in ws]),
        ("total_waits", DataType.int64(), [w.count for w in ws]),
        ("time_waited", DataType.int64(),
         [int(w.total_s * 1e6) for w in ws]),
        ("max_wait", DataType.int64(), [int(w.max_s * 1e6) for w in ws]),
        ("average_wait", DataType.int64(),
         [int(w.avg_s * 1e6) for w in ws]),
    ])


def _query_response_time(db) -> Table:
    """QUERY_RESPONSE_TIME analog: per-histogram latency buckets plus a
    quantile row set (p50/p95/p99 as bucket upper-bound estimates)."""
    rows = []
    for h in sorted(db.metrics.hists_snapshot(), key=lambda x: x.name):
        acc = 0
        for bound, c in zip(h.bounds, h.counts):
            acc += c
            rows.append((h.name, "bucket", int(bound * 1e6), acc))
        rows.append((h.name, "count", 0, h.count))
        for q, v in (("p50", h.p50), ("p95", h.p95), ("p99", h.p99)):
            rows.append((h.name, q, int(v * 1e6), h.count))
    return _t("__all_virtual_query_response_time", [
        ("histogram", DataType.varchar(), [r[0] for r in rows]),
        ("kind", DataType.varchar(), [r[1] for r in rows]),
        ("le_us", DataType.int64(), [r[2] for r in rows]),
        ("count", DataType.int64(), [r[3] for r in rows]),
    ])


def _ls(db) -> Table:
    rows = []
    for ls_id, group in sorted(db.cluster.ls_groups.items()):
        for node, rep in sorted(group.items()):
            rows.append((ls_id, node, rep.palf.role.name,
                         int(rep.is_ready), len(rep.tablets)))
    return _t("__all_virtual_ls", [
        ("ls_id", DataType.int64(), [r[0] for r in rows]),
        ("svr_node", DataType.int64(), [r[1] for r in rows]),
        ("role", DataType.varchar(), [r[2] for r in rows]),
        ("is_ready", DataType.int32(), [r[3] for r in rows]),
        ("tablet_count", DataType.int64(), [r[4] for r in rows]),
    ])


def _ls_replica(db) -> Table:
    """Per-replica serving health: role, keepalive reachability (majority
    vote over peers' NetKeepAlive evidence) and the apply watermark with
    its lag behind GTS — the staleness a follower read of that replica
    would observe."""
    cluster = db.cluster
    dead = cluster.unreachable_nodes() if cluster.keepalives else set()
    now_ts = cluster.gts.current()
    rows = []
    for ls_id, group in sorted(cluster.ls_groups.items()):
        for node, rep in sorted(group.items()):
            wm = rep.apply_watermark
            rows.append((ls_id, node, rep.palf.role.name,
                         int(rep.is_ready), int(node in dead),
                         rep.palf.applied_lsn, wm, max(0, now_ts - wm)))
    return _t("__all_virtual_ls_replica", [
        ("ls_id", DataType.int64(), [r[0] for r in rows]),
        ("svr_node", DataType.int64(), [r[1] for r in rows]),
        ("role", DataType.varchar(), [r[2] for r in rows]),
        ("is_ready", DataType.int32(), [r[3] for r in rows]),
        ("unreachable", DataType.int32(), [r[4] for r in rows]),
        ("applied_lsn", DataType.int64(), [r[5] for r in rows]),
        ("apply_watermark", DataType.int64(), [r[6] for r in rows]),
        ("watermark_lag_us", DataType.int64(), [r[7] for r in rows]),
    ])


def _processlist(db) -> Table:
    rows = sorted(db._active_stmts.items())
    return _t("__all_virtual_processlist", [
        ("session_id", DataType.int64(), [sid for sid, _ in rows]),
        ("stmt_tag", DataType.varchar(),
         [":".join(map(str, iid)) for _, iid in rows]),
        ("tenant", DataType.varchar(), [db.tenant_name for _ in rows]),
    ])


def _tablets(db) -> Table:
    rows = []
    for name in sorted(db.tables):
        ti = db.tables[name]
        for ls_id, tablet_id in ti.all_partitions():
            rows.append((tablet_id, name, ls_id))
    return _t("__all_virtual_tablet", [
        ("tablet_id", DataType.int64(), [r[0] for r in rows]),
        ("table_name", DataType.varchar(), [r[1] for r in rows]),
        ("ls_id", DataType.int64(), [r[2] for r in rows]),
    ])


def _users(db) -> Table:
    pm = db.privileges
    names = sorted(pm.users)
    return _t("__all_virtual_user", [
        ("user_name", DataType.varchar(), names),
        ("grant_count", DataType.int64(),
         [sum(len(p) for p in pm.grants.get(u, {}).values())
          for u in names]),
        ("is_root", DataType.int32(), [int(u == "root") for u in names]),
    ])


def _privileges(db) -> Table:
    pm = db.privileges
    rows = [
        (u, obj, priv)
        for u in sorted(pm.grants)
        for obj in sorted(pm.grants[u])
        for priv in sorted(pm.grants[u][obj])
    ]
    return _t("__all_virtual_privilege", [
        ("user_name", DataType.varchar(), [r[0] for r in rows]),
        ("object", DataType.varchar(), [r[1] for r in rows]),
        ("privilege", DataType.varchar(), [r[2] for r in rows]),
    ])


def _deadlock_stat(db) -> Table:
    lm = db.lock_mgr
    waits = lm.waiting_snapshot()
    return _t("__all_virtual_deadlock_stat", [
        ("deadlocks_resolved", DataType.int64(), [lm.deadlocks]),
        ("waiting_txs", DataType.int64(), [len(waits)]),
        ("wait_edges", DataType.int64(),
         [sum(len(v) for v in waits.values())]),
    ])


def _memory(db) -> Table:
    names = sorted(db.tables)
    sizes = []
    for n in names:
        t = db.catalog.get(n)
        sizes.append(
            sum(getattr(a, "nbytes", 0) for a in t.data.values())
            if t is not None else 0
        )
    return _t("__all_virtual_memory", [
        ("table_name", DataType.varchar(), names),
        ("resident_bytes", DataType.int64(), sizes),
    ])


def _indexes(db) -> Table:
    rows = []
    for name in sorted(db.tables):
        ti = db.tables[name]
        idxs = getattr(ti, "indexes", None) or {}
        if isinstance(idxs, dict):
            idxs = idxs.values()
        for ix in idxs:
            rows.append((ix.name, name, ",".join(ix.cols),
                         int(ix.unique)))
    for tname, specs in sorted(db._vector_specs.items()):
        for col, (lists, nprobe) in sorted(specs.items()):
            rows.append((f"ivf:{col}", tname, col, 0))
    return _t("__all_virtual_index", [
        ("index_name", DataType.varchar(), [r[0] for r in rows]),
        ("table_name", DataType.varchar(), [r[1] for r in rows]),
        ("columns", DataType.varchar(), [r[2] for r in rows]),
        ("is_unique", DataType.int32(), [r[3] for r in rows]),
    ])


def _external_tables(db) -> Table:
    rows = sorted(db._external_specs.items())
    return _t("__all_virtual_external_table", [
        ("table_name", DataType.varchar(), [n for n, _ in rows]),
        ("format", DataType.varchar(), [f for _, (f, _p) in rows]),
        ("location", DataType.varchar(), [p for _, (_f, p) in rows]),
    ])


def _server_stat(db) -> Table:
    n_repl = sum(len(g) for g in db.cluster.ls_groups.values())
    return _t("__all_virtual_server_stat", [
        ("tenant", DataType.varchar(), [db.tenant_name]),
        ("nodes", DataType.int64(), [db.cluster.n_nodes]),
        ("ls_groups", DataType.int64(), [len(db.cluster.ls_groups)]),
        ("replicas", DataType.int64(), [n_repl]),
        ("tables", DataType.int64(), [len(db.tables)]),
        ("active_statements", DataType.int64(), [len(db._active_stmts)]),
    ])


def _procedures(db) -> Table:
    names = sorted(db._procedure_texts)
    return _t("__all_virtual_procedure", [
        ("procedure_name", DataType.varchar(), names),
        ("definition", DataType.varchar(),
         [db._procedure_texts[n].strip()[:200] for n in names]),
    ])


def _sequences(db) -> Table:
    names = sorted(db._sequences)
    return _t("__all_virtual_sequence", [
        ("sequence_name", DataType.varchar(), names),
        ("next_value", DataType.int64(),
         [int(db._sequences[n]["next"]) for n in names]),
        ("increment_by", DataType.int64(),
         [int(db._sequences[n]["inc"]) for n in names]),
        ("reserved_until", DataType.int64(),
         [int(db._sequences[n]["reserved"]) for n in names]),
    ])


def _views(db) -> Table:
    names = sorted(db._view_specs)
    return _t("__all_virtual_view", [
        ("view_name", DataType.varchar(), names),
        ("definition", DataType.varchar(),
         [db._view_specs[n].strip()[:200] for n in names]),
    ])


def _triggers(db) -> Table:
    names = sorted(db._trigger_specs)
    return _t("__all_virtual_trigger", [
        ("trigger_name", DataType.varchar(), names),
        ("timing", DataType.varchar(),
         [db._trigger_specs[n]["timing"] for n in names]),
        ("event", DataType.varchar(),
         [db._trigger_specs[n]["event"] for n in names]),
        ("table_name", DataType.varchar(),
         [db._trigger_specs[n]["table"] for n in names]),
        ("body", DataType.varchar(),
         [db._trigger_specs[n]["body"].strip()[:200] for n in names]),
    ])


def _mviews(db) -> Table:
    names = sorted(db._mview_specs)
    return _t("__all_virtual_mview", [
        ("mview_name", DataType.varchar(), names),
        ("definition", DataType.varchar(),
         [db._mview_specs[n].strip()[:200] for n in names]),
    ])


def _statement_summary(db) -> Table:
    """Digest-keyed rolling statement aggregates (server/workload.py) —
    the durable view the sql_audit ring cannot give: per-digest exec/fail
    counts, latency quantiles and phase sums across every execution."""
    ss = db.stmt_summary.snapshot()
    us = 1e6
    return _t("__all_virtual_statement_summary", [
        ("digest", DataType.varchar(), [s["digest"] for s in ss]),
        ("stmt_type", DataType.varchar(), [s["stmt_type"] for s in ss]),
        ("executions", DataType.int64(), [s["exec_count"] for s in ss]),
        ("fails", DataType.int64(), [s["fail_count"] for s in ss]),
        ("retries", DataType.int64(), [s["retry_count"] for s in ss]),
        ("rows_returned", DataType.int64(),
         [s["rows_returned"] for s in ss]),
        ("affected_rows", DataType.int64(),
         [s["affected_rows"] for s in ss]),
        ("fast_path_hits", DataType.int64(),
         [s["fast_path_count"] for s in ss]),
        ("batched", DataType.int64(), [s["batched_count"] for s in ss]),
        ("cache_hits", DataType.int64(),
         [s["cache_hit_count"] for s in ss]),
        ("total_elapsed_us", DataType.int64(),
         [int(s["total_elapsed_s"] * us) for s in ss]),
        ("avg_elapsed_us", DataType.int64(),
         [int(s["total_elapsed_s"] / s["exec_count"] * us) for s in ss]),
        ("max_elapsed_us", DataType.int64(),
         [int(s["max_elapsed_s"] * us) for s in ss]),
        ("p50_us", DataType.int64(), [int(s["p50_s"] * us) for s in ss]),
        ("p95_us", DataType.int64(), [int(s["p95_s"] * us) for s in ss]),
        ("p99_us", DataType.int64(), [int(s["p99_s"] * us) for s in ss]),
        ("fastparse_us", DataType.int64(),
         [int(s["fastparse_s"] * us) for s in ss]),
        ("bind_us", DataType.int64(), [int(s["bind_s"] * us) for s in ss]),
        ("dispatch_us", DataType.int64(),
         [int(s["dispatch_s"] * us) for s in ss]),
        ("fetch_us", DataType.int64(),
         [int(s["fetch_s"] * us) for s in ss]),
        ("compile_us", DataType.int64(),
         [int(s["compile_s"] * us) for s in ss]),
        ("transfer_bytes", DataType.int64(),
         [s["transfer_bytes"] for s in ss]),
        ("max_device_bytes", DataType.int64(),
         [s["max_device_bytes"] for s in ss]),
        ("max_peak_bytes", DataType.int64(),
         [s["max_peak_bytes"] for s in ss]),
    ])


def _table_access_stat(db) -> Table:
    """Table/column access heat: table-level rows carry scan/DAS/
    projection counters (column_name = ''), column-level rows carry the
    per-role reference counts."""
    rows = []
    for t in db.access.snapshot():
        rows.append((t["table"], "", t["scans"], t["rows_read"],
                     t["das_lookups"], t["das_rows"], t["proj_hits"],
                     t["proj_misses"], 0, 0, 0, 0))
        for c in t["columns"]:
            rows.append((t["table"], c["column"], 0, 0, 0, 0, 0, 0,
                         c["filter_count"], c["join_count"],
                         c["group_count"], c["sort_count"]))
    return _t("__all_virtual_table_access_stat", [
        ("table_name", DataType.varchar(), [r[0] for r in rows]),
        ("column_name", DataType.varchar(), [r[1] for r in rows]),
        ("scans", DataType.int64(), [r[2] for r in rows]),
        ("rows_read", DataType.int64(), [r[3] for r in rows]),
        ("das_lookups", DataType.int64(), [r[4] for r in rows]),
        ("das_rows", DataType.int64(), [r[5] for r in rows]),
        ("proj_hits", DataType.int64(), [r[6] for r in rows]),
        ("proj_misses", DataType.int64(), [r[7] for r in rows]),
        ("filter_count", DataType.int64(), [r[8] for r in rows]),
        ("join_count", DataType.int64(), [r[9] for r in rows]),
        ("group_count", DataType.int64(), [r[10] for r in rows]),
        ("sort_count", DataType.int64(), [r[11] for r in rows]),
    ])


def _device_census(db) -> Table:
    """Device-residency and compile census: per-table device bytes,
    compiled-plan entries with hit counts and pow2 batch buckets, the
    fast text tier, block-cache residency."""
    from .workload import device_census

    rows = device_census(db)
    return _t("__all_virtual_device_census", [
        ("kind", DataType.varchar(), [r["kind"] for r in rows]),
        ("name", DataType.varchar(), [r["name"] for r in rows]),
        ("detail", DataType.varchar(), [r["detail"] for r in rows]),
        ("entries", DataType.int64(), [r["entries"] for r in rows]),
        ("hits", DataType.int64(), [r["hits"] for r in rows]),
        ("bytes", DataType.int64(), [r["bytes"] for r in rows]),
    ])


def _server_timeline(db) -> Table:
    """GV$OB_SERVERS-over-time analog: the serving timeline's bucket
    ring (share/timeline.py) — device/host busy seconds per fixed-width
    time slice, dispatch + batch-occupancy counts, compile/transfer
    interference, admission queue pressure."""
    bs = db.timeline.snapshot()
    return _t("__all_virtual_server_timeline", [
        ("bucket_ts", DataType.float64(), [b["ts"] for b in bs]),
        ("wall_us", DataType.int64(),
         [int(b["wall_s"] * 1e6) for b in bs]),
        ("stmts", DataType.int64(), [b["stmts"] for b in bs]),
        ("errors", DataType.int64(), [b["errors"] for b in bs]),
        ("host_busy_us", DataType.int64(),
         [int(b["host_busy_s"] * 1e6) for b in bs]),
        ("device_busy_us", DataType.int64(),
         [int(b["device_busy_s"] * 1e6) for b in bs]),
        ("device_busy_pct", DataType.float64(),
         [round(100.0 * b["device_busy_frac"], 3) for b in bs]),
        ("dispatches", DataType.int64(), [b["dispatches"] for b in bs]),
        ("batch_dispatches", DataType.int64(),
         [b["batch_dispatches"] for b in bs]),
        ("batch_lanes", DataType.int64(), [b["batch_lanes"] for b in bs]),
        ("compile_events", DataType.int64(),
         [b["compile_events"] for b in bs]),
        ("compile_us", DataType.int64(),
         [int(b["compile_s"] * 1e6) for b in bs]),
        ("transfer_events", DataType.int64(),
         [b["transfer_events"] for b in bs]),
        ("transfer_bytes", DataType.int64(),
         [b["transfer_bytes"] for b in bs]),
        # cross-chip interconnect pressure (mesh-SPMD dispatches): XLA
        # collectives run in the slice + their static byte capacity
        ("collective_ops", DataType.int64(),
         [b["collective_ops"] for b in bs]),
        ("collective_bytes", DataType.int64(),
         [b["collective_bytes"] for b in bs]),
        # streaming pipeline pressure per slice: chunks streamed,
        # wire-busy vs compute-busy seconds and their overlap fraction
        # (is the H2D tunnel or the device the out-of-core ceiling?),
        # grace-hash partitions spilled
        ("stream_chunks", DataType.int64(),
         [b["stream_chunks"] for b in bs]),
        ("stream_h2d_us", DataType.int64(),
         [int(b["stream_h2d_s"] * 1e6) for b in bs]),
        ("stream_compute_us", DataType.int64(),
         [int(b["stream_compute_s"] * 1e6) for b in bs]),
        ("h2d_overlap_pct", DataType.float64(),
         [round(100.0 * b["h2d_overlap_frac"], 3) for b in bs]),
        ("stream_spill_parts", DataType.int64(),
         [b["stream_spill_parts"] for b in bs]),
        ("max_in_flight", DataType.int64(),
         [b["max_in_flight"] for b in bs]),
        ("admitted", DataType.int64(), [b["admitted"] for b in bs]),
        ("rejected", DataType.int64(), [b["rejected"] for b in bs]),
        ("admission_wait_us", DataType.int64(),
         [int(b["admission_wait_s"] * 1e6) for b in bs]),
        # continuous-batching scheduler pressure per slice: queue
        # high-water mark, gate admissions and the time cohorts spent
        # queued at the dispatch gate
        ("sched_queue_max", DataType.int64(),
         [b["sched_queue_max"] for b in bs]),
        ("gate_admissions", DataType.int64(),
         [b["gate_admissions"] for b in bs]),
        ("gate_wait_us", DataType.int64(),
         [int(b["gate_wait_s"] * 1e6) for b in bs]),
        ("wait_p99_us", DataType.int64(),
         [int(b["wait_p99_s"] * 1e6) for b in bs]),
    ])


def _tenant_qos(db) -> Table:
    """Per-tenant QoS ledger: cumulative admission/served/rejected
    accounting against the TenantUnit limits each tenant was given."""
    qos = db.timeline.qos_totals()
    names = list(qos)
    return _t("__all_virtual_tenant_qos", [
        ("tenant", DataType.varchar(), names),
        ("max_workers", DataType.int64(),
         [qos[n]["max_workers"] for n in names]),
        ("queue_timeout_us", DataType.int64(),
         [int(qos[n]["queue_timeout_s"] * 1e6) for n in names]),
        ("stmts", DataType.int64(), [qos[n]["stmts"] for n in names]),
        ("errors", DataType.int64(), [qos[n]["errors"] for n in names]),
        ("admitted", DataType.int64(),
         [qos[n]["admitted"] for n in names]),
        ("rejected", DataType.int64(),
         [qos[n]["rejected"] for n in names]),
        ("wait_us", DataType.int64(),
         [int(qos[n]["wait_s"] * 1e6) for n in names]),
        ("avg_wait_us", DataType.int64(),
         [int(qos[n]["wait_s"] / max(qos[n]["admitted"]
                                     + qos[n]["rejected"], 1) * 1e6)
          for n in names]),
        ("max_in_flight", DataType.int64(),
         [qos[n]["max_in_flight"] for n in names]),
        ("host_busy_us", DataType.int64(),
         [int(qos[n]["host_busy_s"] * 1e6) for n in names]),
    ])


def _alert_history(db) -> Table:
    """Health-sentinel alert ring (server/sentinel.py): deduplicated,
    severity-tagged rule firings with their snapshot window + evidence."""
    import json

    als = db.sentinel.alerts()
    return _t("__all_virtual_alert_history", [
        ("alert_id", DataType.int64(), [a.alert_id for a in als]),
        ("ts", DataType.float64(), [a.ts for a in als]),
        ("rule", DataType.varchar(), [a.rule for a in als]),
        ("severity", DataType.varchar(), [a.severity for a in als]),
        ("subject", DataType.varchar(), [a.key for a in als]),
        ("summary", DataType.varchar(), [a.summary for a in als]),
        ("first_snap_id", DataType.int64(),
         [a.first_snap_id for a in als]),
        ("last_snap_id", DataType.int64(),
         [a.last_snap_id for a in als]),
        ("evidence", DataType.varchar(),
         [json.dumps(a.evidence, sort_keys=True)[:400] for a in als]),
    ])


def _layout_advisor(db) -> Table:
    """Latest layout-advisor pass: each ranked recommendation with its
    evidence, estimated benefit, byte cost, and what happened to it
    (dry_run / queued / applied / rejected:budget)."""
    adv = getattr(db, "layout_advisor", None)
    recs = list(adv.last) if adv is not None else []
    return _t("__all_virtual_layout_advisor", [
        ("action", DataType.varchar(), [r.action for r in recs]),
        ("table_name", DataType.varchar(), [r.table for r in recs]),
        ("column_name", DataType.varchar(), [r.column for r in recs]),
        ("detail", DataType.varchar(), [r.detail for r in recs]),
        ("benefit", DataType.float64(), [float(r.benefit) for r in recs]),
        ("cost_bytes", DataType.int64(), [int(r.cost_bytes) for r in recs]),
        ("status", DataType.varchar(), [r.status for r in recs]),
        ("evidence", DataType.varchar(), [r.evidence for r in recs]),
    ])


def _plan_artifact(db) -> Table:
    """On-disk compiled-plan artifact tier (engine/plan_artifact.py):
    one row per exported executable — identity, byte cost, statement-
    summary exec ranking, exported batch buckets, and this boot's
    hydration hit/miss/load-time tallies. `warm` = 1 means the live
    plan-cache entry is backed by this artifact (hydrated, not
    compiled)."""
    store = getattr(db, "plan_artifact", None)
    rows = store.census() if store is not None else []
    return _t("__all_virtual_plan_artifact", [
        ("artifact_id", DataType.varchar(),
         [r["artifact_id"] for r in rows]),
        ("statement", DataType.varchar(), [r["statement"] for r in rows]),
        ("bytes", DataType.int64(), [r["bytes"] for r in rows]),
        ("execs", DataType.int64(), [r["execs"] for r in rows]),
        ("buckets", DataType.varchar(),
         [",".join(str(b) for b in r["buckets"]) for r in rows]),
        ("hits", DataType.int64(), [r["hits"] for r in rows]),
        ("misses", DataType.int64(), [r["misses"] for r in rows]),
        ("load_us", DataType.int64(), [r["load_us"] for r in rows]),
        ("warm", DataType.int64(), [r["warm"] for r in rows]),
    ])


def _memory_governor(db) -> Table:
    """Device-memory governor ledger (engine/memory_governor.py): the
    budget and its OOM-shrunk effective value, live/peak reserved bytes,
    grant/reject/oom counters, reservation-wait p99, and one
    `reserved:<tenant>` / `limit:<tenant>` row pair per registered
    tenant share."""
    gov = getattr(db, "governor", None)
    st = gov.stats() if gov is not None else {}
    rows: list[tuple[str, int]] = [
        ("budget", int(st.get("budget", 0))),
        ("effective_budget", int(st.get("effective_budget", 0))),
        ("reserved", int(st.get("reserved", 0))),
        ("peak_reserved", int(st.get("peak_reserved", 0))),
        # staged ledger: host-pinned wire-encoded chunk buffers held by
        # the streaming prefetcher (zero between statements — a leak
        # here means a cancelled prefetch did not drain)
        ("staged", int(st.get("staged", 0))),
        ("peak_staged", int(st.get("peak_staged", 0))),
        ("waiters", int(st.get("waiters", 0))),
        ("grants", int(st.get("grants", 0))),
        ("rejects", int(st.get("rejects", 0))),
        ("oom_notes", int(st.get("oom_notes", 0))),
        ("shrink_pct", int(round(st.get("shrink", 1.0) * 100))),
        ("wait_p99_us", int(st.get("wait_p99_s", 0.0) * 1e6)),
    ]
    for name, t in sorted(st.get("tenants", {}).items()):
        rows.append((f"reserved:{name}", int(t["reserved"])))
        rows.append((f"limit:{name}",
                     int(t["limit"]) if t["limit"] is not None else -1))
    return _t("__all_virtual_memory_governor", [
        ("metric", DataType.varchar(), [m for m, _ in rows]),
        ("value", DataType.int64(), [v for _, v in rows]),
    ])


def _storage_integrity(db) -> Table:
    """Storage-scrub ledger (storage/scrub.py): one row per artifact
    class with cumulative scrubbed/failure/quarantine/repair counts,
    plus one `quarantine:<class>` row per quarantined file (its new
    path and the verification failure that sent it there)."""
    scr = getattr(db, "scrubber", None)
    st = scr.stats() if scr is not None else {}
    rows: list[tuple[str, int, int, int, int, int, str]] = []
    for cls, v in sorted((st.get("by_class") or {}).items()):
        rows.append((
            cls, int(v.get("scrubbed", 0)), int(v.get("failures", 0)),
            int(v.get("quarantined", 0)), int(v.get("repaired", 0)),
            int(v.get("unrepaired", 0)),
            f"passes={int(st.get('passes', 0))}",
        ))
    for cls, qpath, reason in st.get("quarantined", ()):
        rows.append((f"quarantine:{cls}", 0, 0, 1, 0, 0,
                     f"{qpath}: {reason}"[:160]))
    return _t("__all_virtual_storage_integrity", [
        ("path_class", DataType.varchar(), [r[0] for r in rows]),
        ("scrubbed", DataType.int64(), [r[1] for r in rows]),
        ("failures", DataType.int64(), [r[2] for r in rows]),
        ("quarantined", DataType.int64(), [r[3] for r in rows]),
        ("repaired", DataType.int64(), [r[4] for r in rows]),
        ("unrepaired", DataType.int64(), [r[5] for r in rows]),
        ("detail", DataType.varchar(), [r[6] for r in rows]),
    ])


def _vector_index(db) -> Table:
    """Registered vector indexes with build + serving counters: spec
    (lists/nprobe), built artifact metadata (version/scn/rows/build
    seconds), uploaded device bytes, and cumulative probe / over-probe /
    query counters folded at statement completion."""
    ex = db.engine.executor
    residency = {}
    try:
        residency = ex.ann_residency()
    except Exception:  # noqa: BLE001 - diagnostics never fail a read
        pass
    builds = getattr(ex, "ann_builds", {}) or {}
    stats = getattr(ex, "ann_stats", {}) or {}
    rows = []
    for tname, specs in sorted(db._vector_specs.items()):
        t = db.catalog.get(tname)
        live = getattr(t, "vector_indexes", {}) if t is not None else {}
        for col, (lists, nprobe) in sorted(specs.items()):
            spec = live.get(col)
            b = builds.get((tname, col), {})
            st = stats.get((tname, col), (0, 0, 0))
            rows.append((
                tname, col,
                int(getattr(spec, "lists", lists) or lists),
                int(getattr(spec, "nprobe", nprobe) or nprobe),
                int(residency.get((tname, col), 0)),
                int(b.get("build_version", -1)),
                float(b.get("build_s", 0.0)),
                int(b.get("rows", 0)),
                int(st[0]), int(st[1]), int(st[2]),
            ))
    return _t("__all_virtual_vector_index", [
        ("table_name", DataType.varchar(), [r[0] for r in rows]),
        ("column_name", DataType.varchar(), [r[1] for r in rows]),
        ("lists", DataType.int64(), [r[2] for r in rows]),
        ("nprobe", DataType.int64(), [r[3] for r in rows]),
        ("device_bytes", DataType.int64(), [r[4] for r in rows]),
        ("build_scn", DataType.int64(), [r[5] for r in rows]),
        ("build_seconds", DataType.float64(), [r[6] for r in rows]),
        ("build_rows", DataType.int64(), [r[7] for r in rows]),
        ("queries", DataType.int64(), [r[8] for r in rows]),
        ("probes", DataType.int64(), [r[9] for r in rows]),
        ("over_probe_escalations", DataType.int64(), [r[10] for r in rows]),
    ])


def _xa(db) -> Table:
    rows = sorted(db._xa_prepared.items())
    return _t("__all_virtual_xa_transaction", [
        ("xid", DataType.varchar(), [x for x, _ in rows]),
        ("owner", DataType.varchar(), [e[1] for _, e in rows]),
        ("state", DataType.varchar(), ["PREPARED" for _ in rows]),
    ])


PROVIDERS = {
    "__all_virtual_parameters": _parameters,
    "__all_virtual_table": _tables,
    "__all_virtual_plan_cache_stat": _plan_cache_stat,
    "__all_virtual_sql_audit": _sql_audit,
    "__all_virtual_host_tax": _host_tax,
    "__all_virtual_result_cache": _result_cache,
    "__all_virtual_sql_plan_monitor": _plan_monitor,
    "__all_virtual_ash": _ash,
    "__all_virtual_trace_span": _trace,
    "__all_virtual_long_ops": _long_ops,
    "__all_virtual_sysstat": _sysstat,
    "__all_virtual_system_event": _system_event,
    "__all_virtual_query_response_time": _query_response_time,
    "__all_virtual_ls": _ls,
    "__all_virtual_ls_replica": _ls_replica,
    "__all_virtual_processlist": _processlist,
    "__all_virtual_tablet": _tablets,
    "__all_virtual_user": _users,
    "__all_virtual_privilege": _privileges,
    "__all_virtual_deadlock_stat": _deadlock_stat,
    "__all_virtual_memory": _memory,
    "__all_virtual_index": _indexes,
    "__all_virtual_external_table": _external_tables,
    "__all_virtual_server_stat": _server_stat,
    "__all_virtual_procedure": _procedures,
    "__all_virtual_view": _views,
    "__all_virtual_trigger": _triggers,
    "__all_virtual_sequence": _sequences,
    "__all_virtual_mview": _mviews,
    "__all_virtual_vector_index": _vector_index,
    "__all_virtual_xa_transaction": _xa,
    "__all_virtual_statement_summary": _statement_summary,
    "__all_virtual_table_access_stat": _table_access_stat,
    "__all_virtual_device_census": _device_census,
    "__all_virtual_server_timeline": _server_timeline,
    "__all_virtual_tenant_qos": _tenant_qos,
    "__all_virtual_alert_history": _alert_history,
    "__all_virtual_layout_advisor": _layout_advisor,
    "__all_virtual_plan_artifact": _plan_artifact,
    "__all_virtual_memory_governor": _memory_governor,
    "__all_virtual_storage_integrity": _storage_integrity,
}
