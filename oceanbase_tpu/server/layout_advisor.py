"""Closed-loop layout advisor: workload-adaptive physical design.

Reference surface: OceanBase exposes the evidence (GV$SQL_AUDIT,
GV$SQL_PLAN_MONITOR, table access stats) and leaves index/layout choice to
the DBA; "Fine-Tuning Data Structures for Analytical Query Processing"
(PAPERS.md) is the blueprint for closing that loop from the query log.
This module folds the workload repository's evidence — `TableAccessStats`
column roles, statement-summary latency, `device_census()` bytes — into
ranked, costed layout actions:

  * create/drop sorted projections (storage/sorted_projection.py): a hot
    filter column with a range-routable dtype earns a projection; an
    advisor-created projection that goes unused for N consecutive
    snapshot windows is dropped again (hysteresis, so recommendations
    don't flap between snapshots);
  * per-column encodings (storage/encoding.py cost model): quantifies
    FOR/RLE/const savings over raw for hot tables' integer columns, and
    records the choice as a hint for the sstable dump path;
  * per-table device-residency priorities that `Database._enforce_memory`
    and the block cache's eviction respect under memory pressure.

Actions apply through the existing `TenantDagScheduler` as BACKGROUND-
priority rebuild DAGs (visible in `__all_virtual_long_ops`), bounded by
the `layout_advisor_max_bytes` budget. Control surface:

  ALTER SYSTEM RUN LAYOUT ADVISOR          -- one pass now (root only)
  ob_layout_advisor_mode = off|dry_run|auto
  select * from __all_virtual_layout_advisor

`auto` mode additionally runs a pass on every workload snapshot
(WorkloadRepository.on_snapshot, chained next to the health sentinel) and
re-queues DML-invalidated projections for background rebuild instead of
losing them silently.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..storage import encoding as enc
from ..storage.sorted_projection import projection_name

# snapshot windows an advisor-created projection may sit unused (base
# table scanned, zero projection hits) before a drop is recommended
DROP_AFTER_WINDOWS = 3
# cumulative scans a table needs before it produces any recommendation
MIN_SCANS = 2
# encoding recommendations below this byte saving are noise
MIN_ENC_SAVINGS = 4 << 10

_ENC_NAMES = {enc.ENC_RAW: "raw", enc.ENC_CONST: "const",
              enc.ENC_FOR: "for", enc.ENC_RLE: "rle"}


@dataclass
class Recommendation:
    """One ranked layout action with its evidence and estimated benefit."""

    action: str  # create_projection | drop_projection | set_encoding | set_residency | create_vector_index
    table: str
    column: str = ""
    detail: str = ""  # action payload: covered cols / encoding / priority
    benefit: float = 0.0  # ranking score (higher first)
    cost_bytes: int = 0  # bytes the action would materialize
    evidence: str = ""
    status: str = "proposed"


def _covered_bytes(t, cols=None) -> int:
    total = 0
    for c, a in t.data.items():
        if cols is None or c in cols:
            total += int(getattr(a, "nbytes", 0))
    return total


def _routable(t, col, range_kinds) -> bool:
    """Mirror the scan router's eligibility: the projection key must be a
    value-ordered range dtype (dict codes and floats never route)."""
    if col in getattr(t, "dicts", {}):
        return False
    try:
        kind = t.schema[col].kind
    except Exception:
        return False
    return kind in range_kinds


def propose(
    access_rows,
    catalog,
    *,
    budget_bytes: int | None = None,
    created: dict | None = None,
    idle: dict | None = None,
    dropped: dict | None = None,
    census_rows=None,
    drop_after: int = DROP_AFTER_WINDOWS,
    min_scans: int = MIN_SCANS,
) -> list[Recommendation]:
    """Pure core: evidence in, ranked costed actions out. No side effects,
    so tests and bench can drive it without a Database.

    `access_rows` is `TableAccessStats.snapshot()`; `catalog` maps table
    name -> core Table; `created`/`idle`/`dropped` are the advisor's
    hysteresis registries ((table, key_col) keyed); `census_rows` is
    `device_census()` output (folded into residency evidence).
    """
    from ..engine.executor import Executor

    range_kinds = Executor._RANGE_KINDS
    created = created or {}
    idle = idle or {}
    dropped = dropped or {}
    dev_bytes = {}
    for r in census_rows or ():
        if r.get("kind") == "table_device":
            dev_bytes[r.get("name")] = (
                dev_bytes.get(r.get("name"), 0) + int(r.get("bytes", 0)))

    recs: list[Recommendation] = []
    # bytes already spent on advisor-created projections count against
    # the budget, so repeated passes under the same budget are stable
    spent = 0
    for (_tab, _key), pname in created.items():
        pt = catalog.get(pname)
        if pt is not None:
            spent += _covered_bytes(pt)

    # ---- create sorted projections ----------------------------------
    for row in sorted(access_rows, key=lambda r: -int(r.get("rows_read", 0))):
        table = row["table"]
        t = catalog.get(table)
        if t is None or "#sp:" in table or table.startswith("__all_virtual"):
            continue
        scans = int(row.get("scans", 0))
        if scans < min_scans:
            continue
        cols = sorted(
            row.get("columns", ()),
            key=lambda c: -int(c.get("filter_count", 0)),
        )
        best = next(
            (c for c in cols
             if int(c.get("filter_count", 0)) > 0
             and _routable(t, c["column"], range_kinds)),
            None,
        )
        if best is None:
            continue
        key_col = best["column"]
        if key_col in getattr(t, "sorted_projections", {}):
            continue  # already laid out (advisor-built or hand-built)
        fcount = int(best.get("filter_count", 0))
        prev = dropped.get((table, key_col))
        if prev is not None and fcount < prev + min_scans:
            # hysteresis: a projection the advisor just dropped only
            # comes back once NEW filtered scans accumulate
            continue
        rows_read = int(row.get("rows_read", 0))
        score = float(fcount * max(rows_read, 1))
        cost = _covered_bytes(t)
        detail = "cover=all"
        status = "proposed"
        if budget_bytes is not None and spent + cost > budget_bytes:
            # narrow to the role-referenced columns + key before giving
            # up (uncovered columns make the router fall back, so this
            # only helps queries that touch the hot column set)
            narrow = {c["column"] for c in row.get("columns", ())
                      if any(int(c.get(k, 0)) > 0 for k in
                             ("filter_count", "join_count",
                              "group_count", "sort_count"))}
            narrow.add(key_col)
            cost = _covered_bytes(t, narrow)
            detail = "cover=" + ",".join(sorted(narrow))
            if spent + cost > budget_bytes:
                status = "rejected:budget"
        if status == "proposed":
            spent += cost
        recs.append(Recommendation(
            action="create_projection", table=table, column=key_col,
            detail=detail, benefit=score, cost_bytes=cost,
            evidence=(f"scans={scans} rows_read={rows_read} "
                      f"filter_count={fcount} "
                      f"proj_hits={int(row.get('proj_hits', 0))}"),
            status=status,
        ))

    # ---- create vector indexes (ANN route enablement) ---------------
    # query heat on a VECTOR column's SORT role means someone is running
    # ORDER BY vec_l2(col, ?) LIMIT k as a brute-force full matmul; an
    # IVF index turns that into the optimizer's probe route
    from ..core.dtypes import TypeKind

    for row in access_rows:
        table = row["table"]
        t = catalog.get(table)
        if t is None or "#sp:" in table or table.startswith("__all_virtual"):
            continue
        if int(row.get("scans", 0)) < min_scans:
            continue
        for c in row.get("columns", ()):
            sc = int(c.get("sort_count", 0))
            if sc <= 0:
                continue
            cname = c["column"]
            fld = next(
                (f for f in t.schema.fields if f.name == cname), None)
            if fld is None or fld.dtype.kind is not TypeKind.VECTOR:
                continue
            if cname in getattr(t, "vector_indexes", {}):
                continue
            arr = t.data.get(cname)
            nrows = int(len(arr)) if arr is not None else 0
            if nrows <= 0:
                continue
            dim = int(np.asarray(arr).shape[1])
            # index footprint: perm (4B/row) + ~sqrt(n) centroids
            L = max(4, int(np.sqrt(nrows)))
            cost = nrows * 4 + L * dim * 4
            recs.append(Recommendation(
                action="create_vector_index", table=table, column=cname,
                detail="lists=auto", benefit=float(sc) * nrows,
                cost_bytes=cost,
                evidence=(f"vec_sorted_scans={sc} rows={nrows} dim={dim} "
                          f"brute_rows_per_query={nrows}"),
            ))

    # ---- drop idle advisor-created projections ----------------------
    for (table, key_col), pname in created.items():
        n_idle = int(idle.get((table, key_col), 0))
        if n_idle >= drop_after:
            recs.append(Recommendation(
                action="drop_projection", table=table, column=key_col,
                detail=pname, benefit=1.0,
                cost_bytes=-_covered_bytes(catalog.get(pname, _EMPTY)),
                evidence=(f"no projection hits for {n_idle} consecutive "
                          f"snapshot windows"),
            ))

    # ---- device residency priorities --------------------------------
    hot = [r for r in access_rows
           if int(r.get("scans", 0)) > 0
           and "#sp:" not in r["table"]
           and not r["table"].startswith("__all_virtual")]
    hot.sort(key=lambda r: -(int(r.get("rows_read", 0))
                             + int(r.get("das_rows", 0))))
    for rank, row in enumerate(hot):
        table = row["table"]
        score = int(row.get("rows_read", 0)) + int(row.get("das_rows", 0))
        prio = float(len(hot) - rank)
        recs.append(Recommendation(
            action="set_residency", table=table, detail=f"{prio:g}",
            benefit=float(score),
            evidence=(f"scans={int(row.get('scans', 0))} "
                      f"rows_read={int(row.get('rows_read', 0))} "
                      f"device_bytes={dev_bytes.get(table, 0)}"),
        ))

    # ---- column encodings (hot tables only) -------------------------
    for row in hot[:8]:
        table = row["table"]
        t = catalog.get(table)
        if t is None:
            continue
        for cname, a in t.data.items():
            a = np.asarray(a)
            if not np.issubdtype(a.dtype, np.integer) or len(a) == 0:
                continue
            stats = enc.analyze_ints(a)
            e, params = enc.choose_encoding(a, stats)
            if e == enc.ENC_RAW:
                continue
            raw_b = len(a) * a.dtype.itemsize
            if e == enc.ENC_CONST:
                best_b = a.dtype.itemsize
            elif e == enc.ENC_FOR:
                best_b = len(a) * params["width"]
            else:  # RLE
                best_b = 4 + stats.nruns * (4 + a.dtype.itemsize)
            saved = raw_b - best_b
            if saved < MIN_ENC_SAVINGS:
                continue
            via = "dict codes" if cname in getattr(t, "dicts", {}) else "raw"
            recs.append(Recommendation(
                action="set_encoding", table=table, column=cname,
                detail=_ENC_NAMES[e], benefit=float(saved),
                evidence=(f"{via} {raw_b}B -> {_ENC_NAMES[e]} {best_b}B "
                          f"(runs={stats.nruns} "
                          f"span={stats.vmax - stats.vmin})"),
            ))

    recs.sort(key=lambda r: (-r.benefit, r.action, r.table, r.column))
    return recs


@dataclass
class _Empty:
    data: dict = field(default_factory=dict)


_EMPTY = _Empty()


class LayoutAdvisor:
    """Stateful wrapper: hysteresis registries + the apply path through
    the tenant DAG scheduler. One per Database."""

    def __init__(self, db):
        self.db = db
        self._lock = threading.RLock()
        # (table, key_col) -> pname for projections THIS advisor built
        # (hand-built ones are never auto-dropped)
        self.created: dict[tuple, str] = {}
        # (table, key_col) -> consecutive snapshot windows with base-table
        # scans but zero projection hits
        self.idle: dict[tuple, int] = {}
        # (table, key_col) -> filter_count at auto-drop time (re-create
        # only after NEW filtered scans arrive)
        self.dropped: dict[tuple, int] = {}
        # (table, col) -> encoding name chosen by the cost model
        self.encoding_hints: dict[tuple, str] = {}
        # (table, column) vector indexes THIS advisor created: their
        # DML-invalidated rebuilds re-queue in any mode, like created[]
        self.created_vec: dict[tuple, bool] = {}
        self.last: list[Recommendation] = []
        self.runs = 0

    @property
    def mode(self) -> str:
        return str(self.db.config["ob_layout_advisor_mode"])

    # ------------------------------------------------------------ passes
    def run(self, apply: bool | None = None) -> list[Recommendation]:
        """One advisor pass over cumulative evidence. `apply=None` follows
        the configured mode (only `auto` mutates); explicit True/False
        overrides it (the smoke uses apply=True after a dry run)."""
        db = self.db
        with self._lock:
            recs = propose(
                db.access.snapshot(),
                db.catalog,
                budget_bytes=int(db.config["layout_advisor_max_bytes"]),
                created=self.created,
                idle=self.idle,
                dropped=self.dropped,
                census_rows=self._census(),
            )
            do_apply = (self.mode == "auto") if apply is None else apply
            if do_apply:
                self._apply(recs)
            else:
                for r in recs:
                    if r.status == "proposed":
                        r.status = "dry_run"
            self.last = recs
            self.runs += 1
            db.metrics.add("layout advisor runs")
            return recs

    def _census(self):
        try:
            from .workload import device_census

            return device_census(self.db)
        except Exception:  # census is evidence, never a failure mode
            return ()

    def on_snapshot(self, first, last) -> None:
        """WorkloadRepository.on_snapshot hook (chained after the health
        sentinel): track per-window projection usage for the drop rule,
        then run a pass (auto applies; dry_run refreshes proposals)."""
        if self.mode == "off":
            return
        win = self._window(first, last)
        with self._lock:
            for (table, key_col) in list(self.created):
                w = win.get(table)
                if w is None:
                    continue
                if w["proj_hits"] > 0:
                    self.idle[(table, key_col)] = 0
                elif w["scans"] > 0:
                    self.idle[(table, key_col)] = (
                        self.idle.get((table, key_col), 0) + 1)
        self.run()

    @staticmethod
    def _window(first, last) -> dict:
        f = {r["table"]: r for r in (first or {}).get("access", ())}
        out = {}
        for r in (last or {}).get("access", ()):
            fr = f.get(r["table"], {})
            d = {}
            for k in ("scans", "proj_hits"):
                delta = int(r.get(k, 0)) - int(fr.get(k, 0))
                # counter reset (TableAccessStats.reset bumps the epoch):
                # the window is the whole new accumulation
                d[k] = delta if delta >= 0 else int(r.get(k, 0))
            out[r["table"]] = d
        return out

    # ------------------------------------------------------------- apply
    def _apply(self, recs: list[Recommendation]) -> None:
        applied = 0
        for r in recs:
            if r.status != "proposed":
                continue
            if r.action == "create_projection":
                cols = None
                if r.detail.startswith("cover=") and r.detail != "cover=all":
                    cols = r.detail[len("cover="):].split(",")
                queued = self._queue_rebuild(r.table, r.column, cols)
                r.status = "queued" if queued else "queued:duplicate"
                applied += queued
            elif r.action == "drop_projection":
                self._drop(r.table, r.column, r.detail)
                r.status = "applied"
                applied += 1
            elif r.action == "create_vector_index":
                queued = self._queue_vector_build(r.table, r.column)
                r.status = "queued" if queued else "queued:duplicate"
                applied += queued
            elif r.action == "set_residency":
                self.db.residency_priority[r.table] = float(r.detail)
                r.status = "applied"
                applied += 1
            elif r.action == "set_encoding":
                self.encoding_hints[(r.table, r.column)] = r.detail
                self._push_encoding(r.table, r.column, r.detail)
                r.status = "applied"
                applied += 1
        if applied:
            self.db.metrics.add("layout advisor actions applied", applied)

    def _push_encoding(self, table: str, column: str, encoding: str) -> None:
        """Install the hint on every replica tablet of the table: the next
        dump/compaction writes its blocks with the chosen encoding, which
        is how the advisor's FOR/RLE/const picks persist on disk (and so
        across restarts — enc_hints also rides node_meta)."""
        db = self.db
        ti = db.tables.get(table)
        if ti is None:
            return
        for pls, ptab in ti.all_partitions():
            for rep in db.cluster.ls_groups.get(pls, {}).values():
                t = rep.tablets.get(ptab)
                if t is not None:
                    t.enc_hints[column] = encoding

    def _queue_rebuild(self, table: str, key_col: str,
                       cols=None) -> bool:
        """Enqueue a BACKGROUND-priority projection (re)build DAG; dedup
        by key while queued. Never runs on the statement path — workers or
        run_maintenance() drain it."""
        from ..share.dag_scheduler import Dag, DagPriority

        db = self.db
        pname = projection_name(table, key_col)
        with self._lock:
            self.created[(table, key_col)] = pname
            self.idle.setdefault((table, key_col), 0)
            self.dropped.pop((table, key_col), None)

        def build():
            from ..storage.sorted_projection import make_sorted_projection

            ti = db.tables.get(table)
            if ti is not None and ti.cached_data_version != ti.data_version:
                # DML landed since queueing: refresh the snapshot first so
                # the projection sorts current data, not the stale copy
                db.refresh_catalog([table])
            t = db.catalog.get(table)
            if t is None or key_col not in t.data:
                return  # table dropped while queued
            if key_col in getattr(t, "sorted_projections", {}):
                return  # already built (hand or a racing rebuild)
            make_sorted_projection(db.catalog, table, key_col, cols)
            db._invalidate(pname)
            # cached plans were routed before this layout existed
            db.plan_cache.flush()
            db.metrics.add("layout advisor projections built")

        dag = Dag("layout rebuild", DagPriority.BACKGROUND,
                  key=("layout rebuild", pname))
        dag.add_task(build, name=f"build {pname}")
        return db.dag_scheduler.add_dag(dag)

    def _queue_vector_build(self, table: str, column: str) -> bool:
        """Enqueue a BACKGROUND IVF build DAG: register the spec (auto
        lists, default nprobe — the same durable registration the DDL
        path writes) and warm the executor's index artifact off the
        statement path, so the first ANN query probes instead of paying
        the k-means build inline. Dedup by (table, column) while
        queued."""
        from ..share.dag_scheduler import Dag, DagPriority

        db = self.db
        with self._lock:
            self.created_vec[(table, column)] = True

        def build():
            from ..storage.vector_index import register_vector_index

            # ti is None for preloaded read-only tables that live only in
            # the catalog — the build still proceeds off the catalog copy
            ti = db.tables.get(table)
            if ti is not None and ti.cached_data_version != ti.data_version:
                db.refresh_catalog([table])
            t = db.catalog.get(table)
            if t is None or column not in t.data:
                return
            if column not in getattr(t, "vector_indexes", {}):
                specs = db._vector_specs.setdefault(table, {})
                specs.setdefault(column, (0, 8))
                lists, nprobe = specs[column]
                register_vector_index(db.catalog, table, column,
                                      lists, nprobe)
                db._save_node_meta()
            try:
                db.engine.executor.ivf_host(table, column)
            except Exception:  # noqa: BLE001 - warm-build is advisory
                pass
            # cached brute-force plans predate the index route
            db.plan_cache.flush()
            db.metrics.add("layout advisor vector indexes built")

        dag = Dag("vector index build", DagPriority.BACKGROUND,
                  key=("vector index build", table, column))
        dag.add_task(build, name=f"build ivf {table}.{column}")
        return db.dag_scheduler.add_dag(dag)

    def note_vector_invalidated(self, table: str, cols) -> None:
        """refresh_catalog hook, after a DML-invalidated table's vector
        specs re-register: queue background rebuilds (auto mode, or any
        advisor-created index) so the next ANN query probes a warm index
        instead of paying the k-means rebuild inline."""
        for col in cols:
            if self.mode != "auto" and (table, col) not in self.created_vec:
                continue
            self._queue_vector_build(table, col)

    def _drop(self, table: str, key_col: str, pname: str) -> None:
        db = self.db
        t = db.catalog.get(table)
        projs = getattr(t, "sorted_projections", {}) if t is not None else {}
        if projs.get(key_col) == pname:
            t.sorted_projections = {
                k: v for k, v in projs.items() if k != key_col}
        db.catalog.pop(pname, None)
        db._invalidate(pname)
        db.plan_cache.flush()
        with self._lock:
            self.created.pop((table, key_col), None)
            self.idle.pop((table, key_col), None)
            # remember the evidence level so the same cumulative counters
            # don't immediately re-create what we just dropped
            fcount = 0
            for row in db.access.snapshot():
                if row["table"] != table:
                    continue
                for c in row.get("columns", ()):
                    if c["column"] == key_col:
                        fcount = int(c.get("filter_count", 0))
            self.dropped[(table, key_col)] = fcount
        db.metrics.add("layout advisor projections dropped")

    # ------------------------------------------------- DML invalidation
    def note_invalidated(self, table: str, projs: dict):
        """Called by refresh_catalog BEFORE it drops a DML-invalidated
        table's projections (the catalog still holds them, so covered
        column sets survive into the rebuild). Returns a zero-arg
        callable the caller invokes AFTER the refreshed snapshot lands —
        queueing any earlier lets a live dag worker observe the stale
        table version and re-enter refresh_catalog concurrently (double-
        counted invalidation, duplicate rebuild). In auto mode — or for
        any advisor-created projection — the layout is re-queued for
        background rebuild instead of silently lost."""
        db = self.db
        jobs = []
        for key_col, pname in projs.items():
            if self.mode != "auto" and (table, key_col) not in self.created:
                continue
            cols = None
            pt = db.catalog.get(pname)
            base = db.catalog.get(table)
            if (pt is not None and base is not None
                    and len(pt.schema.fields) < len(base.schema.fields)):
                cols = [f.name for f in pt.schema.fields]
            jobs.append((key_col, cols))

        def queue():
            for key_col, cols in jobs:
                self._queue_rebuild(table, key_col, cols)

        return queue
