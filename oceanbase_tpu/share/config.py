"""Typed cluster/tenant parameter system with hot reload.

Reference surface: the ~650 DEF_INT/DEF_CAP/DEF_TIME/DEF_BOOL parameter
declarations (share/parameter/ob_parameter_seed.ipp:36+) and the config
manager that validates, persists and hot-reloads them (ObConfigManager,
share/config/ob_config_manager.h; ALTER SYSTEM SET handled via
observer/ob_server_reload_config.cpp).

The rebuild keeps the same model — a declarative registry of typed,
range-checked, scoped parameters; dynamic ones take effect immediately via
change callbacks, static ones require restart — with a compact seed of the
parameters that actually gate rebuild behavior.

Value syntax follows the reference: capacities accept K/M/G/T suffixes,
times accept us/ms/s/m/h suffixes.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field


class ConfigError(Exception):
    pass


_CAP_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([KMGTP]?)B?$", re.I)
_TIME_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*(us|ms|s|m|h|d)?$", re.I)
_CAP_MULT = {"": 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30,
             "T": 1 << 40, "P": 1 << 50}
_TIME_MULT = {"us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
              "d": 86400.0, "": 1.0}


def parse_capacity(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    m = _CAP_RE.match(str(v).strip())
    if not m:
        raise ConfigError(f"bad capacity {v!r}")
    return int(float(m.group(1)) * _CAP_MULT[m.group(2).upper()])


def parse_time(v) -> float:
    """Time value in seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    m = _TIME_RE.match(str(v).strip())
    if not m:
        raise ConfigError(f"bad time {v!r}")
    return float(m.group(1)) * _TIME_MULT[(m.group(2) or "").lower()]


_PARSERS = {
    "int": lambda v: int(str(v), 0),
    "double": lambda v: float(v),
    "bool": lambda v: (
        v if isinstance(v, bool)
        else {"true": True, "1": True, "on": True,
              "false": False, "0": False, "off": False}[str(v).lower()]
    ),
    "str": lambda v: str(v),
    "capacity": parse_capacity,
    "time": parse_time,
}


@dataclass(frozen=True)
class Param:
    name: str
    type: str  # int | double | bool | str | capacity | time
    default: object
    info: str = ""
    scope: str = "tenant"  # cluster | tenant
    dynamic: bool = True  # hot-reloadable (False -> takes effect at restart)
    min: float | None = None
    max: float | None = None
    choices: tuple[str, ...] | None = None

    def parse(self, value):
        try:
            v = _PARSERS[self.type](value)
        except (KeyError, ValueError, TypeError) as e:
            raise ConfigError(f"{self.name}: bad value {value!r}: {e}") from None
        if self.min is not None and v < self.min:
            raise ConfigError(f"{self.name}: {v} < min {self.min}")
        if self.max is not None and v > self.max:
            raise ConfigError(f"{self.name}: {v} > max {self.max}")
        if self.choices is not None and v not in self.choices:
            raise ConfigError(f"{self.name}: {v!r} not in {self.choices}")
        return v


def default_params() -> list[Param]:
    """Seed registry: the parameters the rebuild's subsystems consult.

    Names mirror the reference's where a direct analog exists
    (ob_parameter_seed.ipp)."""
    return [
        # SQL / plan cache
        Param("plan_cache_capacity", "int", 128,
              "max compiled plans (XLA executables) kept per tenant",
              min=1, max=1 << 16),
        Param("ob_enable_plan_cache", "bool", True,
              "serve compiled plans from the cache"),
        Param("parallel_servers_target", "int", 64,
              "cluster-wide PX worker admission quota", scope="cluster",
              min=0),
        Param("ob_sql_parallel_degree", "int", 8,
              "default DOP for PX plans", min=1, max=4096),
        Param("ob_batch_max_size", "int", 16,
              "cross-session micro-batching: max fast-path statements "
              "folded into one batched device dispatch (1 disables "
              "batching); clamped by the tenant unit's max_workers",
              min=1, max=1024),
        Param("ob_batch_max_wait_us", "int", 200,
              "cross-session micro-batching: group-commit window (us) a "
              "batch leader holds open for followers before dispatching",
              min=0, max=1_000_000),
        Param("ob_batch_follower_timeout", "time", 10.0,
              "continuous batching: how long a follower lane waits on "
              "its cohort's dispatch before pulling out and re-executing "
              "solo (a queued leader waits 2x this for gate admission)",
              min=0.01, max=600.0),
        Param("ob_batch_queue_depth", "int", 32,
              "continuous batching: max forming groups queued per tenant "
              "at the dispatch gate; arrivals beyond it shed to the solo "
              "fast path", min=1, max=4096),
        Param("ob_enable_result_narrow", "bool", True,
              "whole-statement fusion: compile the final result-frame "
              "gather (compaction + projection to the rows the client "
              "gets) INTO the plan's device program — one dispatch, one "
              "D2H of final bytes"),
        Param("ob_result_narrow_rows", "int", 256,
              "fused result frame seed width (rows) when the plan root "
              "gives no exact bound (LIMIT/scalar aggregate do); grows "
              "pow2 on frame overflow", min=1, max=1 << 20),
        Param("ob_result_narrow_max_rows", "int", 4096,
              "fused result frame ceiling: a statement whose live result "
              "exceeds this falls back to the plain lazy cursor (wide "
              "results want the transfer-on-touch contract anyway)",
              min=1, max=1 << 24),
        Param("ob_enable_result_cache", "bool", True,
              "device-resident result cache: repeated dashboard "
              "statements (same text, literals, snapshot watermark) "
              "serve the cached narrowed frame with zero dispatches"),
        Param("ob_result_cache_size", "capacity", 4 << 20,
              "result cache capacity (bytes, LRU): charged against the "
              "tenant memory unit through the governor residency "
              "surface", min=0),
        Param("ob_result_cache_entry_limit", "capacity", 65536,
              "max bytes one cached result may occupy (dashboards are "
              "small; big results stay on the lazy cursor path)", min=0),
        Param("ob_enable_completion_drain", "bool", False,
              "serve-then-account: move audit/summary/metrics/timeline "
              "completion folds and governor release behind the wire "
              "write onto a bounded drain worker (exactly-once; "
              "observability surfaces lag the response by the drain "
              "depth — tools that read sql_audit synchronously should "
              "leave this off)"),
        Param("ob_completion_drain_depth", "int", 256,
              "completion drain: max queued statement-completion folds "
              "before submitters fold inline (backpressure, no drops)",
              min=1, max=1 << 16),
        Param("ob_enable_batch_coalesce", "bool", True,
              "micro-batching: let two heterogeneous-plan cohorts "
              "sharing a pow2 bucket shape coalesce into one fused "
              "device dispatch at the gate"),
        Param("ob_tenant_admission_slots", "int", 8,
              "weighted tenant admission: running permits for gated "
              "fast-path statements, shared cluster-wide and allotted "
              "by TenantUnit.weight share; a flooding tenant over its "
              "share waits while other tenants are active (single-"
              "tenant clusters bypass the permit)", min=1, max=1024),
        Param("mysql_async_workers", "int", 8,
              "async MySQL front end: bounded statement-execution worker "
              "pool size (protocol work stays on the event loop)",
              min=1, max=256),
        # memory / freeze / compaction
        Param("memstore_limit", "capacity", 256 << 20,
              "per-tenant active+frozen memtable budget"),
        Param("freeze_trigger_ratio", "double", 0.5,
              "fraction of memstore_limit that triggers a tenant freeze",
              min=0.01, max=0.99),
        Param("minor_compact_trigger", "int", 2,
              "delta sstable count that triggers a minor compaction",
              min=1, max=64),
        Param("major_compact_interval", "time", 0.0,
              "0 disables time-based major compaction"),
        # log / consensus
        Param("log_disk_utilization_limit", "double", 0.95,
              "palf stops appending beyond this disk fraction",
              scope="cluster", min=0.5, max=1.0),
        Param("lease_duration", "time", 4.0,
              "election lease window (RTO driver)", scope="cluster",
              dynamic=False, min=0.5),
        # observability
        Param("enable_sql_audit", "bool", True,
              "record per-statement audit entries"),
        Param("sql_audit_memory_limit", "capacity", 64 << 20,
              "ring-buffer budget for sql_audit"),
        Param("enable_perf_event", "bool", True,
              "per-operator plan monitor collection"),
        Param("enable_query_profile", "bool", True,
              "per-query TPU resource profiling: compile cache hit/miss, "
              "host<->device transfer bytes, device working set"),
        Param("trace_log_slow_query_watermark", "time", 1.0,
              "statements slower than this get a flight-recorder "
              "diagnostic bundle (span tree, plan, metrics delta)",
              min=0.0),
        Param("syslog_level", "str", "INFO", "server log level",
              choices=("DEBUG", "TRACE", "INFO", "WARN", "ERROR")),
        # workload repository (server/workload.py)
        Param("enable_sql_stat", "bool", True,
              "fold completed statements into the digest-keyed statement "
              "summary and table/column access stats"),
        Param("ob_sql_stat_max_digests", "int", 256,
              "statement-summary digest cap; cold digests evict beyond it",
              min=8, max=1 << 20),
        Param("workload_snapshot_capacity", "int", 16,
              "bounded count of workload snapshots held in memory",
              min=2, max=4096),
        Param("workload_snapshot_interval", "time", 0.0,
              "0 disables periodic workload snapshots; otherwise at most "
              "one snapshot per interval, checked at statement completion",
              min=0.0),
        # serving timeline + health sentinel (share/timeline.py,
        # server/sentinel.py)
        Param("enable_serving_timeline", "bool", True,
              "feed the time-sliced serving telemetry ring (device busy, "
              "queue depth, per-tenant QoS) from the statement path"),
        Param("serving_timeline_bucket", "time", 1.0,
              "width of one serving-timeline bucket", min=0.05),
        Param("serving_timeline_capacity", "int", 120,
              "bounded count of timeline buckets held in the ring",
              min=8, max=1 << 16),
        # host-tax gap ledger + stack sampler (share/gap_ledger.py)
        Param("enable_host_tax", "bool", True,
              "conservation-account every statement's e2e wall into "
              "named host phases + an explicit unattributed residual "
              "(share/gap_ledger.py, __all_virtual_host_tax)"),
        Param("host_tax_max_digests", "int", 256,
              "bounded count of per-digest host-tax aggregates",
              min=8, max=1 << 16),
        Param("host_tax_window", "time", 1.0,
              "width of one host-tax chip-idle window bucket", min=0.05),
        Param("enable_stack_sampler", "bool", False,
              "keep the in-process wall-clock stack sampler armed "
              "continuously (otherwise it only auto-arms after a "
              "statement crosses the slow-query watermark)"),
        Param("stack_sampler_interval", "time", 0.005,
              "stack sampler period", min=0.0001),
        Param("stack_sampler_auto_arm", "time", 2.0,
              "how long the sampler stays armed after a statement "
              "crosses trace_log_slow_query_watermark; 0 disables "
              "auto-arming", min=0.0),
        # operator-level plan telemetry (engine/plan_profile.py)
        Param("enable_plan_profile", "bool", True,
              "sampled per-operator profiled execution: segmented fenced "
              "stages yield device time / cardinality / bytes per plan "
              "node as (estimate, actual) calibration pairs "
              "(__all_virtual_sql_plan_monitor per-operator rows)"),
        Param("ob_plan_profile_sample", "int", 64,
              "profile every statement digest's first re-execution (one-"
              "shot digests never pay a segmented trace), then 1-in-N of "
              "its later executions; 0 = first re-execution only",
              min=0, max=1 << 20),
        Param("ob_plan_profile_max_digests", "int", 128,
              "bounded count of per-digest operator calibration records",
              min=1, max=1 << 16),
        Param("enable_health_sentinel", "bool", True,
              "evaluate health rules (latency regressions, starvation, "
              "compile storms...) on every workload snapshot"),
        Param("health_alert_capacity", "int", 256,
              "bounded count of sentinel alerts held in memory",
              min=8, max=1 << 16),
        Param("ob_layout_advisor_mode", "str", "off",
              "closed-loop layout advisor: off (explicit runs only "
              "propose), dry_run (also proposes on every workload "
              "snapshot, mutates nothing), auto (applies through "
              "background rebuild dags)",
              choices=("off", "dry_run", "auto")),
        Param("layout_advisor_max_bytes", "capacity", 512 << 20,
              "budget for advisor-materialized layouts (sorted "
              "projections); candidates over budget are narrowed to the "
              "role-referenced columns, then rejected"),
        # plan artifact store (engine/plan_artifact.py)
        Param("ob_plan_artifact_mode", "str", "off",
              "persistent compiled-plan artifacts: off (memory-only plan "
              "cache), ro (hydrate executables from disk, never write), "
              "rw (also export on compile and re-export on overflow "
              "recompile)",
              choices=("off", "ro", "rw")),
        Param("plan_artifact_dir", "str", "",
              "artifact store directory; empty resolves to "
              "<data_dir>/plan_artifacts (in-memory clusters need an "
              "explicit path for warm restarts to mean anything)"),
        Param("plan_artifact_max_bytes", "capacity", 256 << 20,
              "byte budget for exported executables on disk and for the "
              "boot-time warm-load of the hottest digests; coldest "
              "artifacts evict beyond it"),
        # elastic serving (follower reads + rootserver rebalancing)
        Param("ob_read_consistency", "str", "strong",
              "default read consistency for new sessions: strong (leader "
              "only), bounded_staleness (follower snapshot within "
              "ob_max_read_stale_us), weak (any replica watermark)",
              choices=("strong", "bounded_staleness", "weak")),
        Param("ob_max_read_stale_us", "int", 5_000_000,
              "bounded-staleness ceiling in microseconds of GTS time; a "
              "follower whose apply watermark lags further rejects the "
              "read back to the leader", min=0),
        Param("enable_leader_rebalance", "bool", True,
              "let the rootserver move LS leaders off unreachable or "
              "QoS-overloaded nodes as background dags"),
        Param("leader_rebalance_min_interval", "time", 5.0,
              "floor between rootserver rebalance passes (hysteresis "
              "against leader ping-pong)"),
        # device memory governor
        Param("ob_device_memory_limit", "capacity", 0,
              "device HBM budget the memory governor reserves against; "
              "0 = auto (a fraction of detected HBM, or a synthetic "
              "budget on CPU backends)", scope="cluster", min=0),
        Param("ob_governor_queue_timeout", "time", 5.0,
              "max wait on the 'device memory reservation' event before "
              "a statement is rejected (deadline-bounded)", min=0.0),
        Param("ob_governor_max_queue", "int", 64,
              "queue-depth backpressure: reservation requests beyond "
              "this many waiters are rejected immediately",
              scope="cluster", min=1, max=1 << 16),
        Param("ob_governor_cold_reserve", "capacity", 16 << 20,
              "conservative peak-working-set reservation for digests "
              "the workload repository has not measured yet", min=0),
        # storage
        Param("block_cache_size", "capacity", 256 << 20,
              "budget for decoded micro-block column cache"),
        Param("default_compress_func", "str", "for",
              "preferred micro-block codec family",
              choices=("raw", "for", "rle", "auto")),
        Param("micro_block_rows", "int", 16384,
              "rows per micro block at dump time", min=256, max=1 << 20),
        # storage integrity (storage/integrity.py + storage/scrub.py)
        Param("ob_scrub_interval", "time", 0.0,
              "floor between background storage-scrub passes verifying "
              "every durable artifact's checksum envelope; 0 disables "
              "the scrubber", min=0.0),
        Param("ob_errsim_disk_bitflip", "double", 0.0,
              "disk-fault injection: probability a durable write/read "
              "flips one payload byte (EN_DISK_BITFLIP arm)",
              min=0.0, max=1.0),
        Param("ob_errsim_disk_torn_write", "double", 0.0,
              "disk-fault injection: probability a durable write "
              "persists only a prefix (EN_DISK_TORN_WRITE arm)",
              min=0.0, max=1.0),
        Param("ob_errsim_disk_truncate", "double", 0.0,
              "disk-fault injection: probability a durable file loses "
              "its tail before a read (EN_DISK_TRUNCATE arm)",
              min=0.0, max=1.0),
        Param("ob_errsim_disk_io_error", "double", 0.0,
              "disk-fault injection: probability a durable read/write "
              "raises an I/O error (EN_IO_ERROR arm)",
              min=0.0, max=1.0),
        # security
        Param("secure_file_priv", "str", "",
              "directory non-root external-table locations must resolve "
              "inside; empty = root-only (MySQL secure_file_priv analog)",
              scope="cluster"),
    ]


class Config:
    """A parameter namespace (one per tenant + one cluster scope).

    set() validates, records, and fires change callbacks for dynamic
    params; static params are recorded but only picked up by subsystems
    that re-read at (re)start — matching the reference's semantics.
    """

    def __init__(self, params: list[Param] | None = None):
        self.registry: dict[str, Param] = {
            p.name: p for p in (params if params is not None else default_params())
        }
        self._values: dict[str, object] = {
            p.name: p.default for p in self.registry.values()
        }
        self._lock = threading.RLock()
        self._listeners: dict[str, list] = {}
        self.version = 0

    # ------------------------------------------------------------- access
    def get(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise ConfigError(f"unknown parameter {name}") from None

    def __getitem__(self, name: str):
        return self.get(name)

    def set(self, name: str, value) -> None:
        p = self.registry.get(name)
        if p is None:
            raise ConfigError(f"unknown parameter {name}")
        v = p.parse(value)
        with self._lock:
            old = self._values[name]
            self._values[name] = v
            self.version += 1
            listeners = list(self._listeners.get(name, ())) if p.dynamic else []
        for fn in listeners:
            fn(name, old, v)

    def on_change(self, name: str, fn) -> None:
        """Register a hot-reload callback for a dynamic parameter."""
        if name not in self.registry:
            raise ConfigError(f"unknown parameter {name}")
        self._listeners.setdefault(name, []).append(fn)

    def snapshot(self) -> list[tuple[str, object, Param]]:
        with self._lock:
            return [
                (n, self._values[n], p)
                for n, p in sorted(self.registry.items())
            ]
