"""Shared cluster infrastructure (reference: src/share).

config.py          typed parameter registry + hot reload (DEF_* analog)
schema_service.py  multi-version schema cache (ObMultiVersionSchemaService)
location.py        LS -> leader-node cache w/ refresh (ObLocationService)
metrics.py         sysstat/wait-event/histogram registry (ob_stat_event)
"""

from .config import Config, Param, default_params
from .location import LocationService
from .metrics import Histogram, MetricsRegistry, WaitEvent
from .schema_service import SchemaGuard, SchemaService

__all__ = [
    "Config",
    "Param",
    "default_params",
    "LocationService",
    "SchemaService",
    "SchemaGuard",
    "MetricsRegistry",
    "WaitEvent",
    "Histogram",
]
