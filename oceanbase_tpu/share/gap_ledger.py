"""Host-tax gap ledger: conservation-complete e2e wall attribution.

BENCH_r05 shows the chip nearly idle end to end (warm Q6 tpu_s 5ms vs
e2e_s 115ms) and no existing surface explains the gap: sql_audit phase
columns, the ServingTimeline and QueryProfile each cover fragments of
the statement wall and none of them sums to 100% or names the residual.
This module is the measurement layer for ROADMAP item 2 ("crush the
host tax"): a per-statement ledger where every second of e2e wall lands
in exactly one named phase, with an explicit ``unattributed`` residual
(e2e - sum(phases)) that is surfaced and gated rather than silently
absorbed into neighbouring phases.

Three pieces:

* :class:`GapLedger` — one per statement.  Phases are recorded either
  directly (``add``) or as *hints* inside a measured window
  (``window_start``/``window_end``): inner layers (batcher, governor,
  engine carve) self-report what they know, and ``window_end`` clamps
  the hints proportionally if they exceed the measured wall of the
  window.  That clamp is the conservation guarantee — per-window
  sum(hints) <= window wall, hence globally sum(phases) <= e2e, hence
  ``unattributed = e2e - sum(phases) >= 0`` always holds exactly.
  Device-busy spans (``device``) interleave with the host phases to
  give per-statement ``chip_idle_pct``.

* :class:`HostTaxRegistry` — bounded per-digest aggregate (count,
  e2e, device, per-phase sums, unattributed) behind
  ``__all_virtual_host_tax``, plus a small per-window ring for the
  window-level chip-idle view in awr_report.

* :class:`StackSampler` — a low-overhead in-process profiler over
  stdlib ``sys._current_frames``: off by default (no thread), armed by
  config or automatically for statements over the slow-query
  watermark; collapsed semicolon-joined stacks in a bounded counter
  ride the FlightRecorder bundle.

Inner layers reach the statement's ledger through a thread-local
(``current()``) set by ``server/database.py`` for the duration of the
statement — the batcher and governor run their waits on the statement's
own thread, so no API plumbing is needed to get hints home.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple

# Canonical phase order for rendering (waterfalls, awr, README walk-
# through).  Phases not listed render after these in name order.
PHASE_ORDER = (
    "wire read",
    "admission queue",
    "setup",
    "fast lookup",
    "parse bind",
    "tenant permit",
    "batch window",
    "governor reserve",
    "plan compile",
    "param pack",
    "h2d",
    "device dispatch",
    "device wait",
    "d2h",
    "result fold",
    "engine host",
    "retry backoff",
    "completion fold",
    "wire write",
)


def phase_sort_key(name: str) -> Tuple[int, str]:
    try:
        return (PHASE_ORDER.index(name), name)
    except ValueError:
        return (len(PHASE_ORDER), name)


class GapLedger:
    """Conservation accounting for one statement's e2e wall."""

    __slots__ = ("clock", "t0", "phases", "device_s", "_pending",
                 "_win_t0", "_cursor", "e2e_s", "unattributed_s", "closed")

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self.clock = clock
        self.t0 = 0.0
        self.phases: Dict[str, float] = {}
        self.device_s = 0.0
        self._pending: Optional[List[Tuple[str, float]]] = None
        self._win_t0 = 0.0
        self._cursor = 0.0
        self.e2e_s = 0.0
        self.unattributed_s = 0.0
        self.closed = False

    # -- lifecycle ----------------------------------------------------
    def begin(self, t0: Optional[float] = None) -> "GapLedger":
        """(Re)arm for one statement.  Fully resets state: the serving
        session reuses ONE ledger object per session instead of
        allocating ledger + dicts per statement (the fast path is
        ~200us end to end; allocator/GC churn there is measurable)."""
        self.t0 = self.clock() if t0 is None else t0
        self._cursor = self.t0
        if self.phases:
            self.phases.clear()
        self.device_s = 0.0
        self._pending = None
        self.e2e_s = 0.0
        self.unattributed_s = 0.0
        self.closed = False
        return self

    def close(self, t_end: Optional[float] = None) -> "GapLedger":
        if self._pending is not None:  # unbalanced window: flush clamped
            self.window_end()
        self.e2e_s = max(0.0, (self.clock() if t_end is None else t_end)
                         - self.t0)
        attributed = sum(self.phases.values())
        # The residual is the whole point: never fold it into a phase.
        self.unattributed_s = max(0.0, self.e2e_s - attributed)
        self.closed = True
        return self

    # -- phase recording ----------------------------------------------
    def add(self, phase: str, seconds: float) -> None:
        """Attribute ``seconds`` to ``phase``.

        Inside a window the value is buffered as a hint and clamped at
        ``window_end`` so hinted phases can never exceed the measured
        window wall; outside a window it applies directly (the caller
        measured the span itself).
        """
        if seconds <= 0.0:
            return
        if self._pending is not None:
            self._pending.append((phase, seconds))
        else:
            self.phases[phase] = self.phases.get(phase, 0.0) + seconds
            # the caller measured a span that just ended: advance the
            # serial cursor so a following cut() doesn't re-cover it
            self._cursor = self.clock()

    def cut(self, phase: str) -> None:
        """Attribute ALL wall since the last cut/add/window (or begin)
        to ``phase`` and advance the cursor.

        The serial serving path uses contiguous cuts instead of paired
        perf_counter reads: every nanosecond of inter-span glue (context
        managers, dict bookkeeping, call/return frames) lands in the
        adjacent named phase instead of leaking into ``unattributed`` —
        which matters on a warm fast-path point read where the whole
        statement is ~200us and glue alone would blow the residual gate.
        Not meaningful inside a window (hints there are clamped spans,
        not a serial timeline); calls while a window is open are ignored.
        """
        if self._pending is not None:
            return
        now = self.clock()
        dt = now - self._cursor
        self._cursor = now
        if dt > 0.0:
            self.phases[phase] = self.phases.get(phase, 0.0) + dt

    def device(self, seconds: float) -> None:
        """Record device-busy wall overlapping this statement."""
        if seconds > 0.0:
            self.device_s += seconds

    # -- measured windows ---------------------------------------------
    def window_start(self) -> None:
        self._pending = []
        self._win_t0 = self.clock()

    def window_end(self, default_phase: Optional[str] = None) -> float:
        """Close the window; distribute buffered hints over its wall.

        If sum(hints) exceeds the measured window wall (overlapping
        inner spans, clock skew) every hint is scaled down
        proportionally so the window never over-attributes.  Remaining
        window wall goes to ``default_phase`` when given (the named
        measured remainder, e.g. "engine host"), else stays for the
        global ``unattributed`` residual to pick up.  Returns the
        window wall.
        """
        pending, self._pending = self._pending, None
        now = self.clock()
        self._cursor = now  # the serial timeline resumes at window end
        wall = max(0.0, now - self._win_t0)
        hinted = sum(s for _p, s in (pending or ()))
        scale = 1.0
        if hinted > wall:
            scale = (wall / hinted) if hinted > 0.0 else 0.0
            hinted = wall
        for p, s in pending or ():
            if s > 0.0:
                self.phases[p] = self.phases.get(p, 0.0) + s * scale
        if default_phase is not None and wall > hinted:
            self.phases[default_phase] = (
                self.phases.get(default_phase, 0.0) + (wall - hinted))
        return wall

    def window_end_carved(self, engine_phases: dict,
                          default_phase: Optional[str] = None,
                          include_fastparse: bool = False,
                          served_stream_hints: bool = True) -> float:
        """Fused carve + window_end for the serving hot path: pushes the
        engine's measured subphases (``Session.last_phases``) into the
        open window as hints and closes it, in ONE call instead of
        carve + N add() + device() + window_end (the per-statement call
        count is the ledger's main serving cost)."""
        hints, dev = carve_engine_phases(
            engine_phases, include_fastparse=include_fastparse,
            served_stream_hints=served_stream_hints)
        if dev > 0.0:
            self.device_s += dev
        p = self._pending
        if p is not None:
            p.extend(hints.items())
        else:  # defensive: no window open, apply directly
            ph = self.phases
            for k, v in hints.items():
                ph[k] = ph.get(k, 0.0) + v
        return self.window_end(default_phase)

    # -- views ---------------------------------------------------------
    @property
    def chip_idle_pct(self) -> float:
        if self.e2e_s <= 0.0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.device_s / self.e2e_s)) * 100.0

    def to_dict(self) -> dict:
        return {
            "e2e_s": round(self.e2e_s, 9),
            "device_s": round(self.device_s, 9),
            "chip_idle_pct": round(self.chip_idle_pct, 3),
            "unattributed_s": round(self.unattributed_s, 9),
            "unattributed_pct": round(
                100.0 * self.unattributed_s / self.e2e_s, 3)
            if self.e2e_s > 0 else 0.0,
            "phases": {
                k: round(v, 9) for k, v in sorted(
                    self.phases.items(), key=lambda kv: phase_sort_key(kv[0]))
            },
        }

    @classmethod
    def from_phases(cls, e2e_s: float, phases: dict,
                    device_s: float = 0.0) -> "GapLedger":
        """Build a conservation-complete ledger from an engine-level
        ``Session.last_phases`` dict (bench.py drives the engine Session
        directly, without the serving stack around it)."""
        led = cls(clock=lambda: 0.0)
        led.t0 = 0.0
        hints, dev = carve_engine_phases(phases)
        led.phases.update(hints)
        # Engine-internal wall not covered by the timed subphases is the
        # honest "engine host" remainder, bounded by exec_s (exec_s does
        # not include fastparse/bind, which run before exec_t0).
        exec_s = float(phases.get("exec_s", 0.0) or 0.0)
        covered = sum(led.phases.values()) \
            - led.phases.get("fast lookup", 0.0) \
            - led.phases.get("param pack", 0.0) \
            - led.phases.get("plan compile", 0.0)
        if exec_s > covered:
            led.phases["engine host"] = exec_s - covered
        # Clamp: never attribute more than the e2e wall we were given.
        total = sum(led.phases.values())
        if e2e_s > 0.0 and total > e2e_s:
            scale = e2e_s / total
            for k in led.phases:
                led.phases[k] *= scale
        led.device_s = device_s if device_s > 0.0 else dev
        led.e2e_s = max(0.0, e2e_s)
        led.unattributed_s = max(0.0, led.e2e_s - sum(led.phases.values()))
        led.closed = True
        return led


def carve_engine_phases(phases: dict,
                        include_fastparse: bool = True,
                        served_stream_hints: bool = False
                        ) -> Tuple[Dict[str, float], float]:
    """Map an engine ``Session.last_phases`` dict onto ledger phase
    names.  Returns ``(hints, device_busy_s)``.

    Nesting rules: the per-chunk stream H2D wall sits INSIDE dispatch_s
    (the streamed plan executes under run()), so its non-overlapped part
    is carved OUT of "device dispatch" — never counted twice.  On the
    serving path the pipeline already hinted that H2D wall (and the
    chunk compute as device busy) onto the live ledger; pass
    ``served_stream_hints=True`` so the carve still subtracts it from
    dispatch but does not emit its own "h2d"/compute.  Device busy is
    approximated by the walls the host provably spent waiting on or
    driving the chip: dispatch (enqueue + compute on sync backends) +
    the fetch sync, or stream compute for chunked plans.
    """
    # straight-line, closure-free: this runs once per served statement
    hints: Dict[str, float] = {}
    g = phases.get
    v = (g("plan_s", 0.0) or 0.0) + (g("compile_s", 0.0) or 0.0)
    if v > 0.0:
        hints["plan compile"] = v
    if include_fastparse:
        v = g("fastparse_s", 0.0) or 0.0
        if v > 0.0:
            hints["fast lookup"] = v
    v = g("bind_s", 0.0) or 0.0
    if v > 0.0:
        hints["param pack"] = v
    dispatch = g("dispatch_s", 0.0) or 0.0
    fetch = g("fetch_s", 0.0) or 0.0
    # column-data transfers accumulate into BOTH fetch_s and d2h_s
    # (executor.DeviceResult._observe): carve the transfer wall out of
    # the sync wall so "d2h" and "device wait" never overlap
    d2h = g("d2h_s", 0.0) or 0.0
    if d2h > fetch:
        d2h = fetch
    wait = fetch - d2h
    sh2d = g("stream_h2d_s", 0.0) or 0.0
    scompute = g("stream_compute_s", 0.0) or 0.0
    if sh2d > 0.0 or scompute > 0.0:
        soverlap = g("stream_overlap_s", 0.0) or 0.0
        h2d_wall = min(max(0.0, sh2d - soverlap), dispatch)
        if not served_stream_hints and h2d_wall > 0.0:
            hints["h2d"] = h2d_wall
        if dispatch > h2d_wall:
            hints["device dispatch"] = dispatch - h2d_wall
        device_s = wait if served_stream_hints else scompute + wait
    else:
        if dispatch > 0.0:
            hints["device dispatch"] = dispatch
        device_s = dispatch + wait
    if d2h > 0.0:
        hints["d2h"] = d2h
    if wait > 0.0:
        hints["device wait"] = wait
    return hints, device_s


# -- thread-local current ledger --------------------------------------
# database.py installs the statement's ledger here for the statement's
# lifetime; batcher/governor/engine hints ride it from the same thread.
_tls = threading.local()


def set_current(led: Optional[GapLedger]) -> None:
    _tls.led = led


def current() -> Optional[GapLedger]:
    return getattr(_tls, "led", None)


class LedgerSnapshot:
    """Frozen copy of a closed ledger's fold-relevant surface.

    The serving session REUSES one GapLedger per statement (begin()
    re-arms it in place), so completion work deferred behind the wire
    write (server/completion.py) must never hold the live object — it
    would read the NEXT statement's numbers. HostTaxRegistry.fold reads
    exactly these four attributes, so a snapshot substitutes."""

    __slots__ = ("e2e_s", "device_s", "unattributed_s", "phases")

    def __init__(self, led: GapLedger):
        self.e2e_s = led.e2e_s
        self.device_s = led.device_s
        self.unattributed_s = led.unattributed_s
        self.phases = dict(led.phases)

    @property
    def chip_idle_pct(self) -> float:
        if self.e2e_s <= 0.0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.device_s / self.e2e_s)) * 100.0


class HostTaxRegistry:
    """Bounded digest-keyed host-tax aggregate + per-window idle ring."""

    # The registry clock only stamps window buckets (durations come from
    # the folded ledgers), so it is WALL time: awr_report matches ring
    # entries against snapshot timestamps, which are time.time-domain.
    def __init__(self, max_digests: int = 256, window_s: float = 1.0,
                 window_capacity: int = 120,
                 clock: Callable[[], float] = time.time):
        self.enabled = True
        self.max_digests = max(8, int(max_digests))
        self.window_s = max(1e-3, float(window_s))
        self.window_capacity = max(8, int(window_capacity))
        self.clock = clock
        self._lock = threading.Lock()
        self._agg: Dict[int, dict] = {}
        self._evicted = 0
        # Closed per-window buckets: list of dicts (ts, stmts, e2e_s,
        # device_s, phases); _cur is the open bucket.
        self._win: List[dict] = []
        self._cur: Optional[dict] = None

    def _bucket(self, now: float) -> dict:
        key = int(now / self.window_s)
        cur = self._cur
        if cur is None or cur["key"] != key:
            if cur is not None:
                self._win.append(cur)
                if len(self._win) > self.window_capacity:
                    del self._win[:len(self._win) - self.window_capacity]
            cur = {"key": key, "ts": key * self.window_s, "stmts": 0,
                   "e2e_s": 0.0, "device_s": 0.0, "unattributed_s": 0.0,
                   "phases": {}}
            self._cur = cur
        return cur

    def fold(self, digest: int, led: GapLedger) -> None:
        if not self.enabled:
            return
        with self._lock:
            a = self._agg.get(digest)
            if a is None:
                if len(self._agg) >= self.max_digests:
                    self._evicted += 1
                    # Evict the smallest-wall digest: keep the heavy
                    # hitters that explain where the wall actually goes.
                    victim = min(self._agg, key=lambda d:
                                 self._agg[d]["e2e_s"])
                    del self._agg[victim]
                a = {"count": 0, "e2e_s": 0.0, "device_s": 0.0,
                     "unattributed_s": 0.0, "phases": {}}
                self._agg[digest] = a
            b = self._bucket(self.clock())
            a["count"] += 1
            b["stmts"] += 1
            a["e2e_s"] += led.e2e_s
            b["e2e_s"] += led.e2e_s
            a["device_s"] += led.device_s
            b["device_s"] += led.device_s
            a["unattributed_s"] += led.unattributed_s
            b["unattributed_s"] += led.unattributed_s
            ph, bp = a["phases"], b["phases"]
            for k, v in led.phases.items():
                ph[k] = ph.get(k, 0.0) + v
                bp[k] = bp.get(k, 0.0) + v

    def fold_extra(self, digest: int, phase: str, seconds: float) -> None:
        """Attribute post-close wall (e.g. wire write measured after the
        statement ledger closed) to a digest.  Adds to both the phase
        AND the digest e2e so digest-level conservation still holds."""
        if not self.enabled or seconds <= 0.0:
            return
        with self._lock:
            a = self._agg.get(digest)
            if a is None:
                return  # only annotate digests we already track
            a["e2e_s"] += seconds
            a["phases"][phase] = a["phases"].get(phase, 0.0) + seconds

    def snapshot(self) -> dict:
        """Cumulative per-digest totals + recent window ring.  Workload
        snapshots embed this; awr_report diffs two snapshots."""
        with self._lock:
            digests = {}
            for d, a in self._agg.items():
                digests[d] = {
                    "count": a["count"],
                    "e2e_s": a["e2e_s"],
                    "device_s": a["device_s"],
                    "unattributed_s": a["unattributed_s"],
                    "phases": dict(a["phases"]),
                }
            wins = [dict(w, phases=dict(w["phases"]))
                    for w in self._win[-16:]]
            cur = self._cur
            if cur is not None:
                wins.append(dict(cur, phases=dict(cur["phases"])))
            return {"digests": digests, "evicted": self._evicted,
                    "window_s": self.window_s, "windows": wins}

    def window_chip_idle_pct(self) -> float:
        """Chip idle over the most recent closed-or-open window."""
        with self._lock:
            w = self._cur if self._cur and self._cur["stmts"] else (
                self._win[-1] if self._win else None)
            if not w or w["e2e_s"] <= 0.0:
                return 0.0
            return max(0.0, min(1.0,
                                1.0 - w["device_s"] / w["e2e_s"])) * 100.0

    def rows(self) -> List[dict]:
        """Per-digest rows for __all_virtual_host_tax."""
        snap = self.snapshot()
        out = []
        for d, a in sorted(snap["digests"].items(),
                           key=lambda kv: -kv[1]["e2e_s"]):
            e2e = a["e2e_s"]
            idle = (max(0.0, min(1.0, 1.0 - a["device_s"] / e2e)) * 100.0
                    if e2e > 0 else 0.0)
            out.append({
                "digest": d,
                "count": a["count"],
                "e2e_s": e2e,
                "device_s": a["device_s"],
                "chip_idle_pct": idle,
                "unattributed_s": a["unattributed_s"],
                "unattributed_pct": (100.0 * a["unattributed_s"] / e2e
                                     if e2e > 0 else 0.0),
                "phases": a["phases"],
            })
        return out

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._win.clear()
            self._cur = None
            self._evicted = 0


class StackSampler:
    """Bounded in-process wall-clock stack sampler (sys._current_frames).

    Off by default: no thread exists until the first ``arm``.  Arming
    sets/extends a deadline; a daemon thread samples every thread's
    stack at ``interval_s`` until the deadline passes, then exits.  The
    serving layer auto-arms it when a statement crosses the slow-query
    watermark, so the *next* occurrence of a slow statement is caught
    with stacks in hand.  Collapsed stacks ("file:func;..." root-first)
    are counted in a bounded dict; overflow increments ``dropped``.
    """

    MAX_STACKS = 512
    MAX_DEPTH = 48

    def __init__(self, interval_s: float = 0.005,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = max(1e-4, float(interval_s))
        self.clock = clock
        self._lock = threading.Lock()
        self._deadline = 0.0
        self._continuous = False
        self._thread: Optional[threading.Thread] = None
        self._counts: Dict[str, int] = {}
        self._samples = 0
        self._dropped = 0

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._thread is not None and (
                self._continuous or self.clock() < self._deadline)

    def arm(self, duration_s: float) -> None:
        if duration_s <= 0.0:
            return
        with self._lock:
            self._deadline = max(self._deadline,
                                 self.clock() + duration_s)
            if self._thread is None:
                t = threading.Thread(target=self._run,
                                     name="gap-stack-sampler", daemon=True)
                self._thread = t
                t.start()

    def disarm(self) -> None:
        with self._lock:
            self._deadline = 0.0
            self._continuous = False

    def set_continuous(self, on: bool) -> None:
        """Config-armed mode (enable_stack_sampler=True): keep sampling
        until toggled off, independent of the auto-arm deadline."""
        with self._lock:
            self._continuous = bool(on)
            if on and self._thread is None:
                t = threading.Thread(target=self._run,
                                     name="gap-stack-sampler", daemon=True)
                self._thread = t
                t.start()

    def _run(self) -> None:
        me = threading.get_ident()
        while True:
            with self._lock:
                if not self._continuous and self.clock() >= self._deadline:
                    self._thread = None
                    return
            self._sample(me)
            time.sleep(self.interval_s)

    def _sample(self, skip_ident: int) -> None:
        try:
            frames = sys._current_frames()
        except Exception:
            return
        collapsed = []
        for ident, frame in frames.items():
            if ident == skip_ident:
                continue
            parts = []
            depth = 0
            f = frame
            while f is not None and depth < self.MAX_DEPTH:
                co = f.f_code
                parts.append("%s:%s" % (co.co_filename.rsplit("/", 1)[-1],
                                        co.co_name))
                f = f.f_back
                depth += 1
            if parts:
                parts.reverse()  # root-first, flamegraph convention
                collapsed.append(";".join(parts))
        del frames
        with self._lock:
            self._samples += len(collapsed)
            for st in collapsed:
                if st in self._counts:
                    self._counts[st] += 1
                elif len(self._counts) < self.MAX_STACKS:
                    self._counts[st] = 1
                else:
                    self._dropped += 1

    def collapsed_top(self, n: int = 25) -> List[Tuple[str, int]]:
        with self._lock:
            items = sorted(self._counts.items(), key=lambda kv: -kv[1])
        return items[:n]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "samples": self._samples,
                "dropped": self._dropped,
                "distinct": len(self._counts),
                "armed": self._thread is not None,
                "stacks": sorted(self._counts.items(),
                                 key=lambda kv: -kv[1])[:50],
            }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._samples = 0
            self._dropped = 0


def current_stack_collapsed(limit: int = 32) -> str:
    """Collapse the calling thread's own stack (diagnostics helper)."""
    parts = ["%s:%s" % (fr.filename.rsplit("/", 1)[-1], fr.name)
             for fr in traceback.extract_stack(limit=limit)]
    return ";".join(parts)
