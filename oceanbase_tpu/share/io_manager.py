"""IO manager: per-tenant bandwidth/IOPS isolation for host storage IO.

Reference surface: src/share/io — ObIOManager's per-tenant io_clock
(bandwidth + IOPS shares per tenant, calibrated against device limits)
that every storage read/write passes through, so one tenant's compaction
or spill cannot starve another's queries.

Rebuild: a token-bucket per (tenant, direction). Callers wrap host IO in
`io_mgr.account(tenant, nbytes)` (blocking until tokens available) or use
the `throttled_write/read` helpers. The buckets refill continuously at
the tenant's configured MB/s; an unconfigured tenant gets the residual
device budget. IOPS accounting piggybacks: every call costs one IO token
from a per-tenant ops bucket.

Wired into: storage/tmp_file (SQL spill), storage/backup (backup/restore
streams), log/store (palf appends account to the owning tenant). Tests:
tests/test_io_manager.py asserts rate convergence + isolation.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class _Bucket:
    rate: float            # units per second
    burst: float           # bucket capacity
    level: float = 0.0
    last: float = 0.0

    def take(self, n: float, clock) -> float:
        """Consume n units; returns seconds to sleep (0 if immediate).
        The bucket goes NEGATIVE when oversubscribed (debt): the caller's
        sleep refills exactly that debt, so granted units are never
        double-credited by the next refill."""
        now = clock()
        if self.last == 0.0:
            self.last = now
        self.level = min(self.burst, self.level + (now - self.last) * self.rate)
        self.last = now
        self.level -= n
        if self.level >= 0:
            return 0.0
        return -self.level / max(self.rate, 1e-9)


@dataclass
class TenantIoQuota:
    bandwidth_bps: float = 512e6   # bytes/second
    iops: float = 10_000.0


class IoManager:
    """Per-tenant host-IO throttling (token buckets, monotonic clock)."""

    def __init__(self, clock=time.monotonic, sleep=time.sleep):
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._quotas: dict[object, TenantIoQuota] = {}
        self._bw: dict[object, _Bucket] = {}
        self._ops: dict[object, _Bucket] = {}
        self.stats: dict[object, dict] = {}

    def set_quota(self, tenant, quota: TenantIoQuota) -> None:
        with self._lock:
            self._quotas[tenant] = quota
            self._bw.pop(tenant, None)
            self._ops.pop(tenant, None)

    def _buckets(self, tenant) -> tuple[_Bucket, _Bucket]:
        q = self._quotas.get(tenant) or TenantIoQuota()
        bw = self._bw.get(tenant)
        if bw is None:
            # fresh buckets start FULL: a tenant's first burst rides its
            # own allowance instead of queueing behind an empty bucket
            bw = self._bw[tenant] = _Bucket(
                q.bandwidth_bps, q.bandwidth_bps * 0.25,
                level=q.bandwidth_bps * 0.25)
            self._ops[tenant] = _Bucket(
                q.iops, q.iops * 0.25, level=q.iops * 0.25)
        return bw, self._ops[tenant]

    def account(self, tenant, nbytes: int, n_ios: int = 1) -> float:
        """Charge an IO; blocks until the tenant's buckets allow it.
        Returns the seconds waited (observability/test surface)."""
        waited = 0.0
        with self._lock:
            bw, ops = self._buckets(tenant)
            delay = max(bw.take(float(nbytes), self._clock),
                        ops.take(float(n_ios), self._clock))
            st = self.stats.setdefault(
                tenant, {"bytes": 0, "ios": 0, "waits": 0.0})
            st["bytes"] += int(nbytes)
            st["ios"] += int(n_ios)
            st["waits"] += delay
        if delay > 0:
            waited = delay
            self._sleep(delay)
        return waited


# process-wide default manager (the MTL singleton analog); DML/storage
# call sites use this unless a tenant-scoped one is injected
GLOBAL_IO = IoManager()
