"""TLS contexts for the cluster bus and the MySQL front door.

Reference surface: deps/ussl-hook — the reference intercepts cluster
sockets and upgrades them to OpenSSL with cluster certificates; intra-
cluster auth there is certificate-based with optional mTLS. The rebuild
keeps the same trust model on Python's ssl module:

- the CLUSTER bus (log/tcp_transport.TcpBus) uses MUTUAL TLS: both sides
  present the cluster certificate and verify against the cluster CA, so
  a network position alone cannot join the replication plane (the HELLO
  token then authenticates at the frame layer — defense in depth, and
  no longer replayable off the wire);
- the MySQL front door uses standard server-side TLS negotiated via the
  protocol's CLIENT_SSL capability + SSLRequest packet.

Hostname checks are disabled by design: cluster certs identify the
CLUSTER (one cert, many nodes), not individual hosts — exactly the
reference's deployment shape.
"""

from __future__ import annotations

import ssl


def server_context(certfile: str, keyfile: str,
                   cafile: str | None = None) -> ssl.SSLContext:
    """Server-side context; with `cafile`, peers MUST present a cert
    signed by it (mutual TLS — the cluster-bus mode)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    if cafile:
        ctx.load_verify_locations(cafile)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(cafile: str, certfile: str | None = None,
                   keyfile: str | None = None) -> ssl.SSLContext:
    """Client-side context verifying the server against the cluster CA;
    pass certfile/keyfile for mutual TLS (cluster-bus mode)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(cafile)
    ctx.check_hostname = False  # cluster certs, not per-host certs
    ctx.verify_mode = ssl.CERT_REQUIRED
    if certfile:
        ctx.load_cert_chain(certfile, keyfile)
    return ctx
