"""Statement-level retry control: error taxonomy, policies, deadlines.

Reference surface: ObQueryRetryCtrl (observer/ob_query_retry_ctrl.h) — every
error a statement can surface is classified into a retry policy before the
session gives up; retryable classes re-drive the statement (refreshing the
location cache, re-electing routing, flushing stale plans) with backoff until
the statement deadline (ob_query_timeout / ob_trx_timeout) expires, at which
point the statement fails with a *timeout* error, never the raw transient.

The rebuild keeps the same three pieces:

- ``classify(err)``        -> RetryPolicy       (the taxonomy)
- ``Deadline``             -> ob_query_timeout on the bus virtual clock
- ``RetryController``      -> per-statement attempt/backoff bookkeeping

All waits are in *virtual* seconds: the session retry loop burns them via
``cluster.settle`` so palf elections progress during the backoff, exactly
like the reference's retry sleep overlapping with location cache refresh.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# ------------------------------------------------------------------ errors


class StatementTimeout(Exception):
    """Base for deadline expiries; never retried."""


class QueryTimeout(StatementTimeout):
    """ob_query_timeout expired (OB_TIMEOUT analog)."""


class TrxTimeout(StatementTimeout):
    """ob_trx_timeout expired (OB_TRANS_TIMEOUT analog)."""


class StaleLocation(Exception):
    """Location cache kept pointing at a non-ready replica; the leader for
    the log stream could not be resolved locally (OB_LS_LOCATION_NOT_EXIST
    analog). Retryable after a cache refresh once the election settles."""


class PxAdmissionTimeout(Exception):
    """PX admission queue wait exceeded its bound (OB_ERR_SCHEDULER_THREAD_
    NOT_ENOUGH analog). Retryable: quota frees up as peers finish."""


class SchemaVersionMismatch(Exception):
    """A cached plan was compiled against a schema version that changed
    under the statement (OB_SCHEMA_EAGAIN analog). Retry immediately after
    flushing the plan cache."""


class CommitUnknown(Exception):
    """palf commit-wait timed out: the commit outcome is *unknown* (the log
    may still replicate later), so the statement must not be blindly
    re-driven. Non-retryable, surfaced as a timeout class."""


class DeviceOOM(Exception):
    """Device memory exhausted mid-statement (XlaRuntimeError:
    RESOURCE_EXHAUSTED, or its errsim twin EN_DEVICE_OOM on CPU chaos
    runs). Retryable through the degradation ladder: evict + shrink,
    re-plan chunked, finally execute on host — never surfaced raw."""


class DeviceMemoryTimeout(Exception):
    """Device-memory reservation wait exceeded its bound (the governor
    queue stayed full). Retryable: reservations free up as peers
    finish, exactly like PX admission quota."""


# ---------------------------------------------------------------- policies

#: policy kinds (mirrors ObQueryRetryCtrl's retry_type)
NONE = "none"            # not retryable: surface the error
IMMEDIATE = "immediate"  # retry at once (schema mismatch, plan flush)
BACKOFF = "backoff"      # linear backoff on the virtual clock until deadline
CAPPED = "capped"        # backoff, but give up after max_retries attempts


@dataclass(frozen=True)
class RetryPolicy:
    kind: str = NONE
    reason: str = "non-retryable"
    #: linear backoff base, virtual seconds; attempt N waits base * N
    base_wait: float = 0.0
    #: cap a single backoff wait
    max_wait: float = 2.0
    #: None = bounded only by the deadline
    max_retries: Optional[int] = None
    #: invalidate + re-resolve the location cache between attempts
    refresh_location: bool = False
    #: drop cached plans before the next attempt
    flush_plan_cache: bool = False

    @property
    def retryable(self) -> bool:
        return self.kind != NONE


NOT_RETRYABLE = RetryPolicy()

#: NotMaster / stale location: the replica we routed to is not (or no longer)
#: the ready leader. Refresh the cache and back off so the election settles.
LOCATION_REFRESH = RetryPolicy(
    kind=BACKOFF, reason="not master, location refresh",
    base_wait=0.05, max_wait=1.0, refresh_location=True,
)

STALE_LOCATION = RetryPolicy(
    kind=BACKOFF, reason="stale location cache",
    base_wait=0.05, max_wait=1.0, refresh_location=True,
)

#: Injected transient faults (errsim): short backoff, bounded attempts so a
#: permanently armed point (prob=1, count=-1) cannot spin until the deadline.
INJECTED_TRANSIENT = RetryPolicy(
    kind=CAPPED, reason="injected transient error",
    base_wait=0.02, max_wait=0.5, max_retries=16,
)

PX_ADMISSION = RetryPolicy(
    kind=CAPPED, reason="px admission timeout",
    base_wait=0.05, max_wait=1.0, max_retries=4,
)

SCHEMA_EAGAIN = RetryPolicy(
    kind=IMMEDIATE, reason="schema version mismatch",
    flush_plan_cache=True, max_retries=8,
)

WRITE_CONFLICT = RetryPolicy(
    kind=BACKOFF, reason="write-write conflict",
    base_wait=0.02, max_wait=0.5,
)

#: Device OOM: exactly three attempts — one per rung of the degradation
#: ladder (evict + shrink pool, re-plan chunked, host fallback). The
#: host rung cannot OOM, so a fourth attempt would mean a logic bug.
DEVICE_OOM = RetryPolicy(
    kind=CAPPED, reason="device oom",
    base_wait=0.02, max_wait=0.5, max_retries=3,
)

DEVICE_MEMORY = RetryPolicy(
    kind=CAPPED, reason="device memory reservation timeout",
    base_wait=0.05, max_wait=1.0, max_retries=4,
)

#: Corrupt transient storage mid-statement (a spill segment failed its
#: checksum): the bad file is already deleted by the reader, so a
#: re-drive recomputes it from the base tables. Bounded — corruption
#: of DURABLE state (checkpoint, sstable) surfaces through recovery or
#: the scrubber instead, never a statement retry loop.
STORAGE_CORRUPT = RetryPolicy(
    kind=CAPPED, reason="storage corruption recompute",
    base_wait=0.0, max_wait=0.1, max_retries=3,
)


def _is_xla_oom(err: BaseException) -> bool:
    """Recognize a real XLA RESOURCE_EXHAUSTED without importing jax
    (share/ must stay importable on bare interpreters)."""
    return ("XlaRuntimeError" in type(err).__name__
            and "RESOURCE_EXHAUSTED" in str(err))


def classify(err: BaseException) -> RetryPolicy:
    """Map an engine failure onto its retry policy.

    Import targets lazily: share/ must stay importable without tx/ or
    server/ loaded (tx imports share.errsim; server imports share.*)."""
    from oceanbase_tpu.share.errsim import InjectedError
    from oceanbase_tpu.share.interrupt import QueryInterrupted

    if isinstance(err, (StatementTimeout, QueryInterrupted, CommitUnknown)):
        return NOT_RETRYABLE
    if isinstance(err, StaleLocation):
        return STALE_LOCATION
    if isinstance(err, PxAdmissionTimeout):
        return PX_ADMISSION
    if isinstance(err, SchemaVersionMismatch):
        return SCHEMA_EAGAIN
    if isinstance(err, DeviceOOM) or _is_xla_oom(err):
        return DEVICE_OOM
    if isinstance(err, DeviceMemoryTimeout):
        return DEVICE_MEMORY
    if isinstance(err, InjectedError):
        return INJECTED_TRANSIENT
    try:
        from oceanbase_tpu.storage.integrity import CorruptBlock
    except Exception:  # pragma: no cover - storage layer absent
        pass
    else:
        if isinstance(err, CorruptBlock):
            return STORAGE_CORRUPT
    try:
        from oceanbase_tpu.tx.txn import NotMaster, WriteConflict
    except Exception:  # pragma: no cover - tx layer absent in unit slices
        return NOT_RETRYABLE
    if isinstance(err, NotMaster):
        return LOCATION_REFRESH
    if isinstance(err, WriteConflict):
        return WRITE_CONFLICT
    return NOT_RETRYABLE


# ---------------------------------------------------------------- deadline


@dataclass(slots=True)
class Deadline:
    """An absolute point on the bus virtual clock.

    One Deadline object travels with the statement (thread-local, see
    ``deadline_scope``) so plan compile, PX admission, worker waits, DAS
    routing and palf commit waits all bound themselves by the same clock."""

    clock: Callable[[], float]
    at: float
    label: str = "ob_query_timeout"

    @classmethod
    def after(cls, clock: Callable[[], float], timeout_s: float,
              label: str = "ob_query_timeout") -> "Deadline":
        return cls(clock=clock, at=clock() + timeout_s, label=label)

    def remaining(self) -> float:
        return self.at - self.clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def _error(self) -> StatementTimeout:
        exc = TrxTimeout if self.label == "ob_trx_timeout" else QueryTimeout
        return exc(f"{self.label} expired (deadline {self.at:.3f}s on the "
                   f"virtual clock)")

    def check(self) -> None:
        if self.expired:
            raise self._error()

    def bound(self, timeout_s: Optional[float]) -> float:
        """Clamp a private timeout by the statement deadline. Expired
        deadlines raise rather than returning a non-positive wait."""
        self.check()
        rem = self.remaining()
        if timeout_s is None:
            return rem
        return min(timeout_s, rem)

    def tighter_than(self, timeout_s: Optional[float]) -> bool:
        return timeout_s is None or self.remaining() < timeout_s

    @staticmethod
    def earliest(*deadlines: Optional["Deadline"]) -> Optional["Deadline"]:
        live = [d for d in deadlines if d is not None]
        if not live:
            return None
        return min(live, key=lambda d: d.at)


_tls = threading.local()


def current_deadline() -> Optional[Deadline]:
    return getattr(_tls, "deadline", None)


def set_current_deadline(d: Optional[Deadline]) -> None:
    _tls.deadline = d


@contextmanager
def deadline_scope(d: Optional[Deadline]):
    prev = current_deadline()
    set_current_deadline(d)
    try:
        yield d
    finally:
        set_current_deadline(prev)


def checkpoint_deadline() -> None:
    """Called from share.interrupt.checkpoint(): unwind an expired statement
    at the next cooperative checkpoint, like ObInterruptChecker polling the
    worker's retire timestamp."""
    d = current_deadline()
    if d is not None:
        d.check()


# -------------------------------------------------------------- controller


@dataclass
class Attempt:
    reason: str
    wait_s: float
    error: str


@dataclass(slots=True)
class RetryController:
    """Per-statement retry bookkeeping (ObQueryRetryCtrl's retry_cnt /
    retry_info). The session loop owns location refresh and the actual
    backoff sleep (it must drive the cluster, which share/ cannot see)."""

    deadline: Optional[Deadline] = None
    retry_cnt: int = 0
    attempts: list = field(default_factory=list)
    _per_policy: dict = field(default_factory=dict)

    def decide(self, err: BaseException,
               stmt_retryable: bool = True) -> Optional[RetryPolicy]:
        """Return the policy to apply, or None if the statement must fail.

        ``stmt_retryable`` is False for statements whose side effects are
        not replayable (DML inside an explicit transaction: the tx already
        staged partial writes; OB likewise only retries at statement level
        when the whole statement can be re-driven)."""
        policy = classify(err)
        if not policy.retryable:
            return None
        if not stmt_retryable and policy.kind != IMMEDIATE:
            return None
        if self.retry_cnt >= 256:  # belt: no unbounded redrive, ever
            return None
        n = self._per_policy.get(policy.reason, 0)
        if policy.max_retries is not None and n >= policy.max_retries:
            return None
        return policy

    def record(self, policy: RetryPolicy, err: BaseException) -> float:
        """Account one retry; returns the backoff wait in virtual seconds."""
        n = self._per_policy.get(policy.reason, 0) + 1
        self._per_policy[policy.reason] = n
        self.retry_cnt += 1
        wait = min(policy.base_wait * n, policy.max_wait)
        if self.deadline is not None:
            wait = min(wait, max(self.deadline.remaining(), 0.0))
        self.attempts.append(Attempt(policy.reason, wait,
                                     f"{type(err).__name__}: {err}"))
        return wait

    @property
    def retry_info(self) -> str:
        """Compact audit string: 'reason x count; ...' (retry_info column)."""
        seen: dict[str, int] = {}
        for a in self.attempts:
            seen[a.reason] = seen.get(a.reason, 0) + 1
        return "; ".join(f"{r} x{c}" for r, c in seen.items())

    def timeout_error(self, last: BaseException) -> StatementTimeout:
        """Deadline expired while retrying: surface a timeout chaining the
        last transient, never the raw NotMaster/InjectedError."""
        assert self.deadline is not None
        err = self.deadline._error()
        err.__cause__ = last
        return err


__all__ = [
    "StatementTimeout", "QueryTimeout", "TrxTimeout", "StaleLocation",
    "PxAdmissionTimeout", "SchemaVersionMismatch", "CommitUnknown",
    "DeviceOOM", "DeviceMemoryTimeout",
    "RetryPolicy", "classify", "Deadline", "RetryController",
    "current_deadline", "set_current_deadline", "deadline_scope",
    "checkpoint_deadline",
    "NONE", "IMMEDIATE", "BACKOFF", "CAPPED",
    "NOT_RETRYABLE", "LOCATION_REFRESH", "STALE_LOCATION",
    "INJECTED_TRANSIENT", "PX_ADMISSION", "SCHEMA_EAGAIN", "WRITE_CONFLICT",
    "DEVICE_OOM", "DEVICE_MEMORY", "STORAGE_CORRUPT",
]
