"""LS leader location cache.

Reference surface: ObLocationService (share/location_cache/
ob_location_service.h:34) — a cache of LS/tablet -> server mappings,
refreshed by RPC on miss or on NOT_MASTER feedback, so statement routing
never blocks on consensus state.

The rebuild caches ls_id -> leader node with a TTL; `resolve` refreshes
through a pluggable resolver (LocalCluster.leader_node in-process; a real
RPC in multi-process deployments). NotMaster feedback calls `invalidate`.
"""

from __future__ import annotations

import threading
import time


class LocationService:
    def __init__(self, resolver, ttl: float = 10.0, clock=time.monotonic):
        self._resolver = resolver  # ls_id -> node (may block on election)
        self._ttl = ttl
        self._clock = clock
        self._cache: dict[int, tuple[int, float]] = {}
        self._lock = threading.RLock()
        self.refreshes = 0

    def leader(self, ls_id: int) -> int:
        now = self._clock()
        with self._lock:
            hit = self._cache.get(ls_id)
            if hit is not None and hit[1] > now:
                return hit[0]
        node = self._resolver(ls_id)
        with self._lock:
            self.refreshes += 1
            self._cache[ls_id] = (node, self._clock() + self._ttl)
        return node

    def invalidate(self, ls_id: int) -> None:
        """Drop a mapping (NOT_MASTER feedback / peer death)."""
        with self._lock:
            self._cache.pop(ls_id, None)

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()
