"""Tenant DAG scheduler: prioritized background task graphs.

Reference surface: ObTenantDagScheduler (share/scheduler/
ob_tenant_dag_scheduler.h:1179) — compaction/DDL/backup work is expressed
as DAGs of tasks; the scheduler runs them on bounded worker pools ordered
by priority, records failures in a warning history
(share/scheduler/ob_dag_warning_history_mgr.h), and exposes progress.

The rebuild keeps the same model: a Dag owns tasks with dependencies; the
scheduler pops READY tasks from the highest-priority non-empty queue.
`run_until_idle()` drains everything on the calling thread (deterministic
for tests and single-process deployments); `start(n)` runs a thread pool
for live servers.
"""

from __future__ import annotations

import enum
import itertools
import threading
import traceback
from collections import deque
from dataclasses import dataclass, field


class DagPriority(enum.IntEnum):
    """Lower value = more urgent (matches the reference's prio ordering:
    urgent system dags, then mini, minor, major, background)."""

    URGENT = 0
    MINI_MERGE = 1
    MINOR_MERGE = 2
    MAJOR_MERGE = 3
    BACKGROUND = 4


@dataclass
class DagTask:
    fn: object  # callable() -> None
    name: str = ""
    deps: list["DagTask"] = field(default_factory=list)
    done: bool = False
    error: str = ""

    @property
    def ready(self) -> bool:
        return not self.done and all(d.done for d in self.deps)


@dataclass
class Dag:
    dag_type: str
    priority: DagPriority
    key: tuple = ()  # dedup identity (e.g. (tablet_id, "mini"))
    tasks: list[DagTask] = field(default_factory=list)
    dag_id: int = 0
    failed: bool = False
    # full-link tracing: (trace_id, parent_span_id) of the statement that
    # queued this dag — captured at add_dag so each task's span lands in
    # the initiating statement's trace tree even though it runs later
    trace_ctx: tuple | None = None
    # progress row in the tenant LongOps registry (set by the scheduler)
    long_op: object = None

    def add_task(self, fn, name: str = "", deps: list[DagTask] | None = None) -> DagTask:
        t = DagTask(fn, name or f"task{len(self.tasks)}", list(deps or []))
        self.tasks.append(t)
        return t

    @property
    def finished(self) -> bool:
        return self.failed or all(t.done for t in self.tasks)


@dataclass
class DagWarning:
    dag_type: str
    key: tuple
    task: str
    error: str


class TenantDagScheduler:
    def __init__(self, warning_capacity: int = 512, tracer=None, long_ops=None):
        # observability hooks (server/diag.Tracer + LongOps): when wired,
        # every task runs under a "dag task" span in the queueing
        # statement's trace, and each dag gets a __all_virtual_long_ops row
        self.tracer = tracer
        self.long_ops = long_ops
        self._queues: dict[DagPriority, deque[Dag]] = {
            p: deque() for p in DagPriority
        }
        self._keys: set[tuple] = set()
        self._ids = itertools.count(1)
        self._lock = threading.RLock()
        self.warnings: deque[DagWarning] = deque(maxlen=warning_capacity)
        self.scheduled = 0
        self.completed = 0
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._work = threading.Condition(self._lock)

    # ------------------------------------------------------------- submit
    def add_dag(self, dag: Dag) -> bool:
        """Queue a dag; duplicate keys are rejected (the reference dedups
        merge dags per tablet so one tablet never compacts twice at once)."""
        with self._lock:
            if dag.key and dag.key in self._keys:
                return False
            dag.dag_id = next(self._ids)
            if dag.key:
                self._keys.add(dag.key)
            if dag.trace_ctx is None and self.tracer is not None:
                dag.trace_ctx = self.tracer.current_ctx()
            if self.long_ops is not None and dag.long_op is None:
                dag.long_op = self.long_ops.start(
                    dag.dag_type, target=str(dag.key) if dag.key else "",
                    total=len(dag.tasks),
                    trace_id=dag.trace_ctx[0] if dag.trace_ctx else 0,
                )
            self._queues[dag.priority].append(dag)
            self.scheduled += 1
            self._work.notify_all()
            return True

    # ------------------------------------------------------------ running
    def _next_task(self):
        """Highest-priority dag with a ready task."""
        for p in DagPriority:
            q = self._queues[p]
            for dag in list(q):
                if dag.failed or dag.finished:
                    continue
                for t in dag.tasks:
                    if t.ready and not getattr(t, "_claimed", False):
                        t._claimed = True
                        return dag, t
        return None

    def _finish_dag(self, dag: Dag):
        self._queues[dag.priority].remove(dag)
        self._keys.discard(dag.key)
        self.completed += 1
        if self.long_ops is not None and dag.long_op is not None:
            self.long_ops.finish(dag.long_op, ok=not dag.failed)

    def _run_one(self) -> bool:
        with self._lock:
            nxt = self._next_task()
            if nxt is None:
                # sweep finished/failed dags
                for p in DagPriority:
                    for dag in [d for d in self._queues[p] if d.finished]:
                        self._finish_dag(dag)
                return False
            dag, task = nxt
        try:
            if self.tracer is not None:
                with self.tracer.span(
                    "dag task", ctx=dag.trace_ctx,
                    dag_type=dag.dag_type, task=task.name, dag_id=dag.dag_id,
                ):
                    task.fn()
            else:
                task.fn()
            task.done = True
            if self.long_ops is not None and dag.long_op is not None:
                self.long_ops.update(
                    dag.long_op,
                    done=sum(1 for t in dag.tasks if t.done),
                    message=task.name,
                )
        except Exception as e:  # noqa: BLE001 - background task boundary
            task.error = f"{type(e).__name__}: {e}"
            with self._lock:
                dag.failed = True
                self.warnings.append(
                    DagWarning(dag.dag_type, dag.key, task.name, task.error)
                )
                traceback.clear_frames(e.__traceback__)
        with self._lock:
            if dag.finished:
                if dag in self._queues[dag.priority]:
                    self._finish_dag(dag)
        return True

    def run_until_idle(self, max_tasks: int = 100000) -> int:
        """Drain all runnable work on the calling thread (test/deterministic
        mode). Returns tasks executed."""
        n = 0
        while n < max_tasks and self._run_one():
            n += 1
        return n

    # ------------------------------------------------------ thread pool
    def start(self, n_workers: int = 2) -> None:
        def worker():
            while not self._stop.is_set():
                if not self._run_one():
                    with self._work:
                        self._work.wait(timeout=0.05)

        with self._lock:
            if self._threads:
                return
            for i in range(n_workers):
                t = threading.Thread(target=worker, daemon=True,
                                     name=f"dag-worker-{i}")
                t.start()
                self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._work:
            self._work.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self._stop.clear()

    @property
    def pending(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())
