"""KV cache: memory-budgeted LRU for decoded storage blocks.

Reference surface: ObKVGlobalCache (share/cache) — a tenant-aware cache
framework whose main users are the block cache (decoded micro blocks) and
row cache; eviction is by memory watermark.

The rebuild caches decoded column arrays keyed by (sstable uid, block,
column). Byte-accounted LRU; hit/miss stats surface through virtual
tables. One instance per Database (= tenant); storage readers take the
cache as an optional collaborator so unit tests can run cacheless.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np


class KVCache:
    def __init__(self, capacity_bytes: int = 256 << 20):
        self.capacity_bytes = capacity_bytes
        self._map: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # optional residency hook (key -> priority, higher = keep
        # longer): eviction scans a bounded LRU-ordered window and takes
        # the coldest entry of the lowest priority tier, so the layout
        # advisor's hot tables survive memory pressure
        self.priority_of = None

    # bounded so a priority-heavy cache can't turn eviction into a full
    # scan; within the window the LRU-most entry of the lowest tier goes
    _EVICT_SCAN = 32

    def _evict_one(self) -> None:
        if self.priority_of is None:
            _, ev = self._map.popitem(last=False)
        else:
            best_k = best_p = None
            for i, k in enumerate(self._map):
                if i >= self._EVICT_SCAN:
                    break
                try:
                    p = float(self.priority_of(k))
                except Exception:  # noqa: BLE001 - advisory hook
                    p = 0.0
                if best_p is None or p < best_p:
                    best_k, best_p = k, p
                if p <= 0.0:
                    break  # default tier: nothing beats evicting it
            ev = self._map.pop(best_k)
        self._bytes -= int(ev.nbytes)
        self.evictions += 1

    def get(self, key: tuple):
        with self._lock:
            v = self._map.get(key)
            if v is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key: tuple, value: np.ndarray) -> None:
        nbytes = int(value.nbytes)
        if nbytes > self.capacity_bytes:
            return  # larger than the whole budget: bypass
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= int(old.nbytes)
            self._map[key] = value
            self._bytes += nbytes
            while self._bytes > self.capacity_bytes and self._map:
                self._evict_one()

    def set_capacity(self, capacity_bytes: int) -> None:
        with self._lock:
            self.capacity_bytes = capacity_bytes
            while self._bytes > self.capacity_bytes and self._map:
                self._evict_one()

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._map)
