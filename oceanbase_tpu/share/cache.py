"""KV cache: memory-budgeted LRU for decoded storage blocks.

Reference surface: ObKVGlobalCache (share/cache) — a tenant-aware cache
framework whose main users are the block cache (decoded micro blocks) and
row cache; eviction is by memory watermark.

The rebuild caches decoded column arrays keyed by (sstable uid, block,
column). Byte-accounted LRU; hit/miss stats surface through virtual
tables. One instance per Database (= tenant); storage readers take the
cache as an optional collaborator so unit tests can run cacheless.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np


class KVCache:
    def __init__(self, capacity_bytes: int = 256 << 20):
        self.capacity_bytes = capacity_bytes
        self._map: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple):
        with self._lock:
            v = self._map.get(key)
            if v is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key: tuple, value: np.ndarray) -> None:
        nbytes = int(value.nbytes)
        if nbytes > self.capacity_bytes:
            return  # larger than the whole budget: bypass
        with self._lock:
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= int(old.nbytes)
            self._map[key] = value
            self._bytes += nbytes
            while self._bytes > self.capacity_bytes and self._map:
                _, ev = self._map.popitem(last=False)
                self._bytes -= int(ev.nbytes)
                self.evictions += 1

    def set_capacity(self, capacity_bytes: int) -> None:
        with self._lock:
            self.capacity_bytes = capacity_bytes
            while self._bytes > self.capacity_bytes and self._map:
                _, ev = self._map.popitem(last=False)
                self._bytes -= int(ev.nbytes)
                self.evictions += 1

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._map)
