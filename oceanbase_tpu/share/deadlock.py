"""Distributed deadlock detection: edge-chasing probes over the bus.

Reference surface: share/deadlock — OceanBase's LCL (lock-chain-length)
distributed detector, which propagates labels along wait-for edges between
nodes and deterministically kills one participant of any cycle.

Rebuild: the Chandy-Misra-Haas edge-chasing form of the same idea. Every
node runs a DeadlockService next to its LockManager:

  * locally, each waiting tx periodically originates a LockProbe for every
    tx it waits on;
  * a node that hosts the chased tx's wait state forwards the probe along
    that tx's own wait edges (local chains collapse in one step because
    wait_for() already walks local edges transitively);
  * a probe arriving back at a tx that IS its initiator proves a cycle;
    the node hosting the LARGEST tx id in the closing edge aborts it
    (youngest-victim policy — deterministic cluster-wide because every
    waiter originates probes, so the max-id member of the cycle is always
    chased by someone).

The victim is aborted by marking it in its LockManager; the blocked
session's next lock() retry raises DeadlockDetected, exactly like a
locally-detected cycle.

Probes ride the typed wire codec (log/wire.py tag 8) between the
bus endpoints DEADLOCK_EP + node_id; they are idle-cheap (no probes
without waiters) and cycles are found within ~2 probe periods.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

# bus endpoint namespace offset (palf replicas use the raw node ids)
DEADLOCK_EP = 1_000_000


@dataclass(frozen=True)
class LockProbe:
    initiator: int  # tx id whose wait started the chase
    holder: int     # tx id being chased
    max_seen: int   # largest tx id on the chase path (victim arbitration)
    hops: int
    init_token: int = 0  # initiator's wait-instance token at origination


@dataclass(frozen=True)
class ConfirmRequest:
    """Cycle closed at `victim`'s host; asks the initiator's host to
    verify the originating wait still exists (same token) before the
    abort — the CMH phantom-cycle guard: a wait edge released mid-chase
    must not let a stale probe kill a live transaction."""

    initiator: int
    victim: int
    init_token: int
    victim_node: int


@dataclass(frozen=True)
class AbortGrant:
    initiator: int
    victim: int


class DeadlockService:
    """One node's detector. `peers` lists the OTHER node ids; the bus
    routes DEADLOCK_EP + node endpoints."""

    def __init__(self, node_id: int, bus, lock_mgr, peers,
                 period: float = 0.05, max_hops: int = 32):
        self.node_id = node_id
        self.bus = bus
        self.lock_mgr = lock_mgr
        self.peers = [p for p in peers if p != node_id]
        self.period = period
        self.max_hops = max_hops
        self.cycles_found = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        bus.register(DEADLOCK_EP + node_id, self._on_message)

    # ------------------------------------------------------------ probes
    def _broadcast(self, probe: LockProbe) -> None:
        for p in self.peers:
            self.bus.send(
                DEADLOCK_EP + self.node_id, DEADLOCK_EP + p, probe
            )

    def _chase(self, initiator: int, holder: int, max_seen: int,
               hops: int, init_token: int) -> None:
        """Follow `holder`'s local wait edges; close the cycle or forward.

        max_seen accumulates the largest tx id along the chase; a probe
        that closes a cycle aborts its holder ONLY when the holder is
        that maximum — so among the N probes circulating one N-cycle,
        exactly the one whose path ends at the max-id member kills it
        (one victim per cycle, the youngest-tx policy).

        Phantom-cycle guard: the closing edge is freshly read here, but
        the ORIGINATING wait may have dissolved mid-chase (lock granted,
        tx re-blocked elsewhere) — so the abort only fires after the
        initiator's host confirms the same wait instance (init_token)
        still stands. Local initiators check inline; remote ones go
        through a ConfirmRequest/AbortGrant round-trip."""
        if hops > self.max_hops:
            return
        max_seen = max(max_seen, holder)
        edges = self.lock_mgr.wait_edges_of(holder)
        for t in edges:
            if t == initiator:
                # cycle: the closing edge is holder -> initiator
                self.cycles_found += 1
                if holder >= max_seen:
                    self._confirm_then_abort(initiator, holder, init_token)
                continue
            if self.lock_mgr.hosts_wait(t):
                self._chase(initiator, t, max_seen, hops + 1, init_token)
            else:
                self._broadcast(
                    LockProbe(initiator, t, max_seen, hops + 1, init_token))

    def _confirm_then_abort(self, initiator: int, victim: int,
                            init_token: int) -> None:
        if self.lock_mgr.hosts_wait(initiator):
            if self.lock_mgr.wait_token(initiator) == init_token:
                self.lock_mgr.abort(victim)
            return
        self._broadcast(ConfirmRequest(
            initiator, victim, init_token, self.node_id))

    def _on_message(self, src: int, msg) -> None:
        if isinstance(msg, LockProbe) and self.lock_mgr.hosts_wait(msg.holder):
            self._chase(msg.initiator, msg.holder, msg.max_seen, msg.hops,
                        msg.init_token)
        elif isinstance(msg, ConfirmRequest):
            if (self.lock_mgr.hosts_wait(msg.initiator)
                    and self.lock_mgr.wait_token(msg.initiator)
                    == msg.init_token):
                self.bus.send(
                    DEADLOCK_EP + self.node_id,
                    DEADLOCK_EP + msg.victim_node,
                    AbortGrant(msg.initiator, msg.victim),
                )
        elif isinstance(msg, AbortGrant):
            # revalidate the closing edge before the kill: the victim
            # must still be waiting on the initiator
            if msg.initiator in self.lock_mgr.wait_edges_of(msg.victim):
                self.lock_mgr.abort(msg.victim)

    # ----------------------------------------------------------- driving
    def scan_once(self) -> None:
        """Originate probes for every local waiter (one detection round)."""
        for tx, holders in self.lock_mgr.waiting_snapshot().items():
            tok = self.lock_mgr.wait_token(tx)
            if tok is None:
                continue  # wait dissolved between snapshot and here
            for h in holders:
                if self.lock_mgr.hosts_wait(h):
                    self._chase(tx, h, tx, 1, tok)
                else:
                    self._broadcast(LockProbe(tx, h, tx, 1, tok))

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.period):
                self.scan_once()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
