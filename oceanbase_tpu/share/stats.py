"""Optimizer statistics: per-column NDV / min-max / equi-height histograms.

Reference surface: src/share/stat (dbms_stats collection, ObOptColumnStat
histograms, NDV) feeding the cost-based optimizer's selectivity and join
ordering (src/sql/optimizer/ob_join_order.h, ob_opt_selectivity.cpp). The
reference collects via full/sampled table scans into __all_*_stat inner
tables; the rebuild collects directly from catalog snapshot Tables (whose
columns are already dense numpy arrays — a "scan" is vectorized numpy) and
caches per snapshot object.

Everything is computed in the STORAGE domain (decimals as scaled ints,
dates as day numbers, VARCHAR as sorted-dictionary codes). Sorted dict
codes order like their strings, so range selectivity on codes is string
range selectivity — the global-dictionary dividend the engine design
already pays for.

Estimation entry points:
  * `TableStats.selectivity(expr, table)` — fraction of rows satisfying a
    pushed-filter conjunct tree (Compare/Between/InList/IsNull/BoolOp/Not).
  * `ColumnStats.eq_frac` / `range_frac` — primitives (histogram based).
  * `StatsManager` — per-catalog cache keyed on snapshot identity.

Unknown expression shapes fall back to the classic constants (eq 1/ndv,
range 1/4, unknown 1/4) so estimates degrade, never fail.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

N_BUCKETS = 64
SAMPLE_CAP = 1 << 16

_DEFAULT_SEL = 0.25


@dataclass
class ColumnStats:
    ndv: float
    vmin: float
    vmax: float
    null_frac: float
    # equi-height histogram: N_BUCKETS+1 edges over the non-null values
    # (edges[i] = quantile i/N). None for empty columns.
    edges: np.ndarray | None = None

    # ---- primitives --------------------------------------------------
    def _eq_nonnull(self, v: float) -> float:
        """P(col = v | col not null)."""
        if self.ndv <= 0 or v < self.vmin or v > self.vmax:
            return 0.0
        return 1.0 / max(self.ndv, 1.0)

    def eq_frac(self, v: float) -> float:
        return self._eq_nonnull(v) * (1.0 - self.null_frac)

    def le_frac(self, v: float) -> float:
        """P(col <= v | col not null), via histogram interpolation."""
        if self.edges is None:
            return _DEFAULT_SEL
        e = self.edges
        if v < e[0]:
            return 0.0
        if v >= e[-1]:
            return 1.0
        # position among bucket edges + linear interpolation inside bucket
        i = int(np.searchsorted(e, v, side="right")) - 1
        i = min(i, len(e) - 2)
        lo, hi = float(e[i]), float(e[i + 1])
        frac_in = 0.5 if hi <= lo else (v - lo) / (hi - lo)
        return (i + frac_in) / (len(e) - 1)

    def range_frac(self, lo: float | None, hi: float | None,
                   lo_inc: bool = True, hi_inc: bool = True) -> float:
        """P(lo <op> col <op> hi) over ALL rows (nulls fail the filter).
        Exclusive bounds subtract one value's probability mass — essential
        on discrete domains (dict codes, dates, small ints)."""
        if hi is None:
            p_hi = 1.0
        else:
            p_hi = self.le_frac(hi) - (
                self._eq_nonnull(hi) if not hi_inc else 0.0
            )
        if lo is None:
            p_lo = 0.0
        else:
            p_lo = self.le_frac(lo) - (
                self._eq_nonnull(lo) if lo_inc else 0.0
            )
        sel = min(max(p_hi - p_lo, 0.0), 1.0)
        return sel * (1.0 - self.null_frac)


@dataclass
class TableStats:
    nrows: int
    cols: dict[str, ColumnStats] = field(default_factory=dict)

    # ---- expression selectivity --------------------------------------
    def selectivity(self, expr, table) -> float:
        """Estimated fraction of rows satisfying `expr` (a filter tree).
        `table` is the core Table (for dictionaries + schema)."""
        from ..expr import ir as E

        def col_of(e):
            if isinstance(e, E.ColRef):
                base = e.name.split(".", 1)[-1]
                return base if base in self.cols else None
            return None

        def lit_storage(value, colname):
            """Literal -> storage-domain float (None if unconvertible)."""
            from ..core.dtypes import TypeKind
            from ..expr.compile import bind_value

            if value is None:
                return None
            try:
                dt = table.schema[colname]
            except KeyError:
                return None
            if dt.kind is TypeKind.VARCHAR:
                import bisect

                d = table.dicts.get(colname)
                if d is None or not isinstance(value, str):
                    return None
                # sorted dicts: rank of the string = code-domain position
                return float(bisect.bisect_left(d.values(), value))
            try:
                return float(bind_value(value, dt))
            except (TypeError, ValueError):
                return None

        def sel(e) -> float:
            if isinstance(e, E.BoolOp):
                parts = [sel(a) for a in e.args]
                if e.op == "and":
                    out = 1.0
                    for p in parts:
                        out *= p
                    return out
                out = 1.0
                for p in parts:
                    out *= (1.0 - p)
                return 1.0 - out
            if isinstance(e, E.Not):
                return max(0.0, 1.0 - sel(e.arg))
            if isinstance(e, E.IsNull):
                c = col_of(e.arg)
                if c is None:
                    return _DEFAULT_SEL
                nf = self.cols[c].null_frac
                return (1.0 - nf) if e.negated else nf
            if isinstance(e, E.Between):
                c = col_of(e.arg)
                if c is None:
                    return _DEFAULT_SEL
                lo = lit_storage(e.low.value, c) if isinstance(e.low, E.Literal) else None
                hi = lit_storage(e.high.value, c) if isinstance(e.high, E.Literal) else None
                if lo is None and hi is None:
                    return _DEFAULT_SEL
                s = self.cols[c].range_frac(lo, hi)
                return (1.0 - s) if e.negated else s
            if isinstance(e, E.InList):
                c = col_of(e.arg)
                if c is None:
                    return _DEFAULT_SEL
                cs = self.cols[c]
                s = min(
                    len(e.values) * (1.0 - cs.null_frac) / max(cs.ndv, 1.0),
                    1.0,
                )
                return (1.0 - s) if e.negated else s
            if isinstance(e, E.Compare):
                l, r = e.left, e.right
                op = e.op
                if isinstance(l, E.Literal) and not isinstance(r, E.Literal):
                    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                    l, r = r, l
                    op = flip.get(op, op)
                c = col_of(l)
                if c is None or not isinstance(r, E.Literal):
                    return _DEFAULT_SEL
                v = lit_storage(r.value, c)
                if v is None:
                    return _DEFAULT_SEL
                cs = self.cols[c]
                if op in ("=", "=="):
                    return cs.eq_frac(v)
                if op in ("!=", "<>"):
                    return max(0.0, (1.0 - cs.null_frac) - cs.eq_frac(v))
                if op == "<":
                    return cs.range_frac(None, v, hi_inc=False)
                if op == "<=":
                    return cs.range_frac(None, v)
                if op == ">":
                    return max(
                        0.0, (1.0 - cs.null_frac) - cs.range_frac(None, v)
                    )
                if op == ">=":
                    return max(
                        0.0,
                        (1.0 - cs.null_frac) - cs.range_frac(None, v, hi_inc=False),
                    )
                return _DEFAULT_SEL
            # LIKE / Func / Case / arithmetic comparisons: no model
            return _DEFAULT_SEL

        s = sel(expr)
        return float(min(max(s, 0.0), 1.0))

    def ndv_of(self, colname: str) -> float | None:
        base = colname.split(".", 1)[-1]
        cs = self.cols.get(base)
        return cs.ndv if cs is not None else None


def collect_table_stats(table) -> TableStats:
    """One vectorized pass per column; big columns are stride-sampled to
    SAMPLE_CAP rows for NDV/histograms (min/max always exact)."""
    nrows = table.nrows
    ts = TableStats(nrows)
    if nrows == 0:
        return ts
    for f in table.schema.fields:
        arr = table.data.get(f.name)
        if arr is None or arr.dtype.kind not in "iufb":
            continue
        arr = np.asarray(arr)
        valid = table.valid.get(f.name)
        if valid is not None:
            nn = arr[np.asarray(valid, dtype=bool)]
        else:
            nn = arr
        n_nonnull = len(nn)
        if n_nonnull == 0:
            ts.cols[f.name] = ColumnStats(0.0, 0.0, 0.0, 1.0, None)
            continue
        vmin = float(nn.min())
        vmax = float(nn.max())
        if n_nonnull > SAMPLE_CAP:
            step = n_nonnull // SAMPLE_CAP
            sample = nn[:: step]
        else:
            sample = nn
        d = len(np.unique(sample))
        if len(sample) == n_nonnull:
            ndv = float(d)
        elif d >= 0.1 * len(sample):
            # near-unique in the sample: scale linearly
            ndv = min(float(n_nonnull), d * (n_nonnull / len(sample)))
        else:
            # saturated: the sample already saw (almost) every value
            ndv = float(d)
        qs = np.linspace(0.0, 1.0, N_BUCKETS + 1)
        edges = np.quantile(sample.astype(np.float64), qs)
        null_frac = 1.0 - n_nonnull / nrows
        ts.cols[f.name] = ColumnStats(ndv, vmin, vmax, null_frac, edges)
    return ts


class StatsManager:
    """Per-catalog stats cache: recollects when a table's snapshot object
    changes (refresh installs a NEW Table per data version)."""

    def __init__(self, catalog):
        self.catalog = catalog
        self._cache: dict[str, tuple[object, TableStats]] = {}

    def table_stats(self, name: str) -> TableStats | None:
        t = self.catalog.get(name)
        if t is None:
            return None
        is_priv = getattr(self.catalog, "is_private", None)
        if is_priv is not None and is_priv(name):
            # tx-private view: per-statement snapshot objects would force a
            # recollection every statement AND evict the committed entry.
            # Slightly-stale committed stats are fine for estimation.
            hit = self._cache.get(name)
            return hit[1] if hit is not None else None
        hit = self._cache.get(name)
        if hit is not None and hit[0] is t:
            return hit[1]
        ts = collect_table_stats(t)
        # hold the Table itself: identity compare is exact, and the held
        # reference prevents id() reuse from serving stale stats
        self._cache[name] = (t, ts)
        return ts

    def invalidate(self, name: str) -> None:
        self._cache.pop(name, None)
