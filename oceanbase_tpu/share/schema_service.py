"""Multi-version schema service.

Reference surface: ObMultiVersionSchemaService (share/schema/
ob_multi_version_schema_service.h:113) — a versioned in-memory cache of all
table schemas; every DDL produces a new schema version; executing code
takes a schema *guard* pinning one version so concurrent DDL never mutates
a statement's view mid-flight.

The rebuild keeps copy-on-write name->TableInfo maps per version. TableInfo
objects themselves carry runtime state (dictionaries, data versions) shared
across schema versions — the version history answers "which tables existed
and with what shape", not "what rows they held".
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from types import MappingProxyType


class SchemaError(Exception):
    pass


@dataclass(frozen=True)
class SchemaGuard:
    """An immutable view of the schema at one version."""

    version: int
    tables: MappingProxyType

    def get(self, name: str):
        return self.tables.get(name)

    def __contains__(self, name: str) -> bool:
        return name in self.tables

    def names(self) -> list[str]:
        return sorted(self.tables)


class SchemaService:
    """Versioned table registry with guard-based reads."""

    def __init__(self, history_limit: int = 64):
        self._lock = threading.RLock()
        self._version = 0
        self._maps: dict[int, MappingProxyType] = {
            0: MappingProxyType({})
        }
        self._history_limit = history_limit

    @property
    def version(self) -> int:
        return self._version

    def guard(self, version: int | None = None) -> SchemaGuard:
        if version is None:
            # lock-free current-version read: dict.get is GIL-atomic, and
            # a publish race (version bumped before its map lands) simply
            # misses and falls through to the locked path below
            v = self._version
            m = self._maps.get(v)
            if m is not None:
                return SchemaGuard(v, m)
        with self._lock:
            v = self._version if version is None else version
            m = self._maps.get(v)
            if m is None:
                raise SchemaError(f"schema version {v} expired")
            return SchemaGuard(v, m)

    def apply_ddl(self, mutate) -> int:
        """Run a DDL mutation on a copy of the current map; publish it as a
        new version. `mutate(dict)` edits in place and may raise to abort."""
        with self._lock:
            cur = dict(self._maps[self._version])
            mutate(cur)
            self._version += 1
            self._maps[self._version] = MappingProxyType(cur)
            # retire old versions beyond the history window
            floor = self._version - self._history_limit
            for v in [v for v in self._maps if v < floor]:
                del self._maps[v]
            return self._version
