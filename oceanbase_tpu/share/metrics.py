"""Tenant-wide metrics registry: counters, gauges, wait events, histograms.

Reference surface: the uniform stats/event fabric the reference threads
through every layer — ob_stat_event.h counter ids (GV$SYSSTAT),
ob_wait_event.h wait classes with count/total/max accumulators
(GV$SYSTEM_EVENT), and the response-time histogram behind
QUERY_RESPONSE_TIME. The rebuild keeps the same three shapes:

  * Counter/Gauge  — monotonically-added / last-set numeric stats,
    surfaced by __all_virtual_sysstat;
  * WaitEvent      — count / total_time / max_time per event class,
    surfaced by __all_virtual_system_event;
  * Histogram      — fixed log-spaced latency buckets with p50/p95/p99
    readout, surfaced by __all_virtual_query_response_time.

One registry per Database (per tenant). Everything is guarded by a single
lock — the hot-path cost is one dict lookup + float add, and the
`enabled` flag turns every record call into a cheap early return so the
overhead bench (tools/obs_overhead_bench.py) can compare on/off.

Device-side note: nothing here may be called from traced/jitted code
(Python side effects don't survive tracing). All recording happens at the
host boundaries: statement dispatch, compile, result fetch, bus delivery.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from contextlib import contextmanager
from dataclasses import dataclass, field

# log-spaced upper bounds (seconds) shared by every latency histogram:
# 50us..10s covers host parse (<100us) through XLA compiles (seconds)
DEFAULT_BUCKETS: tuple[float, ...] = (
    50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3, 100e-3, 250e-3, 500e-3,
    1.0, 2.5, 5.0, 10.0,
)


@dataclass
class WaitEvent:
    """count/total_time/max_time accumulator for one wait-event class."""

    event: str
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    @property
    def avg_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


@dataclass
class Histogram:
    """Fixed-bucket latency histogram (cumulative-on-read, prometheus
    style: bucket i counts observations <= bounds[i], +Inf catches all)."""

    name: str
    bounds: tuple[float, ...] = DEFAULT_BUCKETS
    counts: list[int] = field(default_factory=list)
    count: int = 0
    sum_s: float = 0.0

    def __post_init__(self):
        if not self.counts:
            self.counts = [0] * (len(self.bounds) + 1)

    def observe(self, seconds: float) -> None:
        self.counts[bisect_left(self.bounds, seconds)] += 1
        self.count += 1
        self.sum_s += seconds

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile (the bucket boundary the
        cumulative count crosses q*total at; the last bucket reports the
        largest finite bound — an +Inf readout is useless for dashboards)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
        return self.bounds[-1]

    @property
    def p50(self) -> float:
        return self.quantile(0.50)

    @property
    def p95(self) -> float:
        return self.quantile(0.95)

    @property
    def p99(self) -> float:
        return self.quantile(0.99)


def _prom_name(name: str) -> str:
    """Stat names are human ('sql select count'); prometheus names are
    [a-zA-Z_][a-zA-Z0-9_]*."""
    out = []
    for ch in name.lower():
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return "ob_" + s


class MetricsRegistry:
    """Thread-safe named metrics. Names are free-form strings (the stat
    catalog grows with the engine; the virtual tables sort them)."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._waits: dict[str, WaitEvent] = {}
        self._hists: dict[str, Histogram] = {}
        self.enabled = True

    # ------------------------------------------------------------ counters
    def add(self, name: str, n: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def bulk(self, adds=(), observes=(), waits=()) -> None:
        """Apply several counter bumps / histogram observations / wait
        samples under ONE lock acquisition. The serving hot path finishes
        every statement with 2-4 metric updates; taking the registry's
        shared lock once instead of per-update keeps it off the contended
        list when many session threads complete statements together.

        `adds` is an iterable of (name, n); `observes` of (name,
        seconds); `waits` of (event, seconds)."""
        if not self.enabled:
            return
        with self._lock:
            self.bulk_locked(adds, observes, waits)

    def bulk_locked(self, adds=(), observes=(), waits=()) -> None:
        """bulk() body for callers already holding self._lock — lets a
        collaborator that shares this lock (the statement-summary
        registry) fold its own state and apply the statement's metric
        updates in ONE acquisition."""
        c = self._counters
        for name, n in adds:
            c[name] = c.get(name, 0) + n
        for name, seconds in observes:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name)
            h.observe(seconds)
        for event, seconds in waits:
            w = self._waits.get(event)
            if w is None:
                w = self._waits[event] = WaitEvent(event)
            w.count += 1
            w.total_s += seconds
            if seconds > w.max_s:
                w.max_s = seconds

    # -------------------------------------------------------------- gauges
    def gauge_set(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        """High-watermark gauge: keep the largest value ever observed
        (queue-depth peaks outlive the instant a snapshot is taken)."""
        if not self.enabled:
            return
        with self._lock:
            if value > self._gauges.get(name, float("-inf")):
                self._gauges[name] = value

    def gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0)

    # --------------------------------------------------------- wait events
    def wait(self, event: str, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            w = self._waits.get(event)
            if w is None:
                w = self._waits[event] = WaitEvent(event)
            w.count += 1
            w.total_s += seconds
            if seconds > w.max_s:
                w.max_s = seconds

    @contextmanager
    def waiting(self, event: str):
        """Time a host-side wait (lock/queue/log-sync) into its class."""
        if not self.enabled:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            self.wait(event, self._clock() - t0)

    def wait_event(self, event: str) -> WaitEvent | None:
        with self._lock:
            w = self._waits.get(event)
            return WaitEvent(w.event, w.count, w.total_s, w.max_s) if w else None

    # ----------------------------------------------------------- histograms
    def observe(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name)
            h.observe(seconds)

    @contextmanager
    def timed(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            self.observe(name, self._clock() - t0)

    def histogram(self, name: str) -> Histogram | None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            return Histogram(h.name, h.bounds, list(h.counts), h.count, h.sum_s)

    # ------------------------------------------------------------ snapshots
    def counters_snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges_snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def waits_snapshot(self) -> list[WaitEvent]:
        with self._lock:
            return [
                WaitEvent(w.event, w.count, w.total_s, w.max_s)
                for w in self._waits.values()
            ]

    def hists_snapshot(self) -> list[Histogram]:
        with self._lock:
            return [
                Histogram(h.name, h.bounds, list(h.counts), h.count, h.sum_s)
                for h in self._hists.values()
            ]

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._waits.clear()
            self._hists.clear()

    # ------------------------------------------------------------- exporter
    def prometheus_text(self) -> str:
        """Text exposition format (one scrape of the whole registry):
        counters as `counter`, gauges as `gauge`, wait events as a
        count/sum/max triple, histograms as cumulative `le` buckets."""
        lines: list[str] = []
        for name, v in sorted(self.counters_snapshot().items()):
            p = _prom_name(name) + "_total"
            lines.append(f"# HELP {p} {name}")
            lines.append(f"# TYPE {p} counter")
            lines.append(f"{p} {v:g}")
        for name, v in sorted(self.gauges_snapshot().items()):
            p = _prom_name(name)
            lines.append(f"# HELP {p} {name}")
            lines.append(f"# TYPE {p} gauge")
            lines.append(f"{p} {v:g}")
        for w in sorted(self.waits_snapshot(), key=lambda x: x.event):
            p = _prom_name("wait " + w.event)
            lines.append(f"# HELP {p}_seconds wait event: {w.event}")
            lines.append(f"# TYPE {p}_seconds summary")
            lines.append(f"{p}_seconds_count {w.count}")
            lines.append(f"{p}_seconds_sum {w.total_s:g}")
            # a summary family only owns _count/_sum/quantile samples;
            # the max rides as its own declared gauge family so every
            # sample in the scrape belongs to a typed family
            lines.append(f"# HELP {p}_seconds_max wait event max: {w.event}")
            lines.append(f"# TYPE {p}_seconds_max gauge")
            lines.append(f"{p}_seconds_max {w.max_s:g}")
        for h in sorted(self.hists_snapshot(), key=lambda x: x.name):
            p = _prom_name(h.name) + "_seconds"
            lines.append(f"# HELP {p} latency histogram: {h.name}")
            lines.append(f"# TYPE {p} histogram")
            acc = 0
            for bound, c in zip(h.bounds, h.counts):
                acc += c
                lines.append(f'{p}_bucket{{le="{bound:g}"}} {acc}')
            lines.append(f'{p}_bucket{{le="+Inf"}} {h.count}')
            lines.append(f"{p}_count {h.count}")
            lines.append(f"{p}_sum {h.sum_s:g}")
        return "\n".join(lines) + "\n"
