"""User accounts + table privileges, enforced at statement resolve time.

Reference surface: src/sql/privilege_check/ (ObOraSysChecker and the
MySQL-mode priv check entrypoints) and the DCL resolvers under
src/sql/resolver/dcl/ — GRANT/REVOKE mutate the privilege columns of the
__all_user / __all_database_privilege inner tables, and every resolved
statement is checked against them before optimization.

The rebuild keeps the same shape at this engine's scale: one
PrivilegeManager per tenant Database, persisted in node meta (grants
survive restart exactly like schema), checked in DbSession._dispatch
before any plan executes. MySQL-compatible error codes surface through
SqlError.code (1142 ER_TABLEACCESS_DENIED_ERROR, 1045 for bad login,
1396 for user-management failures).
"""

from __future__ import annotations

import hashlib

PRIVS = {"select", "insert", "update", "delete", "create", "drop", "index"}


def stage2_hash(password: str) -> str:
    """mysql_native_password stage-2 hash SHA1(SHA1(pw)) as hex ('' stays
    ''). This is what __all_user stores in the reference — never the
    plaintext — and it is all the front door needs to verify a login
    scramble (see mysql_front.verify_native_password)."""
    if not password:
        return ""
    return hashlib.sha1(hashlib.sha1(password.encode()).digest()).hexdigest()

ER_TABLEACCESS_DENIED = 1142
ER_CANNOT_USER = 1396
ER_ACCESS_DENIED = 1045


class AccessDenied(Exception):
    def __init__(self, msg: str, code: int = ER_TABLEACCESS_DENIED):
        super().__init__(msg)
        self.code = code


class PrivilegeManager:
    """Accounts + grants. `root` is the bootstrap superuser (implicit ALL
    everywhere, cannot be dropped) — the reference's __all_user bootstrap
    row. Grants: user -> object ('*' = global) -> set of privileges."""

    def __init__(self, users: dict[str, str] | None = None,
                 grants: dict[str, dict[str, set]] | None = None):
        self.users = dict(users) if users else {"root": ""}
        self.users.setdefault("root", "")
        self.grants: dict[str, dict[str, set]] = {
            u: {o: set(p) for o, p in g.items()}
            for u, g in (grants or {}).items()
        }

    # ------------------------------------------------------- accounts
    def create_user(self, name: str, password: str) -> None:
        if name in self.users:
            raise AccessDenied(
                f"CREATE USER failed: '{name}' exists", ER_CANNOT_USER)
        # Only the stage-2 hash is ever stored (or persisted via to_meta):
        # plaintext at rest would disclose every credential to any
        # meta-file read.
        self.users[name] = stage2_hash(password)
        self.grants.setdefault(name, {})

    def drop_user(self, name: str) -> None:
        if name == "root":
            raise AccessDenied("cannot drop root", ER_CANNOT_USER)
        if name not in self.users:
            raise AccessDenied(
                f"DROP USER failed: no user '{name}'", ER_CANNOT_USER)
        del self.users[name]
        self.grants.pop(name, None)

    def authenticate_db(self) -> dict[str, str]:
        """name -> stage2-hash map for the MySQL front door."""
        return dict(self.users)

    # --------------------------------------------------------- grants
    def grant(self, user: str, obj: str, privs) -> None:
        if user not in self.users:
            raise AccessDenied(
                f"GRANT to unknown user '{user}'", ER_CANNOT_USER)
        ps = set(privs)
        if "all" in ps:
            ps = set(PRIVS)
        bad = ps - PRIVS
        if bad:
            raise AccessDenied(f"unknown privileges {sorted(bad)}")
        self.grants.setdefault(user, {}).setdefault(obj, set()).update(ps)

    def revoke(self, user: str, obj: str, privs) -> None:
        if user not in self.users:
            raise AccessDenied(
                f"REVOKE from unknown user '{user}'", ER_CANNOT_USER)
        ps = set(privs)
        if "all" in ps:
            ps = set(PRIVS)
        have = self.grants.get(user, {}).get(obj)
        if have is not None:
            have -= ps
            if not have:
                self.grants[user].pop(obj, None)

    def check(self, user: str, priv: str, objs) -> None:
        """Raise AccessDenied(1142) unless `user` holds `priv` on every
        object in `objs` (directly or via the '*' global grant)."""
        if user == "root":
            return
        g = self.grants.get(user, {})
        glob = g.get("*", ())
        for obj in objs:
            if priv in glob or priv in g.get(obj, ()):
                continue
            raise AccessDenied(
                f"{priv.upper()} command denied to user '{user}' "
                f"for table '{obj}'"
            )

    # ---------------------------------------------------- persistence
    def to_meta(self) -> dict:
        return {
            "users": dict(self.users),
            "hashed": True,
            "grants": {
                u: {o: sorted(p) for o, p in g.items()}
                for u, g in self.grants.items()
            },
        }

    @classmethod
    def from_meta(cls, meta: dict | None) -> "PrivilegeManager":
        if not meta:
            return cls()
        users = meta.get("users")
        if users and not meta.get("hashed"):
            # Pre-r5 metas persisted plaintext — hash on load, and the
            # next to_meta writes the hashed form.
            users = {u: stage2_hash(p) for u, p in users.items()}
        return cls(users, meta.get("grants"))
