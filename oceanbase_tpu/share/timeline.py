"""Time-sliced serving telemetry: the device/host utilization timeline.

The workload repository (server/workload.py) answers "what ran" with
point-in-time snapshots; this module answers "when, and how hard" — the
time-resolved view the async-serving front end (ROADMAP item 1) needs to
decide whether the HOST or the DEVICE is the serving ceiling. It is the
rebuild's analog of the reference's time-window stats behind
GV$OB_SERVERS cpu/time columns plus a per-tenant QoS ledger over the
OMT worker queues.

Shape: a ring of fixed-width time buckets (injectable clock — tests
drive it without sleeping; bounded memory — the ring never grows past
`capacity` buckets). Three layers feed it:

  * engine (Session._execute_entry / Executor uploads) — device-dispatch
    busy seconds, compile events, host<->device transfer interference;
  * batcher (StatementBatcher._dispatch) — batched-dispatch busy
    seconds + window-occupancy histogram (lanes per batch);
  * server (DbSession.sql / _sql_inner) — per-tenant admission waits /
    rejections against the TenantUnit worker quota, statement
    completions with host wall seconds and in-flight depth.

Every record call is a handful of GIL-atomic scalar adds into the
current bucket — no lock on the hot path (the ring lock guards only
bucket resets and readers; a preempted increment can drop a count,
which telemetry tolerates). `enabled = False` turns each record into
an attribute read; the obs_overhead_bench timeline A/B leg measures
exactly this switch under 32 serving threads.

Readout: __all_virtual_server_timeline / __all_virtual_tenant_qos
virtual tables, Database.metrics_text() gauges, and WorkloadRepository
snapshots (so tools/awr_report.py windows gain a saturation section and
server/sentinel.py can watch for starvation/compile storms).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left

from .metrics import DEFAULT_BUCKETS

# pow2 occupancy/depth histogram slots: bucket i counts samples whose
# value's next_pow2 is 2**i (slot 0 = 1, slot 10 = 1024+, clamped)
_POW2_SLOTS = 11

# per-tenant accumulator indices (one small list per tenant per bucket,
# plus one cumulative list per tenant for snapshot-diffable QoS totals)
_T_STMTS, _T_ERRORS, _T_ADMITTED, _T_REJECTED = 0, 1, 2, 3
_T_WAIT_S, _T_MAX_INFLIGHT, _T_HOST_S = 4, 5, 6
_T_FIELDS = 7

_TENANT_KEYS = ("stmts", "errors", "admitted", "rejected",
                "wait_s", "max_in_flight", "host_busy_s")


def _pow2_slot(n: int) -> int:
    s = 0
    v = 1
    while v < n and s < _POW2_SLOTS - 1:
        v <<= 1
        s += 1
    return s


def hist_quantile(bounds, counts, q: float) -> float:
    """Bucket-boundary quantile (same estimate share/metrics reports)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


class _Bucket:
    """One fixed-width time slice of serving activity."""

    __slots__ = (
        "period", "stmts", "errors", "host_busy_s", "device_busy_s",
        "dispatches", "batch_dispatches", "batch_lanes", "compile_events",
        "compile_s", "transfer_events", "transfer_bytes",
        "collective_ops", "collective_bytes",
        "stream_chunks", "stream_h2d_s", "stream_compute_s",
        "stream_overlap_s", "stream_spill_parts", "max_in_flight",
        "admitted", "rejected", "admission_wait_s", "sched_queue_max",
        "gate_admissions", "gate_wait_s", "occ_hist",
        "depth_hist", "wait_hist", "tenants",
    )

    def __init__(self):
        self.period = -1
        self.occ_hist = [0] * _POW2_SLOTS
        self.depth_hist = [0] * _POW2_SLOTS
        self.wait_hist = [0] * (len(DEFAULT_BUCKETS) + 1)
        self.tenants: dict[str, list] = {}
        self._zero()

    def _zero(self) -> None:
        self.stmts = 0
        self.errors = 0
        self.host_busy_s = 0.0
        self.device_busy_s = 0.0
        self.dispatches = 0
        self.batch_dispatches = 0
        self.batch_lanes = 0
        self.compile_events = 0
        self.compile_s = 0.0
        self.transfer_events = 0
        self.transfer_bytes = 0
        self.collective_ops = 0
        self.collective_bytes = 0
        self.stream_chunks = 0
        self.stream_h2d_s = 0.0
        self.stream_compute_s = 0.0
        self.stream_overlap_s = 0.0
        self.stream_spill_parts = 0
        self.max_in_flight = 0
        self.admitted = 0
        self.rejected = 0
        self.admission_wait_s = 0.0
        self.sched_queue_max = 0
        self.gate_admissions = 0
        self.gate_wait_s = 0.0

    def reset(self, period: int) -> None:
        self.period = period
        self._zero()
        # zero in place: the ring never reallocates its histograms
        for h in (self.occ_hist, self.depth_hist, self.wait_hist):
            for i in range(len(h)):
                h[i] = 0
        self.tenants.clear()


class ServingTimeline:
    """Bounded ring of serving-telemetry buckets, shared cluster-wide
    (tenants feed under their own name; one reader sees all of them —
    starvation is only visible ACROSS tenants)."""

    def __init__(self, bucket_s: float = 1.0, capacity: int = 120,
                 clock=time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self.bucket_s = max(float(bucket_s), 1e-3)
        self.capacity = max(int(capacity), 2)
        self._ring = [_Bucket() for _ in range(self.capacity)]
        self.enabled = True
        # self-metering: records folded since construction (sysstat gauge)
        self.records = 0
        # cumulative per-tenant QoS ledger (snapshot-diffable: windows
        # longer than the ring still diff cleanly) + TenantUnit seeds
        self._totals: dict[str, list] = {}
        self._limits: dict[str, tuple] = {}

    # ---------------------------------------------------------- tenants
    def register_tenant(self, name: str, max_workers=None,
                        queue_timeout_s: float = 0.0) -> None:
        """Seed the QoS ledger from the tenant's TenantUnit limits — the
        share the scheduler (ROADMAP item 1) will enforce against."""
        with self._lock:
            self._totals.setdefault(name, [0] * _T_FIELDS)
            self._limits[name] = (max_workers, queue_timeout_s)

    # ------------------------------------------------------------ feeds
    #
    # The record_* hot path takes NO lock: under 32 serving threads the
    # single ring lock convoys and costs ~6% of throughput (measured by
    # obs_overhead_bench's timeline A/B, budget 2%). The adds are plain
    # CPython scalar/list increments — a preempted read-modify-write can
    # drop a count, which telemetry tolerates; the lock guards only the
    # once-per-period bucket reset and the reader methods below.
    def _bucket(self, now: float) -> _Bucket:
        period = int(now / self.bucket_s)
        b = self._ring[period % self.capacity]
        if b.period != period:
            with self._lock:
                if b.period < period:
                    b.reset(period)
        return b

    def _tenant(self, b: _Bucket, name: str) -> list:
        t = b.tenants.get(name)
        if t is None:
            t = b.tenants[name] = [0] * _T_FIELDS
        return t

    def _total(self, name: str) -> list:
        t = self._totals.get(name)
        if t is None:
            t = self._totals[name] = [0] * _T_FIELDS
        return t

    def record_stmt(self, tenant: str, elapsed_s: float, failed: bool,
                    in_flight: int) -> None:
        """One completed statement (the exactly-once completion point):
        host wall seconds + admitted count + in-flight depth sample."""
        if not self.enabled:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.stmts += 1
        b.admitted += 1
        b.host_busy_s += elapsed_s
        if failed:
            b.errors += 1
        if in_flight > b.max_in_flight:
            b.max_in_flight = in_flight
        b.depth_hist[_pow2_slot(max(in_flight, 1))] += 1
        t = self._tenant(b, tenant)
        t[_T_STMTS] += 1
        t[_T_ADMITTED] += 1
        t[_T_HOST_S] += elapsed_s
        if failed:
            t[_T_ERRORS] += 1
        if in_flight > t[_T_MAX_INFLIGHT]:
            t[_T_MAX_INFLIGHT] = in_flight
        tt = self._total(tenant)
        tt[_T_STMTS] += 1
        tt[_T_ADMITTED] += 1
        tt[_T_HOST_S] += elapsed_s
        if failed:
            tt[_T_ERRORS] += 1
        if in_flight > tt[_T_MAX_INFLIGHT]:
            tt[_T_MAX_INFLIGHT] = in_flight

    def record_admission(self, tenant: str, wait_s: float,
                         admitted: bool) -> None:
        """One pass through the TenantUnit worker queue (DbSession.sql):
        wait seconds into the bucket's queue-wait histogram; a timeout
        counts the tenant a rejection."""
        if not self.enabled:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.admission_wait_s += wait_s
        b.wait_hist[bisect_left(DEFAULT_BUCKETS, wait_s)] += 1
        t = self._tenant(b, tenant)
        tt = self._total(tenant)
        t[_T_WAIT_S] += wait_s
        tt[_T_WAIT_S] += wait_s
        if not admitted:
            b.rejected += 1
            t[_T_REJECTED] += 1
            tt[_T_REJECTED] += 1

    def record_exec(self, dispatch_s: float, compile_s: float,
                    d2h_bytes: int) -> None:
        """One solo device dispatch (engine Session._execute_entry):
        device busy seconds + compile/transfer interference."""
        if not self.enabled:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.device_busy_s += dispatch_s
        b.dispatches += 1
        if compile_s > 0.0:
            b.compile_events += 1
            b.compile_s += compile_s
        if d2h_bytes:
            b.transfer_events += 1
            b.transfer_bytes += d2h_bytes

    def record_batch(self, dispatch_s: float, lanes: int,
                     queued: int = 0) -> None:
        """One batched device dispatch (StatementBatcher._dispatch):
        the whole cohort's busy time once + window occupancy + the
        dispatch-gate queue depth left behind it."""
        if not self.enabled:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.device_busy_s += dispatch_s
        b.dispatches += 1
        b.batch_dispatches += 1
        b.batch_lanes += lanes
        b.occ_hist[_pow2_slot(max(lanes, 1))] += 1
        if queued > b.sched_queue_max:
            b.sched_queue_max = queued

    def record_gate(self, wait_s: float, queued: int = 0) -> None:
        """One cohort leader through the continuous-batching dispatch
        gate (StatementBatcher._lead): admission wait seconds + the
        queue depth it observed — the scheduler's backpressure trace."""
        if not self.enabled:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.gate_admissions += 1
        b.gate_wait_s += wait_s
        if queued > b.sched_queue_max:
            b.sched_queue_max = queued

    def record_transfer(self, nbytes: int) -> None:
        """One host->device upload (Executor): transfer interference —
        a cold upload stealing device time from the serving stream."""
        if not self.enabled or not nbytes:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.transfer_events += 1
        b.transfer_bytes += nbytes

    def record_collective(self, ops: int, nbytes: int) -> None:
        """One SPMD dispatch's exchange traffic (mesh PX): how many XLA
        collectives the program ran and their static byte capacity —
        cross-chip interconnect pressure, the third interference axis
        next to compiles and host transfers."""
        if not self.enabled or not ops:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.collective_ops += ops
        b.collective_bytes += nbytes

    def record_stream(self, chunks: int, h2d_s: float, compute_s: float,
                      overlap_s: float, spill_parts: int = 0) -> None:
        """One streaming execution's pipeline activity (engine
        Session._execute_entry, from the prepared plan's StreamStats
        delta): wire-busy vs compute-busy seconds and their interval-
        union overlap — the fourth interference axis, answering whether
        the H2D tunnel or the device is the out-of-core ceiling."""
        if not self.enabled or not chunks:
            return
        b = self._bucket(self._clock())
        self.records += 1
        b.stream_chunks += chunks
        b.stream_h2d_s += h2d_s
        b.stream_compute_s += compute_s
        b.stream_overlap_s += overlap_s
        b.stream_spill_parts += spill_parts

    # ---------------------------------------------------------- readout
    def snapshot(self) -> list[dict]:
        """Live buckets as dicts, oldest first. The current (partial)
        bucket reports the wall seconds actually elapsed into it, so
        busy fractions never understate a window still filling."""
        now = self._clock()
        cur_period = int(now / self.bucket_s)
        out = []
        with self._lock:
            for b in self._ring:
                if b.period < 0 or b.period > cur_period:
                    continue
                if b.period == cur_period:
                    wall = max(now - b.period * self.bucket_s, 1e-9)
                else:
                    wall = self.bucket_s
                busy = min(b.device_busy_s / wall, 1.0) if wall else 0.0
                out.append({
                    "ts": b.period * self.bucket_s,
                    "wall_s": wall,
                    "stmts": b.stmts,
                    "errors": b.errors,
                    "host_busy_s": b.host_busy_s,
                    "device_busy_s": b.device_busy_s,
                    "device_busy_frac": busy,
                    "dispatches": b.dispatches,
                    "batch_dispatches": b.batch_dispatches,
                    "batch_lanes": b.batch_lanes,
                    "compile_events": b.compile_events,
                    "compile_s": b.compile_s,
                    "transfer_events": b.transfer_events,
                    "transfer_bytes": b.transfer_bytes,
                    "collective_ops": b.collective_ops,
                    "collective_bytes": b.collective_bytes,
                    "stream_chunks": b.stream_chunks,
                    "stream_h2d_s": b.stream_h2d_s,
                    "stream_compute_s": b.stream_compute_s,
                    "stream_overlap_s": b.stream_overlap_s,
                    "stream_spill_parts": b.stream_spill_parts,
                    "h2d_overlap_frac": (
                        b.stream_overlap_s / b.stream_h2d_s
                        if b.stream_h2d_s else 0.0),
                    "max_in_flight": b.max_in_flight,
                    "admitted": b.admitted,
                    "rejected": b.rejected,
                    "admission_wait_s": b.admission_wait_s,
                    "sched_queue_max": b.sched_queue_max,
                    "gate_admissions": b.gate_admissions,
                    "gate_wait_s": b.gate_wait_s,
                    "wait_p99_s": hist_quantile(
                        DEFAULT_BUCKETS, b.wait_hist, 0.99),
                    "occ_hist": list(b.occ_hist),
                    "depth_hist": list(b.depth_hist),
                    "wait_hist": list(b.wait_hist),
                    "tenants": {
                        name: dict(zip(_TENANT_KEYS, vals))
                        for name, vals in sorted(b.tenants.items())
                    },
                })
        out.sort(key=lambda d: d["ts"])
        return out

    def meta(self) -> dict:
        """Shape constants a stdlib-only offline reader (tools/
        awr_report.py) needs to merge bucket histograms from a dump."""
        return {"bucket_s": self.bucket_s, "capacity": self.capacity,
                "wait_bounds": list(DEFAULT_BUCKETS)}

    def qos_totals(self) -> dict[str, dict]:
        """Cumulative per-tenant QoS ledger (+ TenantUnit seeds).
        Monotone since process start: two snapshots diff into exact
        window numbers even after the bucket ring wrapped."""
        with self._lock:
            out = {}
            for name in sorted(self._totals):
                d = dict(zip(_TENANT_KEYS, self._totals[name]))
                mw, qt = self._limits.get(name, (None, 0.0))
                d["max_workers"] = -1 if mw is None else int(mw)
                d["queue_timeout_s"] = qt
                out[name] = d
            return out

    def stats(self) -> dict:
        """Self-metering (bounded-memory evidence): live bucket count,
        approximate resident bytes, records folded."""
        with self._lock:
            live = sum(1 for b in self._ring if b.period >= 0)
            nten = sum(len(b.tenants) for b in self._ring)
            # ~fixed per-bucket footprint: 3 histograms + a dozen scalars
            per_bucket = (
                (_POW2_SLOTS * 2 + len(DEFAULT_BUCKETS) + 1) * 8 + 200)
            approx = (self.capacity * per_bucket
                      + (nten + len(self._totals)) * _T_FIELDS * 8 + 120)
            return {"buckets": live, "capacity": self.capacity,
                    "bytes": approx, "records": self.records}

    def meter(self, metrics) -> None:
        """Publish the self-metering stats as sysstat gauges."""
        st = self.stats()
        snap = self.snapshot()
        wall = sum(b["wall_s"] for b in snap)
        busy = sum(b["device_busy_s"] for b in snap)
        metrics.gauge_set("timeline buckets", st["buckets"])
        metrics.gauge_set("timeline bytes", st["bytes"])
        metrics.gauge_set("timeline records", st["records"])
        metrics.gauge_set(
            "timeline device busy pct",
            round(100.0 * busy / wall, 3) if wall else 0.0)

    # ----------------------------------------------------------- config
    def set_bucket_s(self, v: float) -> None:
        with self._lock:
            self.bucket_s = max(float(v), 1e-3)
            for b in self._ring:
                b.reset(-1)  # re-keyed ring: old periods no longer map

    def set_capacity(self, n: int) -> None:
        with self._lock:
            n = max(int(n), 2)
            if n == self.capacity:
                return
            self.capacity = n
            self._ring = [_Bucket() for _ in range(n)]
