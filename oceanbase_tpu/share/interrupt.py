"""Global query interrupt: cluster-wide abort of a running statement.

Reference surface: share/interrupt — ObGlobalInterruptManager
(ob_global_interrupt_call.h:246) delivers an interrupt code to a query's
workers on every node by interrupt id; operators poll their interrupt
checker between batches and unwind.

The rebuild's analog: every node runs an InterruptManager; a statement
registers an interrupt id and polls its checker at its host-side
checkpoints — between chunks of an out-of-core run, between overflow
retries, between DML qualification and staging, between set-op/statement
stages. (A single jitted XLA program is not abortable mid-flight; the
reference's operators poll between batches, the rebuild polls between
device programs — same contract at the granularity the substrate
allows.) interrupt() reaches every node through the cluster bus, so a
coordinator can kill work running anywhere (KILL QUERY <session>).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from oceanbase_tpu.share.retry import checkpoint_deadline


class QueryInterrupted(Exception):
    """Raised at a statement checkpoint after an interrupt arrived."""


@dataclass(slots=True)
class InterruptChecker:
    interrupt_id: tuple
    _mgr: "InterruptManager"

    @property
    def is_set(self) -> bool:
        return self.interrupt_id in self._mgr._fired

    @property
    def reason(self) -> str:
        return self._mgr._fired.get(self.interrupt_id, "")

    def check(self) -> None:
        if self.is_set:
            raise QueryInterrupted(
                f"query {self.interrupt_id} interrupted: {self.reason}"
            )


@dataclass
class InterruptManager:
    """Per-node registry of live interrupt ids (one per running statement).

    Cluster propagation: `attach_bus` registers a handler at a dedicated
    bus address; interrupt() sends to every peer manager so checkers fire
    on whichever node hosts the work."""

    node_id: int = 0
    _live: set = field(default_factory=set)
    _fired: dict = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _bus: object = None
    _peer_addrs: list = field(default_factory=list)
    _addr: int | None = None

    def register(self, interrupt_id: tuple) -> InterruptChecker:
        # set.add / dict.pop are atomic under the GIL, and interrupt ids
        # carry a per-statement sequence number (never reused), so there
        # is no stale state that needs clearing atomically — the serving
        # hot path registers and unregisters lock-free.
        self._live.add(interrupt_id)
        self._fired.pop(interrupt_id, None)
        return InterruptChecker(interrupt_id, self)

    def unregister(self, interrupt_id: tuple) -> None:
        self._live.discard(interrupt_id)
        self._fired.pop(interrupt_id, None)

    def interrupt(self, interrupt_id: tuple, reason: str = "killed") -> None:
        """Fire locally and broadcast to every peer node."""
        self._fire(interrupt_id, reason)
        if self._bus is not None:
            for addr in self._peer_addrs:
                if addr != self._addr:
                    self._bus.send(
                        self._addr, addr, ("interrupt", interrupt_id, reason)
                    )

    def _fire(self, interrupt_id: tuple, reason: str) -> None:
        with self._lock:
            self._fired[interrupt_id] = reason

    # ------------------------------------------------------- cluster wire
    def attach_bus(self, bus, addr: int, peer_addrs: list[int]) -> None:
        self._bus = bus
        self._addr = addr
        self._peer_addrs = list(peer_addrs)
        bus.register(addr, self._on_message)

    def _on_message(self, _src: int, msg) -> None:
        if isinstance(msg, tuple) and msg and msg[0] == "interrupt":
            self._fire(msg[1], msg[2])


# ------------------------------------------------- per-statement plumbing
_tls = threading.local()


def set_current(checker: InterruptChecker | None):
    """Install the running statement's checker for this thread; returns
    the previous one (restore in a finally)."""
    prev = getattr(_tls, "checker", None)
    _tls.checker = checker
    return prev


def current_checker() -> InterruptChecker | None:
    return getattr(_tls, "checker", None)


def checkpoint() -> None:
    """Host-side interrupt checkpoint: raises QueryInterrupted if the
    current statement was killed, or a StatementTimeout if its deadline
    (SET ob_query_timeout / ob_trx_timeout) expired. Engines call this
    between device programs (chunks, retries, staging batches)."""
    c = current_checker()
    if c is not None:
        c.check()
    checkpoint_deadline()


# address space for interrupt managers on the LocalBus (disjoint from
# palf replica addresses, which are small ls-base + node numbers)
INTERRUPT_ADDR_BASE = 900_000


def attach_cluster_interrupts(cluster) -> dict[int, InterruptManager]:
    """One InterruptManager per node, wired through the cluster bus."""
    addrs = [INTERRUPT_ADDR_BASE + n for n in range(cluster.n_nodes)]
    managers = {}
    for n in range(cluster.n_nodes):
        m = InterruptManager(node_id=n)
        m.attach_bus(cluster.bus, addrs[n], addrs)
        managers[n] = m
    return managers
