"""Durable file-write primitives shared by every meta/checkpoint writer.

One implementation of the write-tmp -> flush -> fsync -> rename -> fsync-dir
sequence (torn writes invisible, rename durable) so the log store's meta,
LS checkpoints, and node meta cannot drift apart in their crash behavior.
"""

from __future__ import annotations

import os


def atomic_write(path: str, data: bytes, fsync: bool = True) -> None:
    """Atomically replace `path` with `data`. With fsync, both the file and
    its directory entry are durable when this returns."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync and d:
        try:
            dfd = os.open(d, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
