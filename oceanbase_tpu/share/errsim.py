"""ERRSIM tracepoints + debug sync: runtime fault injection.

Reference surface: the ERRSIM build's EN_* tracepoints
(deps/oblib/src/lib/utility/ob_tracepoint_def.h, activated at runtime to
return injected errors at named code points) and ObDebugSync
(share/ob_debug_sync.h, named sync points where tests park/interleave
executions).

The rebuild keeps both always-on (they cost one dict lookup when idle):

  errsim_point("EN_MINI_MERGE")      raises the armed error (count-limited
                                     and/or probabilistic) at the point
  debug_sync("BEFORE_COMMIT")        runs a test-armed callback at the
                                     point — the deterministic harness's
                                     way to interleave actions mid-flow
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass


class InjectedError(Exception):
    """Default error raised by an armed tracepoint."""


@dataclass
class _Arm:
    error: Exception | None
    prob: float
    remaining: int  # -1 = unlimited
    fired: int = 0
    # scope the arm to one path class (ckpt/meta/artifact/spill/backup/
    # sstable — see storage/integrity.py); None fires for every class
    path_class: object = None


#: default seed for probabilistic arms; reseed() replays a chaos schedule
DEFAULT_SEED = 0xE5


class ErrsimRegistry:
    def __init__(self, seed: int = DEFAULT_SEED):
        self._arms: dict[str, _Arm] = {}
        self._lock = threading.Lock()
        self.seed = seed
        self._rng = random.Random(seed)

    def reseed(self, seed: int) -> None:
        """Reset the probabilistic-arm RNG so a logged chaos seed replays
        the exact same firing sequence."""
        with self._lock:
            self.seed = seed
            self._rng = random.Random(seed)

    def arm(self, name: str, error: Exception | None = None,
            prob: float = 1.0, count: int = -1,
            path_class: object = None) -> None:
        """Arm a tracepoint: `error` raises at the point (default
        InjectedError(name)); fires `count` times (-1 = until cleared)
        with probability `prob`. `path_class` (str or tuple of str)
        restricts a disk-fault arm to matching should_fire() callers."""
        with self._lock:
            self._arms[name] = _Arm(error, prob, count,
                                    path_class=path_class)

    def clear(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._arms.clear()
            else:
                self._arms.pop(name, None)

    def fired(self, name: str) -> int:
        with self._lock:
            a = self._arms.get(name)
            return a.fired if a else 0

    def check(self, name: str) -> None:
        """Called at the injection point; raises if armed."""
        with self._lock:
            a = self._arms.get(name)
            if a is None or a.remaining == 0:
                return
            if a.prob < 1.0 and self._rng.random() >= a.prob:
                return
            if a.remaining > 0:
                a.remaining -= 1
            a.fired += 1
            err = a.error
        raise err if err is not None else InjectedError(name)

    def should_fire(self, name: str, path_class: str | None = None) -> bool:
        """Non-raising fire decision for data-corrupting arms (the disk
        fault layer in storage/integrity.py asks, then corrupts the bytes
        itself instead of raising). Honors prob/count exactly like check()
        and additionally filters on the arm's path-class scope."""
        with self._lock:
            a = self._arms.get(name)
            if a is None or a.remaining == 0:
                return False
            if a.path_class is not None:
                classes = (a.path_class if isinstance(a.path_class, (tuple, list, set, frozenset)) else (a.path_class,))
                if path_class not in classes:
                    return False
            if a.prob < 1.0 and self._rng.random() >= a.prob:
                return False
            if a.remaining > 0:
                a.remaining -= 1
            a.fired += 1
            return True


class DebugSyncRegistry:
    def __init__(self):
        self._actions: dict[str, object] = {}
        self._lock = threading.Lock()

    def activate(self, name: str, action) -> None:
        with self._lock:
            self._actions[name] = action

    def deactivate(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._actions.clear()
            else:
                self._actions.pop(name, None)

    def reach(self, name: str) -> None:
        with self._lock:
            action = self._actions.get(name)
        if action is not None:
            action()


ERRSIM = ErrsimRegistry()
DEBUG_SYNC = DebugSyncRegistry()


def errsim_point(name: str) -> None:
    """The EN_* macro analog: call at a fault-injectable code point."""
    ERRSIM.check(name)


def debug_sync(name: str) -> None:
    """The DEBUG_SYNC macro analog: call at an interleavable code point."""
    DEBUG_SYNC.reach(name)
