"""Tmp-file manager: paged spill storage for larger-than-device operators.

Reference surface: storage/tmp_file — the paged temp-file system backing
SQL spill (sort runs, hash-join partitions, hash-agg partitions) with
per-tenant accounting.

The rebuild spills numpy column chunks to .npz segments under a spill
directory, tracks bytes, and cleans up deterministically. The device-side
consumers live in ops/spill.py, and the streaming pipeline's grace-hash
partitioned join/group-by (engine/pipeline.py) spills its key-disjoint
partition segments through the same manager.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading

import numpy as np


class TmpFileManager:
    def __init__(self, root: str | None = None, limit_bytes: int = 8 << 30,
                 tenant: object = "sys", io_mgr=None):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="ob_tpu_spill_")
        os.makedirs(self.root, exist_ok=True)
        self.limit_bytes = limit_bytes
        self._bytes = 0
        self._seq = 0
        self._lock = threading.Lock()
        # per-tenant IO isolation (share/io_manager; ObIOManager analog)
        self.tenant = tenant
        if io_mgr is None:
            from ..share.io_manager import GLOBAL_IO

            io_mgr = GLOBAL_IO
        self.io_mgr = io_mgr

    def write_segment(self, cols: dict[str, np.ndarray]) -> str:
        """Spill one segment (a dict of equal-length column arrays)."""
        with self._lock:
            self._seq += 1
            path = os.path.join(self.root, f"seg_{self._seq:06d}.npz")
        self.io_mgr.account(
            self.tenant, sum(a.nbytes for a in cols.values())
        )
        np.savez(path, **cols)
        sz = os.path.getsize(path)
        with self._lock:
            self._bytes += sz
            if self._bytes > self.limit_bytes:
                self._bytes -= sz
                os.unlink(path)
                raise RuntimeError(
                    f"spill limit exceeded: {self._bytes + sz} > {self.limit_bytes}"
                )
        return path

    def read_segment(self, path: str) -> dict[str, np.ndarray]:
        self.io_mgr.account(self.tenant, os.path.getsize(path))
        with np.load(path) as z:
            return {k: z[k] for k in z.files}

    def free_segment(self, path: str) -> None:
        try:
            sz = os.path.getsize(path)
            os.unlink(path)
            with self._lock:
                self._bytes -= sz
        except FileNotFoundError:
            pass

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def close(self) -> None:
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)
        self._bytes = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
