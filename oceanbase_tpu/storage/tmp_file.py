"""Tmp-file manager: paged spill storage for larger-than-device operators.

Reference surface: storage/tmp_file — the paged temp-file system backing
SQL spill (sort runs, hash-join partitions, hash-agg partitions) with
per-tenant accounting.

The rebuild spills numpy column chunks to .npz segments under a spill
directory, tracks bytes, and cleans up deterministically. The device-side
consumers live in ops/spill.py, and the streaming pipeline's grace-hash
partitioned join/group-by (engine/pipeline.py) spills its key-disjoint
partition segments through the same manager.

Segments ride the shared integrity envelope (storage/integrity.py): a
corrupt segment raises a typed CorruptBlock on read — counted, and the
bad file deleted so it is never re-read — and the statement retry
taxonomy (share/retry.py) classifies it as recomputable: the grace-hash
run re-partitions from the base tables on the retry.
"""

from __future__ import annotations

import io
import os
import shutil
import tempfile
import threading

import numpy as np

from .integrity import SPILL, CorruptBlock, apply_write_faults, read_verified, wrap


class TmpFileManager:
    def __init__(self, root: str | None = None, limit_bytes: int = 8 << 30,
                 tenant: object = "sys", io_mgr=None, metrics=None):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="ob_tpu_spill_")
        os.makedirs(self.root, exist_ok=True)
        self.limit_bytes = limit_bytes
        self._bytes = 0
        self._seq = 0
        self._lock = threading.Lock()
        # per-tenant IO isolation (share/io_manager; ObIOManager analog)
        self.tenant = tenant
        if io_mgr is None:
            from ..share.io_manager import GLOBAL_IO

            io_mgr = GLOBAL_IO
        self.io_mgr = io_mgr
        self.metrics = metrics

    def write_segment(self, cols: dict[str, np.ndarray]) -> str:
        """Spill one segment (a dict of equal-length column arrays)."""
        with self._lock:
            self._seq += 1
            path = os.path.join(self.root, f"seg_{self._seq:06d}.npz")
        self.io_mgr.account(
            self.tenant, sum(a.nbytes for a in cols.values())
        )
        buf = io.BytesIO()
        np.savez(buf, **cols)
        # spill is transient (a crash loses the statement anyway): no
        # fsync/rename, but the envelope + write-fault arms still apply
        data = apply_write_faults(wrap(buf.getvalue()), SPILL)
        with open(path, "wb") as f:
            f.write(data)
        sz = os.path.getsize(path)
        with self._lock:
            self._bytes += sz
            if self._bytes > self.limit_bytes:
                self._bytes -= sz
                os.unlink(path)
                raise RuntimeError(
                    f"spill limit exceeded: {self._bytes + sz} > {self.limit_bytes}"
                )
        return path

    def read_segment(self, path: str) -> dict[str, np.ndarray]:
        self.io_mgr.account(self.tenant, os.path.getsize(path))
        try:
            payload = read_verified(path, path_class=SPILL)
        except CorruptBlock:
            # count, then delete: the segment must never be re-read (the
            # retrying statement re-partitions and re-spills fresh ones)
            if self.metrics is not None:
                self.metrics.add("spill segment corruption")
                self.metrics.add("checksum failures")
            self.free_segment(path)
            raise
        with np.load(io.BytesIO(payload)) as z:
            return {k: z[k] for k in z.files}

    def free_segment(self, path: str) -> None:
        try:
            sz = os.path.getsize(path)
            os.unlink(path)
            with self._lock:
                self._bytes -= sz
        except FileNotFoundError:
            pass

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def close(self) -> None:
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)
        self._bytes = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
