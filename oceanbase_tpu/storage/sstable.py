"""Immutable columnar SSTables.

Reference surface: storage/blocksstable — LSM sstables of macro/micro blocks
with a block index tree (index_block/), per-block zone maps used by filter
pushdown, and a bloom-filter cache; minor sstables carry multi-version rows
and delete tombstones, major sstables one flattened version per key
(storage/compaction). The rebuild stores:

  * rows sorted by rowkey, chunked into micro blocks (microblock.py);
  * two hidden columns: __version (commit version of the row) and __op
    (0 = PUT, 1 = DELETE tombstone) — the multi-version/tombstone model;
  * a footer-addressed block index: per block {offset, len, nrows, end key}
    plus per-column zone maps (min/max) for block pruning;
  * a bloom filter over hashed rowkeys for point-get negatives.

Everything is a single bytes blob / file; readers decode pruned blocks into
numpy columns which the engine ships to the device once.
"""

from __future__ import annotations

import itertools
import os
import struct
from dataclasses import dataclass

import numpy as np

from ..core.dtypes import Schema
from . import encoding as enc
from .microblock import DEFAULT_BLOCK_ROWS, BlockReader, write_block

MAGIC = 0x0B55_7AB1
VERSION = 1
VERSION_COL = "__version"
OP_COL = "__op"
OP_PUT = 0
OP_DELETE = 1

_FOOTER = struct.Struct("<IHHIQQQQqqI")
# magic, version, nkeys, ncols, nblocks, index_off, bloom_off, bloom_len,
# base_version, end_version, crc


def _mix64(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def hash_keys(keys: np.ndarray) -> np.ndarray:
    """Hash [n, nkeys] int64 rowkeys to uint64 (bloom + routing)."""
    h = np.zeros(len(keys), dtype=np.uint64)
    golden = np.uint64(0x9E3779B97F4A7C15)
    for j in range(keys.shape[1]):
        h = _mix64(h ^ (keys[:, j].astype(np.uint64) + golden))
    return h


class Bloom:
    """Split-block-free simple bloom: k=4 probes from one 64-bit hash."""

    def __init__(self, bits: np.ndarray):
        self.bits = bits  # uint8 array, length power of two
        self.mask = np.uint64(len(bits) * 8 - 1)

    @staticmethod
    def build(hashes: np.ndarray, bits_per_key: int = 10) -> "Bloom":
        nbits = 1 << max(6, int(np.ceil(np.log2(max(1, len(hashes)) * bits_per_key))))
        bits = np.zeros(nbits // 8, dtype=np.uint8)
        bloom = Bloom(bits)
        for probe in bloom._probes(hashes):
            np.bitwise_or.at(bits, probe >> 3, np.uint8(1) << (probe & 7).astype(np.uint8))
        return bloom

    def _probes(self, h: np.ndarray):
        h = h.astype(np.uint64)
        h2 = _mix64(h)
        for k in range(4):
            yield ((h + np.uint64(k) * h2) & self.mask).astype(np.int64)

    def may_contain(self, hashes: np.ndarray) -> np.ndarray:
        out = np.ones(len(hashes), dtype=bool)
        for probe in self._probes(hashes):
            bit = (self.bits[probe >> 3] >> (probe & 7).astype(np.uint8)) & 1
            out &= bit.astype(bool)
        return out


@dataclass
class SSTableMeta:
    nrows: int
    nblocks: int
    base_version: int  # oldest commit version contained (exclusive floor)
    end_version: int  # newest commit version contained


def write_sstable(
    schema: Schema,
    key_cols: list[str],
    data: dict[str, np.ndarray],
    versions: np.ndarray,
    ops: np.ndarray,
    valids: dict[str, np.ndarray] | None = None,
    base_version: int = 0,
    end_version: int = 0,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    enc_hints: dict | None = None,
) -> bytes:
    """Build an sstable blob. Rows MUST be sorted by (rowkey, -version).
    `enc_hints` maps column name -> advisor encoding preference
    ("for"/"rle"/"const"/"raw"), applied per block where lossless."""
    names = schema.names()
    cols = [np.ascontiguousarray(data[n]) for n in names]
    cols.append(versions.astype(np.int64))
    cols.append(ops.astype(np.int8))
    valids = valids or {}
    vlist: list[np.ndarray | None] = [valids.get(n) for n in names] + [None, None]
    # per-column hint list aligned to cols (version/op streams un-hinted)
    hlist = ([enc_hints.get(n) for n in names] + [None, None]
             if enc_hints else None)
    n = len(versions)
    key_idx = [schema.index(k) for k in key_cols]

    blocks: list[bytes] = []
    index_rows = []
    zmins, zmaxs = [], []
    off = 0
    for start in range(0, max(n, 1), block_rows):
        end = min(start + block_rows, n)
        if end <= start:
            bcols = [c[:0] for c in cols]
            bval = [None] * len(cols)
        else:
            bcols = [c[start:end] for c in cols]
            bval = [v[start:end] if v is not None else None for v in vlist]
        blob, zones = write_block(bcols, bval, hints=hlist)
        blocks.append(blob)
        # Zone bounds are stored as float64; ints above 2^53 round to nearest,
        # which could wrongly EXCLUDE a boundary value. Round outward so zone
        # maps stay conservative (pruning may keep extra blocks, never drops).
        zlo = [
            np.nextafter(z.vmin, -np.inf) if float(z.vmin) > z.vmin else float(z.vmin)
            for z in zones
        ]
        zhi = [
            np.nextafter(z.vmax, np.inf) if float(z.vmax) < z.vmax else float(z.vmax)
            for z in zones
        ]
        end_key = (
            [int(cols[i][end - 1]) for i in key_idx] if end > start else [0] * len(key_idx)
        )
        index_rows.append((off, len(blob), end - start, end_key))
        zmins.append(zlo)
        zmaxs.append(zhi)
        off += len(blob)
        if n == 0:
            break

    nb = len(blocks)
    ncols = len(cols)
    offsets = np.array([r[0] for r in index_rows], dtype=np.uint64)
    lens = np.array([r[1] for r in index_rows], dtype=np.uint32)
    nrows_arr = np.array([r[2] for r in index_rows], dtype=np.uint32)
    endkeys = np.array([r[3] for r in index_rows], dtype=np.int64).reshape(nb, len(key_idx))
    zmin_arr = np.array(zmins, dtype=np.float64).reshape(nb, ncols)
    zmax_arr = np.array(zmaxs, dtype=np.float64).reshape(nb, ncols)

    if n:
        keys2d = np.stack([data[k].astype(np.int64) for k in key_cols], axis=1)
        bloom = Bloom.build(hash_keys(keys2d))
    else:
        bloom = Bloom.build(np.zeros(0, dtype=np.uint64))

    out = bytearray()
    for b in blocks:
        out += b
    index_off = len(out)
    for arr in (offsets, lens, nrows_arr, endkeys, zmin_arr, zmax_arr):
        out += arr.tobytes()
    bloom_off = len(out)
    out += bloom.bits.tobytes()
    footer_wo_crc = _FOOTER.pack(
        MAGIC, VERSION, len(key_idx), ncols, nb, index_off, bloom_off,
        len(bloom.bits), base_version, end_version, 0,
    )[:-4]
    crc = enc.crc32(bytes(out) + footer_wo_crc)
    out += footer_wo_crc + struct.pack("<I", crc)
    return bytes(out)


class SSTable:
    """Reader over an sstable blob (mmap-able file or bytes).

    `cache` (share/cache.KVCache) memoizes decoded block columns — the
    block-cache analog: repeated snapshot scans skip codec work."""

    _uids = itertools.count(1)

    def __init__(self, buf, schema: Schema, key_cols: list[str], cache=None):
        self.uid = next(SSTable._uids)
        self.cache = cache
        self.buf = memoryview(buf)
        self.schema = schema
        self.key_cols = list(key_cols)
        fsz = _FOOTER.size
        (magic, version, nkeys, ncols, nb, index_off, bloom_off, bloom_len,
         base_version, end_version, crc) = _FOOTER.unpack_from(self.buf, len(self.buf) - fsz)
        if magic != MAGIC:
            raise ValueError(f"bad sstable magic 0x{magic:08X}")
        if version != VERSION:
            raise ValueError(f"unsupported sstable version {version}")
        if nkeys != len(key_cols):
            raise ValueError(f"sstable has {nkeys} key cols, expected {len(key_cols)}")
        self.ncols = ncols
        self.nblocks = nb
        self.base_version = base_version
        self.end_version = end_version
        pos = index_off
        self.offsets = np.frombuffer(self.buf, np.uint64, nb, pos); pos += nb * 8
        self.lens = np.frombuffer(self.buf, np.uint32, nb, pos); pos += nb * 4
        self.block_nrows = np.frombuffer(self.buf, np.uint32, nb, pos); pos += nb * 4
        self.endkeys = np.frombuffer(self.buf, np.int64, nb * nkeys, pos).reshape(nb, nkeys)
        pos += nb * nkeys * 8
        self.zmin = np.frombuffer(self.buf, np.float64, nb * ncols, pos).reshape(nb, ncols)
        pos += nb * ncols * 8
        self.zmax = np.frombuffer(self.buf, np.float64, nb * ncols, pos).reshape(nb, ncols)
        self.bloom = Bloom(np.frombuffer(self.buf, np.uint8, bloom_len, bloom_off))
        self._col_index = {n: i for i, n in enumerate(schema.names())}
        self._col_index[VERSION_COL] = ncols - 2
        self._col_index[OP_COL] = ncols - 1
        self._col_dtype = {n: schema[n].storage_np for n in schema.names()}
        self._col_dtype[VERSION_COL] = np.dtype(np.int64)
        self._col_dtype[OP_COL] = np.dtype(np.int8)

    # Checkpoint serialization (storage/slog_ckpt analog): persist the raw
    # blob only — memoryviews/np views/cache are rebuilt by __init__; the
    # block cache is runtime-only and reattached by the owner (fresh uid
    # keys mean no stale cache hits).
    def __getstate__(self):
        return {
            "buf": bytes(self.buf),
            "schema": self.schema,
            "key_cols": self.key_cols,
        }

    def __setstate__(self, d):
        self.__init__(d["buf"], d["schema"], d["key_cols"], cache=None)

    @staticmethod
    def open_file(path: str, schema: Schema, key_cols: list[str]) -> "SSTable":
        return load_sstable(path, schema, key_cols)

    def verify(self) -> bool:
        """At-rest framing check: recompute the footer crc over the whole
        blob (write_sstable stamps it; __init__ deliberately skips the
        full-blob pass on the hot path — the scrubber calls this)."""
        return sstable_crc_ok(self.buf)

    @property
    def nrows(self) -> int:
        return int(self.block_nrows.sum())

    def prune_blocks(self, ranges: dict[str, tuple[float, float]] | None) -> np.ndarray:
        """Block selection by zone maps: keep blocks overlapping every range."""
        keep = np.ones(self.nblocks, dtype=bool)
        if ranges:
            for col, (lo, hi) in ranges.items():
                i = self._col_index[col]
                keep &= (self.zmax[:, i] >= lo) & (self.zmin[:, i] <= hi)
        return np.flatnonzero(keep)

    def read_blocks(
        self, block_ids: np.ndarray, columns: list[str]
    ) -> dict[str, np.ndarray]:
        """Decode the requested columns of the given blocks, concatenated."""
        parts: dict[str, list[np.ndarray]] = {c: [] for c in columns}
        for b in block_ids:
            reader = None
            for c in columns:
                if self.cache is not None:
                    ck = (self.uid, int(b), c)
                    hit = self.cache.get(ck)
                    if hit is not None:
                        parts[c].append(hit)
                        continue
                if reader is None:
                    start = int(self.offsets[b])
                    reader = BlockReader.open(
                        self.buf[start : start + int(self.lens[b])]
                    )
                vals, _ = reader.column(self._col_index[c])
                if self.cache is not None:
                    self.cache.put((self.uid, int(b), c), vals)
                parts[c].append(vals)
        return {
            c: (np.concatenate(v) if v else np.zeros(0, dtype=self._col_dtype[c]))
            for c, v in parts.items()
        }

    def scan(
        self,
        columns: list[str] | None = None,
        ranges: dict[str, tuple[float, float]] | None = None,
        with_hidden: bool = True,
    ) -> dict[str, np.ndarray]:
        cols = list(columns) if columns is not None else self.schema.names()
        if with_hidden:
            cols = cols + [VERSION_COL, OP_COL]
        return self.read_blocks(self.prune_blocks(ranges), cols)

    def may_contain_keys(self, keys2d: np.ndarray) -> np.ndarray:
        return self.bloom.may_contain(hash_keys(keys2d))


def sstable_crc_ok(buf) -> bool:
    """Verify the embedded footer crc: it covers every byte of the blob
    except the trailing 4-byte crc field itself."""
    b = bytes(buf)
    if len(b) < _FOOTER.size:
        return False
    stored = struct.unpack_from("<I", b, len(b) - 4)[0]
    return enc.crc32(b[:-4]) == stored


def save_sstable(path: str, blob: bytes, fsync: bool = True) -> None:
    """Persist one sstable blob under the shared integrity envelope
    (at-rest framing: the envelope catches disk damage, the embedded
    footer crc stays verifiable end-to-end inside the payload)."""
    from .integrity import SSTABLE, write_atomic

    write_atomic(path, blob, fsync=fsync, path_class=SSTABLE)


def load_sstable(path: str, schema: Schema, key_cols: list[str],
                 cache=None) -> "SSTable":
    """Verified read of a save_sstable() file; raises CorruptBlock on
    envelope damage or an embedded-crc mismatch."""
    from .integrity import SSTABLE, CorruptBlock, read_verified

    blob = read_verified(path, path_class=SSTABLE)
    if not sstable_crc_ok(blob):
        raise CorruptBlock(path, "sstable footer crc mismatch")
    return SSTable(blob, schema, key_cols, cache=cache)
