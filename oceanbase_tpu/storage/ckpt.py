"""LS checkpoint: durable snapshot of replica storage state.

Reference surface: storage/slog + slog_ckpt — the storage-meta redo log and
its periodic checkpoints, which bound boot-time replay and let palf recycle
log blocks below the checkpointed point (SURVEY §5: "boot = slog ckpt
replay + palf replay", ob_server.cpp:923).

The rebuild collapses slog+ckpt into an atomic whole-replica snapshot (the
LSM state at test scale pickles in one file): {applied_lsn, tablets,
tx_table, pending 2PC redo}. Correctness rules:

  * a checkpoint is only taken when the replica has no locally-staged
    uncommitted rows (a leader mid-transaction): those belong to a live
    coordinator whose state is not durable, so the snapshot would leak
    orphan stages. Follower-side prepared redo IS included — it is
    log-derived and must survive restart for 2PC to finish.
  * the file is written tmp + fsync + rename (a torn checkpoint is
    invisible; boot falls back to the previous one).
  * after a successful checkpoint the caller may recycle the palf log
    strictly below applied_lsn + 1.

Boot order matters: restore tablets BEFORE the replica's palf elects or
receives appends, so replay (applied_lsn+1 ..] lands on restored state.
"""

from __future__ import annotations

import os
import pickle


def write_ls_checkpoint(path: str, rep, fsync: bool = True) -> int | None:
    """Snapshot one LSReplica's storage state. Returns the applied_lsn the
    snapshot covers (the ONLY safe recycle bound — the replica's live
    applied_lsn may advance while/after the pickle is cut), or None while
    leader-staged uncommitted rows exist. The previous checkpoint is kept
    as `<path>.prev` so a damaged latest file still has a fallback."""
    if rep._locally_staged:
        return None
    covered = rep.palf.applied_lsn
    # max commit version inside the snapshot: boot must advance GTS past it
    # even when NO log records remain to replay (fully-applied checkpoint)
    hwm = 0
    for t in rep.tablets.values():
        hwm = max(hwm, t.active._max_version)
        for m in t.frozen:
            hwm = max(hwm, m._max_version)
        for ss in t.deltas:
            hwm = max(hwm, ss.end_version)
        if t.base is not None:
            hwm = max(hwm, t.base.end_version)
    state = {
        "ls_id": rep.ls_id,
        "applied_lsn": covered,
        "max_version": hwm,
        "tablets": rep.tablets,
        "tx_table": dict(rep.tx_table),
        "pending_redo": dict(rep._pending_redo),
    }
    blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    if os.path.exists(path):
        try:
            os.replace(path, path + ".prev")
        except OSError:
            pass
    from .integrity import CKPT, write_atomic

    write_atomic(path, blob, fsync=fsync, path_class=CKPT)
    return covered


def read_ls_checkpoint(path: str, metrics=None) -> dict | None:
    """Read the newest verifiable checkpoint.

    Missing and corrupt are DIFFERENT outcomes: None means no checkpoint
    was ever written (fresh boot, full log replay); a damaged latest file
    is counted ("checkpoint corruption"), quarantined, and recovery falls
    back to the retained previous snapshot — replay then covers the gap
    from that older applied_lsn. Only when every existing copy fails
    verification does this raise CorruptBlock, so the caller can decide
    whether log replay from zero (or a replica rebuild) is still safe."""
    from .integrity import CKPT, CorruptBlock, quarantine_file, read_verified

    last_err: CorruptBlock | None = None
    for p in (path, path + ".prev"):
        if not os.path.exists(p):
            continue
        try:
            return pickle.loads(read_verified(p, path_class=CKPT))
        except CorruptBlock as e:
            last_err = e
        except Exception as e:  # unpicklable payload despite a valid crc
            last_err = CorruptBlock(p, f"{type(e).__name__}: {e}")
        if metrics is not None:
            metrics.add("checkpoint corruption")
            metrics.add("checksum failures")
        quarantine_file(p, last_err.reason)
    if last_err is not None:
        raise last_err
    return None


def restore_ls_replica(rep, state: dict) -> None:
    """Install a checkpoint into a freshly-built replica (before election/
    appends). Replay then resumes at applied_lsn + 1."""
    if state["applied_lsn"] < rep.palf.log.base - 1:
        # the log below base was recycled on the promise of a NEWER
        # checkpoint; this snapshot cannot be completed by replay
        raise RuntimeError(
            f"ls {rep.ls_id} node {rep.node_id}: checkpoint covers lsn "
            f"{state['applied_lsn']} but the log was recycled to "
            f"{rep.palf.log.base}; replica needs a snapshot rebuild"
        )
    rep.tablets = state["tablets"]
    rep.tx_table = dict(state["tx_table"])
    rep._pending_redo = dict(state["pending_redo"])
    rep.palf.applied_lsn = max(rep.palf.applied_lsn, state["applied_lsn"])
