"""MVCC memtable: the mutable head of the LSM.

Reference surface: storage/memtable — ObMemtable::set/scan
(ob_memtable.cpp:540) over an ObKeyBtree of ObMvccRow version chains
(mvcc/ob_mvcc_engine.h), with row latches + a lock-wait manager, frozen and
dumped by compaction. The rebuild keeps the same semantics on the host
control path (per the north star, mutation stays on CPU):

  * rowkey -> version chain, newest first; each node is
    (commit_version, op, values) once committed;
  * writes stage under a transaction id and become visible atomically at
    commit with the transaction's commit version (tx layer drives this);
  * write-write conflicts: a staged (uncommitted) node blocks other txs on
    the same key; a committed node newer than the writer's read snapshot
    aborts it (lost-update prevention);
  * snapshot reads return the newest committed node with version <= snapshot;
  * freeze() makes the memtable immutable; dump() flattens it to sorted
    arrays for a mini sstable (compaction.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..core.dtypes import Schema
from .sstable import OP_DELETE, OP_PUT


class WriteConflict(Exception):
    """Write-write conflict: retry or abort the transaction."""


@dataclass
class _Version:
    version: int  # commit version; 0 while uncommitted
    op: int  # OP_PUT / OP_DELETE
    values: tuple
    tx_id: int  # owning tx while uncommitted, else 0


@dataclass
class Memtable:
    schema: Schema
    key_cols: list[str]
    frozen: bool = False
    _rows: dict[tuple, list[_Version]] = field(default_factory=dict)
    _lock: threading.RLock = field(default_factory=threading.RLock)
    _min_version: int = 2**63 - 1
    _max_version: int = 0
    # incremental byte accounting (O(1) freezer checks): ~48B node overhead
    # + 16B per cell, maintained on stage/replay/abort
    _bytes: int = 0
    _staged: int = 0  # undecided staged node count (O(1) has_uncommitted)

    @property
    def _node_cost(self) -> int:
        return 48 + 16 * len(self.schema)

    # Checkpoint serialization (storage/slog_ckpt analog): locks are
    # runtime-only state, recreated on load.
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.RLock()

    # ---------------------------------------------------------- writes
    def stage(self, tx_id: int, read_snapshot: int, key: tuple, op: int,
              values: tuple | None) -> None:
        """Stage a write for tx_id. Raises WriteConflict on contention."""
        if self.frozen:
            raise RuntimeError("memtable is frozen")
        with self._lock:
            chain = self._rows.setdefault(key, [])
            if chain:
                head = chain[0]
                if head.tx_id and head.tx_id != tx_id:
                    raise WriteConflict(f"key {key} locked by tx {head.tx_id}")
                if head.tx_id == 0 and head.version > read_snapshot:
                    raise WriteConflict(
                        f"key {key} modified at {head.version} > snapshot {read_snapshot}"
                    )
            if chain and chain[0].tx_id == tx_id:
                # same tx overwrites its own staged node
                chain[0] = _Version(0, op, values or (), tx_id)
            else:
                chain.insert(0, _Version(0, op, values or (), tx_id))
                self._bytes += self._node_cost
                self._staged += 1

    @property
    def has_uncommitted(self) -> bool:
        """True while any staged (un-committed/un-aborted) row remains —
        a frozen memtable must not dump to sstable until every tx that
        wrote it decided (the reference blocks mini merge on active tx
        ref counts)."""
        return self._staged > 0

    @property
    def bytes_estimate(self) -> int:
        """Approximate resident bytes (tenant-freezer accounting),
        maintained incrementally so freezer checks are O(1)."""
        return max(self._bytes, 0)

    def commit(self, tx_id: int, commit_version: int) -> None:
        """Publish all nodes staged by tx_id at commit_version."""
        with self._lock:
            touched = False
            for chain in self._rows.values():
                if chain and chain[0].tx_id == tx_id:
                    chain[0].version = commit_version
                    chain[0].tx_id = 0
                    self._staged -= 1
                    touched = True
            if touched:
                self._min_version = min(self._min_version, commit_version)
                self._max_version = max(self._max_version, commit_version)

    def replay(self, key: tuple, op: int, values: tuple | None, version: int) -> None:
        """Follower replay: insert an already-committed node directly.

        Apply order is serialized by the log (one applier per log stream,
        the analog of ObTxReplayExecutor), so no conflict checks — just keep
        the chain ordered newest-first.
        """
        with self._lock:
            chain = self._rows.setdefault(key, [])
            node = _Version(version, op, values or (), 0)
            i = 0
            while i < len(chain) and (chain[i].tx_id != 0 or chain[i].version > version):
                i += 1
            if i < len(chain) and chain[i].tx_id == 0 and chain[i].version == version:
                # same tx wrote the key twice: later mutation wins, exactly
                # one node per (key, version) — matches the leader's staged
                # chain where stage() overwrote in place
                chain[i] = node
            else:
                chain.insert(i, node)
                self._bytes += self._node_cost
            self._min_version = min(self._min_version, version)
            self._max_version = max(self._max_version, version)

    def abort(self, tx_id: int) -> None:
        with self._lock:
            dead = []
            for key, chain in self._rows.items():
                if chain and chain[0].tx_id == tx_id:
                    chain.pop(0)
                    self._bytes -= self._node_cost
                    self._staged -= 1
                    if not chain:
                        dead.append(key)
            for key in dead:
                del self._rows[key]

    # ----------------------------------------------------------- reads
    def get(self, key: tuple, snapshot: int, tx_id: int = 0):
        """Newest visible node: own staged writes, else committed <= snapshot.

        Returns (op, values) or None if the key has no visible version.
        """
        with self._lock:
            chain = self._rows.get(key)
            if not chain:
                return None
            for node in chain:
                if node.tx_id == tx_id and tx_id != 0:
                    return (node.op, node.values)
                if node.tx_id == 0 and node.version <= snapshot:
                    return (node.op, node.values)
            return None

    def snapshot_rows(self, snapshot: int, tx_id: int = 0) -> dict[tuple, tuple[int, tuple]]:
        """All visible rows at `snapshot` -> {key: (op, values)} (incl. deletes)."""
        out = {}
        with self._lock:
            for key, chain in self._rows.items():
                for node in chain:
                    if (node.tx_id == tx_id and tx_id != 0) or (
                        node.tx_id == 0 and 0 < node.version <= snapshot
                    ):
                        out[key] = (node.op, node.values)
                        break
        return out

    # ---------------------------------------------------- freeze / dump
    def freeze(self) -> None:
        with self._lock:
            self.frozen = True

    @property
    def nkeys(self) -> int:
        return len(self._rows)

    @property
    def version_range(self) -> tuple[int, int]:
        if self._max_version == 0:
            return (0, 0)
        return (self._min_version, self._max_version)

    def dump(self) -> tuple[dict[str, np.ndarray], np.ndarray, np.ndarray]:
        """Flatten committed multi-version rows to sorted column arrays.

        Returns (data, versions, ops) sorted by (rowkey asc, version desc) —
        the sstable row order. Uncommitted nodes are skipped (a frozen
        memtable may still hold staged nodes of live txs; the tx layer keeps
        the memtable alive until they resolve, mirroring the reference's
        freeze protocol).
        """
        names = self.schema.names()
        keys, rows = [], []
        with self._lock:
            for key, chain in self._rows.items():
                for node in chain:
                    if node.tx_id == 0 and node.version > 0:
                        keys.append(key)
                        rows.append(node)
        if not rows:
            empty = {n: np.zeros(0, dtype=self.schema[n].storage_np) for n in names}
            return empty, np.zeros(0, np.int64), np.zeros(0, np.int8)
        keys_arr = np.array(keys, dtype=np.int64).reshape(len(rows), -1)
        vers = np.array([r.version for r in rows], dtype=np.int64)
        order = np.lexsort((-vers,) + tuple(keys_arr[:, j] for j in range(keys_arr.shape[1] - 1, -1, -1)))
        ops = np.array([rows[i].op for i in order], dtype=np.int8)
        vers = vers[order]
        data: dict[str, np.ndarray] = {}
        key_idx = {k: self.key_cols.index(k) for k in self.key_cols}
        for ci, n in enumerate(names):
            dt = self.schema[n].storage_np
            if n in key_idx:
                data[n] = keys_arr[order, key_idx[n]].astype(dt)
            else:
                vals = []
                for i in order:
                    node = rows[i]
                    vals.append(node.values[ci] if node.op == OP_PUT else 0)
                data[n] = np.asarray(vals, dtype=dt)
        return data, vers, ops
