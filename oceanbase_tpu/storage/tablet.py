"""Tablet: the storage unit binding memtables and sstables for one shard.

Reference surface: storage/tablet + ls — a tablet is the replication/storage
unit of one table partition; ObLSTabletService::table_scan
(ls/ob_ls_tablet_service.cpp:616) routes reads through the memtable +
sstable fuse; the tenant freezer (tx_storage/ob_tenant_freezer.h) freezes
memtables on memory pressure and the tablet scheduler compacts.

The rebuild's Tablet owns:
  * one active Memtable + a list of frozen ones awaiting dump;
  * delta sstables (mini/minor, multi-version) and one base (major);
  * scan(): MVCC fuse via scan_merge into numpy columns (then to_batch()
    for device execution);
  * freeze()/minor_compact()/major_compact(): the LSM maintenance ops,
    callable directly or from the dag scheduler.

Thread-safety: structural changes (freeze/compact swaps) take _meta_lock;
row-level concurrency lives inside Memtable.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from ..core.dtypes import Schema
from ..core.table import Table
from .compaction import freeze_to_mini, major_compact, minor_compact
from .memtable import Memtable
from .scan_merge import scan_merge
from .sstable import SSTable


class SnapshotDiscarded(Exception):
    """Read snapshot is older than the tablet's recycle point: the versions
    needed to reconstruct it were dropped by major compaction (the analog of
    the reference's OB_SNAPSHOT_DISCARDED)."""


@dataclass
class Tablet:
    tablet_id: int
    schema: Schema
    key_cols: list[str]
    active: Memtable = None  # type: ignore[assignment]
    frozen: list[Memtable] = field(default_factory=list)
    deltas: list[SSTable] = field(default_factory=list)  # oldest -> newest
    base: SSTable | None = None
    cache: object = None  # share/cache.KVCache for decoded blocks
    # column -> advisor encoding preference ("for"/"rle"/"const"/"raw"),
    # applied at every dump/compaction so the choice persists on disk;
    # rides checkpoints through __getstate__ like the rest of the tablet
    enc_hints: dict = field(default_factory=dict)
    _meta_lock: threading.RLock = field(default_factory=threading.RLock)
    # serializes whole maintenance operations (dump/minor/major) so two dag
    # workers cannot dump the same frozen memtable or compact the same
    # victims twice; _meta_lock still guards the structure swaps inside
    _maint_lock: threading.RLock = field(default_factory=threading.RLock)

    def __post_init__(self):
        if self.active is None:
            self.active = Memtable(self.schema, self.key_cols)

    # Checkpoint serialization (storage/slog_ckpt analog): locks and the
    # block cache are runtime-only, recreated/reattached on load.
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_meta_lock", None)
        d.pop("_maint_lock", None)
        d["cache"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.__dict__.setdefault("enc_hints", {})  # pre-hint checkpoints
        self._meta_lock = threading.RLock()
        self._maint_lock = threading.RLock()

    # ------------------------------------------------------------ write
    def stage(self, tx_id: int, read_snapshot: int, key: tuple, op: int,
              values: tuple | None) -> "Memtable":
        """Stage a row write; returns the memtable written (for tx bookkeeping)."""
        with self._meta_lock:
            mt = self.active
        mt.stage(tx_id, read_snapshot, key, op, values)
        return mt

    def commit_tx(self, tx_id: int, commit_version: int) -> None:
        """Publish a tx's staged rows wherever they live — the ACTIVE
        memtable or one FROZEN while the tx was open (a freeze must never
        strand undecided rows)."""
        with self._meta_lock:
            mts = [self.active] + list(self.frozen)
        for mt in mts:
            mt.commit(tx_id, commit_version)

    def abort_tx(self, tx_id: int) -> None:
        with self._meta_lock:
            mts = [self.active] + list(self.frozen)
        for mt in mts:
            mt.abort(tx_id)

    # ------------------------------------------------------------- read
    def scan(
        self,
        snapshot: int,
        columns: list[str] | None = None,
        ranges: dict[str, tuple[float, float]] | None = None,
        tx_id: int = 0,
    ) -> dict[str, np.ndarray]:
        with self._meta_lock:
            ssts = ([self.base] if self.base else []) + list(self.deltas)
            mts = list(self.frozen) + [self.active]
            recycle = self.base.end_version if self.base else 0
        if snapshot < recycle:
            raise SnapshotDiscarded(
                f"snapshot {snapshot} < recycle point {recycle}"
            )
        return scan_merge(
            self.schema, self.key_cols, ssts, mts, snapshot,
            columns=columns, ranges=ranges, tx_id=tx_id,
        )

    def get(self, key: tuple, snapshot: int, tx_id: int = 0):
        """Point lookup: memtables newest-first, then one fused sstable read.

        A tombstone anywhere newer than a PUT must hide it, so sstables are
        never consulted one at a time — bloom filters only deselect sstables
        that provably hold NO row (including no tombstone) for the key, and
        the survivors go through a single scan_merge which resolves versions
        and tombstones globally.
        """
        from .sstable import OP_DELETE

        with self._meta_lock:
            mts = [self.active] + list(reversed(self.frozen))
            ssts = ([self.base] if self.base else []) + list(self.deltas)
        for mt in mts:
            hit = mt.get(key, snapshot, tx_id)
            if hit is not None:
                return None if hit[0] == OP_DELETE else hit
        keys2d = np.array([key], dtype=np.int64)
        cands = [st for st in ssts if st.may_contain_keys(keys2d)[0]]
        if not cands:
            return None
        names = self.schema.names()
        key_ranges = {k: (float(key[j]), float(key[j])) for j, k in enumerate(self.key_cols)}
        got = scan_merge(self.schema, self.key_cols, cands, [], snapshot,
                         ranges=key_ranges)
        kmask = np.ones(len(got[names[0]]), dtype=bool)
        for j, k in enumerate(self.key_cols):
            kmask &= got[k] == key[j]
        rows = np.flatnonzero(kmask)
        if len(rows):
            r = rows[0]
            return (0, tuple(got[n][r] for n in names))
        return None

    # ---------------------------------------------------- LSM maintenance
    def freeze(self) -> Memtable | None:
        """Swap in a fresh active memtable; returns the frozen one."""
        with self._meta_lock:
            if self.active.nkeys == 0:
                return None
            mt = self.active
            mt.freeze()
            self.frozen.append(mt)
            self.active = Memtable(self.schema, self.key_cols)
            return mt

    def dump_mini(self) -> SSTable | None:
        """Dump the oldest frozen memtable into a mini delta sstable."""
        from ..share.errsim import debug_sync, errsim_point

        errsim_point("EN_MINI_MERGE")
        debug_sync("BEFORE_MINI_DUMP")
        with self._maint_lock:
            with self._meta_lock:
                if not self.frozen:
                    return None
                mt = self.frozen[0]
            blob = freeze_to_mini(mt, enc_hints=self.enc_hints or None)
            st = SSTable(blob, self.schema, self.key_cols, cache=self.cache)
            with self._meta_lock:
                self.deltas.append(st)
                self.frozen.remove(mt)
            return st

    def minor_compact(self, recycle_version: int = 0) -> SSTable | None:
        with self._maint_lock:
            with self._meta_lock:
                victims = list(self.deltas)
            if len(victims) < 2:
                return None
            blob = minor_compact(self.schema, self.key_cols, victims,
                                 recycle_version,
                                 enc_hints=self.enc_hints or None)
            st = SSTable(blob, self.schema, self.key_cols, cache=self.cache)
            with self._meta_lock:
                kept = [d for d in self.deltas if d not in victims]
                self.deltas = [st] + kept
            return st

    def major_compact(self, snapshot: int) -> SSTable:
        """Flatten base + all dumped deltas at `snapshot` into a new base."""
        with self._maint_lock:
            with self._meta_lock:
                srcs = ([self.base] if self.base else []) + list(self.deltas)
            blob = major_compact(self.schema, self.key_cols, srcs, snapshot,
                                 enc_hints=self.enc_hints or None)
            st = SSTable(blob, self.schema, self.key_cols, cache=self.cache)
            with self._meta_lock:
                self.deltas = [d for d in self.deltas if d not in srcs]
                self.base = st
            return st

    # ----------------------------------------------------------- bridge
    def to_table(self, snapshot: int, name: str | None = None,
                 dicts: dict | None = None) -> Table:
        """Materialize a snapshot as a core Table (device marshalling point)."""
        data = self.scan(snapshot)
        return Table(name or f"tablet_{self.tablet_id}", self.schema, data,
                     dicts or {})

    @property
    def nrows_estimate(self) -> int:
        with self._meta_lock:
            n = self.active.nkeys + sum(m.nkeys for m in self.frozen)
            n += sum(d.nrows for d in self.deltas)
            if self.base:
                n += self.base.nrows
            return n
