"""Freeze and compaction: LSM maintenance.

Reference surface: storage/compaction — ObTenantTabletScheduler triggers
mini (memtable dump), minor (delta merge) and major (full flatten) merges
as DAG tasks (ob_tablet_merge_task.h:197). The rebuild implements the three
merge kinds as pure functions over sstable blobs; tablet.py owns the
scheduling policy and the dag_scheduler runs them on worker threads.

Version semantics:
  * mini: flatten a frozen memtable's committed chains (all versions kept);
  * minor: merge several delta sstables into one, keeping all versions
    (bounded by recycle_version: versions <= it are collapsed per key);
  * major: flatten everything at a snapshot into exactly one committed
    version per key, dropping tombstones.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import Schema
from .memtable import Memtable
from .sstable import OP_COL, OP_PUT, VERSION_COL, SSTable, write_sstable


def freeze_to_mini(mt: Memtable, block_rows: int = 16384,
                   enc_hints: dict | None = None) -> bytes:
    """Dump a frozen memtable into a mini sstable blob."""
    if not mt.frozen:
        raise RuntimeError("memtable must be frozen before dump")
    data, versions, ops = mt.dump()
    lo, hi = mt.version_range
    return write_sstable(
        mt.schema, mt.key_cols, data, versions, ops,
        base_version=lo, end_version=hi, block_rows=block_rows,
        enc_hints=enc_hints,
    )


def _merge_rows(
    schema: Schema, key_cols: list[str], sstables: list[SSTable]
) -> tuple[dict[str, np.ndarray], np.ndarray, np.ndarray, np.ndarray]:
    """Concatenate all rows of all sstables, sorted (key asc, version desc,
    recency desc). Returns (data, versions, ops, first_per_key mask)."""
    names = schema.names()
    parts = [st.scan(names) for st in sstables]
    ranks = np.concatenate(
        [np.full(len(p[VERSION_COL]), i, np.int32) for i, p in enumerate(parts)]
    )
    cat = {c: np.concatenate([p[c] for p in parts]) for c in names + [VERSION_COL, OP_COL]}
    keys2d = np.stack([cat[k].astype(np.int64) for k in key_cols], axis=1)
    n = len(ranks)
    order = np.lexsort(
        (-ranks, -cat[VERSION_COL])
        + tuple(keys2d[:, j] for j in range(keys2d.shape[1] - 1, -1, -1))
    )
    data = {c: cat[c][order] for c in names}
    versions = cat[VERSION_COL][order]
    ops = cat[OP_COL][order]
    sk = keys2d[order]
    first = np.ones(n, dtype=bool)
    if n > 1:
        first[1:] = (sk[1:] != sk[:-1]).any(axis=1)
    return data, versions, ops, first


def _first_match_per_key(first: np.ndarray, match: np.ndarray) -> np.ndarray:
    """Rows sorted (key asc, version desc): mark, per key segment, the FIRST
    row where `match` holds (i.e. the newest matching version)."""
    n = len(first)
    out = np.zeros(n, dtype=bool)
    if n == 0 or not match.any():
        return out
    seg = np.cumsum(first) - 1
    nseg = int(seg[-1]) + 1
    first_idx = np.full(nseg, n, dtype=np.int64)
    midx = np.flatnonzero(match)
    np.minimum.at(first_idx, seg[midx], midx)
    out[first_idx[first_idx < n]] = True
    return out


def minor_compact(
    schema: Schema,
    key_cols: list[str],
    sstables: list[SSTable],
    recycle_version: int = 0,
    block_rows: int = 16384,
    enc_hints: dict | None = None,
) -> bytes:
    """Merge delta sstables (oldest -> newest) into one multi-version delta.

    Versions <= recycle_version are collapsed to at most one (the newest
    visible at recycle_version) per key — no reader holds an older snapshot.
    """
    data, versions, ops, first = _merge_rows(schema, key_cols, sstables)
    n = len(versions)
    if recycle_version > 0 and n:
        old = versions <= recycle_version
        keep = (~old) | _first_match_per_key(first, old)
        data = {c: a[keep] for c, a in data.items()}
        versions, ops = versions[keep], ops[keep]
    lo = min((s.base_version for s in sstables), default=0)
    hi = max((s.end_version for s in sstables), default=0)
    return write_sstable(
        schema, key_cols, data, versions, ops,
        base_version=lo, end_version=hi, block_rows=block_rows,
        enc_hints=enc_hints,
    )


def major_compact(
    schema: Schema,
    key_cols: list[str],
    sstables: list[SSTable],
    snapshot: int,
    block_rows: int = 16384,
    enc_hints: dict | None = None,
) -> bytes:
    """Flatten all sources at `snapshot`: newest committed version per key,
    tombstones dropped. Produces the new base (one version per key)."""
    data, versions, ops, first = _merge_rows(schema, key_cols, sstables)
    # rows are (key asc, version desc): the winner per key is its newest
    # version visible at the snapshot; tombstone winners drop the key.
    winner = _first_match_per_key(first, versions <= snapshot)
    keep = winner & (ops == OP_PUT)
    data = {c: a[keep] for c, a in data.items()}
    return write_sstable(
        schema, key_cols, data, versions[keep], ops[keep],
        base_version=0, end_version=snapshot, block_rows=block_rows,
        enc_hints=enc_hints,
    )
