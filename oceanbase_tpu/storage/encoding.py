"""Per-column micro-block encodings (host side).

Reference surface: storage/blocksstable/encoding + cs_encoding — per-column
lightweight encodings chosen per micro block (raw/dict/RLE/const/delta...)
with SIMD decoders. This rebuild keeps four byte-aligned encodings — RAW,
CONST, FOR (frame-of-reference at byte width), RLE — chosen by a one-pass
cost model, implemented twice with an identical wire format:

  * native C++ (oceanbase_tpu/native/codec.cpp), used when a toolchain is
    available — the decode loop is a widening add that autovectorizes;
  * numpy (this file), always available.

Floats are stored RAW (or CONST); integers/dates/dict-codes/decimals go
through the integer encodings. Validity (null) bitmaps are packed little-
endian with np.packbits(bitorder="little").
"""

from __future__ import annotations

import ctypes
import zlib
from dataclasses import dataclass

import numpy as np

from ..native import load as load_native

ENC_RAW = 0
ENC_CONST = 1
ENC_FOR = 2
ENC_RLE = 3

_INT_DTYPES = {
    np.dtype(np.int8): "int8_t",
    np.dtype(np.int16): "int16_t",
    np.dtype(np.int32): "int32_t",
    np.dtype(np.int64): "int64_t",
}


def _lib():
    lib = load_native("codec")
    if lib is not None and not getattr(lib, "_ob_configured", False):
        lib.ob_crc32.restype = ctypes.c_uint32
        lib.ob_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
        for cname in _INT_DTYPES.values():
            fe = getattr(lib, f"ob_for_encode_{cname}")
            fe.restype = ctypes.c_int64
            fe.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                           ctypes.c_int, ctypes.c_void_p, ctypes.c_int64]
            fd = getattr(lib, f"ob_for_decode_{cname}")
            fd.restype = ctypes.c_int64
            fd.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                           ctypes.c_int, ctypes.c_void_p]
            re_ = getattr(lib, f"ob_rle_encode_{cname}")
            re_.restype = ctypes.c_int64
            re_.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                            ctypes.c_int64]
            rd = getattr(lib, f"ob_rle_decode_{cname}")
            rd.restype = ctypes.c_int64
            rd.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                           ctypes.c_int64]
        lib.ob_analyze_i64.restype = None
        lib.ob_analyze_i64.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_void_p]
        lib._ob_configured = True
    return lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def crc32(data: bytes | np.ndarray, seed: int = 0) -> int:
    b = data.tobytes() if isinstance(data, np.ndarray) else data
    return zlib.crc32(b, seed) & 0xFFFFFFFF


@dataclass(frozen=True)
class ColumnStats:
    vmin: int
    vmax: int
    nruns: int


def analyze_ints(a: np.ndarray) -> ColumnStats:
    """min/max/run-count in one pass (cost model input + zone map)."""
    if len(a) == 0:
        return ColumnStats(0, 0, 0)
    lib = _lib()
    if lib is not None and a.dtype == np.int64 and a.flags.c_contiguous:
        mn = ctypes.c_int64()
        mx = ctypes.c_int64()
        runs = ctypes.c_int64()
        lib.ob_analyze_i64(_ptr(a), len(a), ctypes.byref(mn), ctypes.byref(mx),
                           ctypes.byref(runs))
        return ColumnStats(mn.value, mx.value, runs.value)
    vmin = int(a.min())
    vmax = int(a.max())
    nruns = int(1 + np.count_nonzero(a[1:] != a[:-1])) if len(a) > 1 else 1
    return ColumnStats(vmin, vmax, nruns)


def _for_width(span: int) -> int:
    if span < (1 << 8):
        return 1
    if span < (1 << 16):
        return 2
    if span < (1 << 32):
        return 4
    return 8


def choose_encoding(a: np.ndarray, stats: ColumnStats) -> tuple[int, dict]:
    """Pick the cheapest encoding; returns (enc, params)."""
    n = len(a)
    if not np.issubdtype(a.dtype, np.integer):
        if n and bool(np.all(a == a.flat[0])):
            return ENC_CONST, {}
        return ENC_RAW, {}
    if n == 0:
        return ENC_RAW, {}
    if stats.vmin == stats.vmax:
        return ENC_CONST, {}
    span = stats.vmax - stats.vmin
    width = _for_width(span)
    for_bytes = n * width
    rle_bytes = 4 + stats.nruns * (4 + a.dtype.itemsize)
    raw_bytes = n * a.dtype.itemsize
    best = min(for_bytes, rle_bytes, raw_bytes)
    if best == rle_bytes:
        return ENC_RLE, {}
    if best == for_bytes and for_bytes < raw_bytes:
        return ENC_FOR, {"min": stats.vmin, "width": width}
    return ENC_RAW, {}


_HINT_ENCS = {"raw": ENC_RAW, "const": ENC_CONST, "for": ENC_FOR,
              "rle": ENC_RLE}


def hinted_encoding(a: np.ndarray, stats: ColumnStats,
                    hint: str) -> tuple[int, dict] | None:
    """Resolve an advisor encoding hint ("for"/"rle"/"const"/"raw") to
    (enc, params), or None when the hint cannot be honored losslessly on
    THIS block — a hint is a cost-model preference, never a correctness
    override (e.g. "const" on a block that stopped being constant)."""
    e = _HINT_ENCS.get(hint)
    if e is None or len(a) == 0 or not np.issubdtype(a.dtype, np.integer):
        return None
    if e == ENC_CONST:
        return (ENC_CONST, {}) if stats.vmin == stats.vmax else None
    if e == ENC_FOR:
        return ENC_FOR, {"min": stats.vmin,
                         "width": _for_width(stats.vmax - stats.vmin)}
    return e, {}


# ------------------------------------------------------------- encoders

def encode_column(a: np.ndarray, enc: int, params: dict) -> bytes:
    a = np.ascontiguousarray(a)
    if enc == ENC_RAW:
        return a.tobytes()
    if enc == ENC_CONST:
        return a[:1].tobytes()
    if enc == ENC_FOR:
        return _for_encode(a, params["min"], params["width"])
    if enc == ENC_RLE:
        return _rle_encode(a)
    raise ValueError(f"unknown encoding {enc}")


def decode_column(buf: memoryview | bytes, enc: int, params: dict,
                  dtype: np.dtype, n: int) -> np.ndarray:
    if enc == ENC_RAW:
        return np.frombuffer(buf, dtype=dtype, count=n).copy()
    if enc == ENC_CONST:
        v = np.frombuffer(buf, dtype=dtype, count=1)
        return np.full(n, v[0], dtype=dtype)
    if enc == ENC_FOR:
        return _for_decode(buf, params["min"], params["width"], dtype, n)
    if enc == ENC_RLE:
        return _rle_decode(buf, dtype, n)
    raise ValueError(f"unknown encoding {enc}")


def _for_encode(a: np.ndarray, vmin: int, width: int) -> bytes:
    lib = _lib()
    cname = _INT_DTYPES.get(a.dtype)
    out = np.empty(len(a) * width, dtype=np.uint8)
    if lib is not None and cname is not None:
        wrote = getattr(lib, f"ob_for_encode_{cname}")(
            _ptr(a), len(a), vmin, width, _ptr(out), len(out))
        if wrote != len(out):
            raise RuntimeError(f"native FOR encode failed: {wrote}")
        return out.tobytes()
    udt = np.dtype(f"u{width}")
    deltas = (a.astype(np.int64) - vmin).astype(udt)
    return deltas.tobytes()


def _for_decode(buf, vmin: int, width: int, dtype: np.dtype, n: int) -> np.ndarray:
    lib = _lib()
    cname = _INT_DTYPES.get(np.dtype(dtype))
    if lib is not None and cname is not None:
        src = np.frombuffer(buf, dtype=np.uint8, count=n * width)
        out = np.empty(n, dtype=dtype)
        got = getattr(lib, f"ob_for_decode_{cname}")(
            _ptr(np.ascontiguousarray(src)), n, vmin, width, _ptr(out))
        if got != n:
            raise RuntimeError(f"native FOR decode failed: {got}")
        return out
    udt = np.dtype(f"u{width}")
    deltas = np.frombuffer(buf, dtype=udt, count=n).astype(np.int64)
    return (deltas + vmin).astype(dtype)


def _rle_encode(a: np.ndarray) -> bytes:
    lib = _lib()
    cname = _INT_DTYPES.get(a.dtype)
    if lib is not None and cname is not None:
        cap = 4 + len(a) * (4 + a.dtype.itemsize) + 16
        out = np.empty(cap, dtype=np.uint8)
        wrote = getattr(lib, f"ob_rle_encode_{cname}")(_ptr(a), len(a),
                                                       _ptr(out), cap)
        if wrote < 0:
            raise RuntimeError(f"native RLE encode failed: {wrote}")
        return out[:wrote].tobytes()
    # numpy: vectorized run detection
    if len(a) == 0:
        return np.uint32(0).tobytes()
    starts = np.flatnonzero(np.concatenate(([True], a[1:] != a[:-1])))
    lens = np.diff(np.concatenate((starts, [len(a)]))).astype(np.uint32)
    vals = a[starts]
    nruns = np.uint32(len(starts))
    # interleave {u32 len, value} pairs
    pair = np.dtype([("len", np.uint32), ("val", a.dtype)], align=False)
    runs = np.empty(len(starts), dtype=pair)
    runs["len"] = lens
    runs["val"] = vals
    return nruns.tobytes() + runs.tobytes()


def _rle_decode(buf, dtype: np.dtype, n: int) -> np.ndarray:
    lib = _lib()
    dtype = np.dtype(dtype)
    cname = _INT_DTYPES.get(dtype)
    raw = np.frombuffer(buf, dtype=np.uint8)
    if lib is not None and cname is not None:
        out = np.empty(n, dtype=dtype)
        got = getattr(lib, f"ob_rle_decode_{cname}")(
            _ptr(np.ascontiguousarray(raw)), len(raw), _ptr(out), n)
        if got != n:
            raise RuntimeError(f"native RLE decode failed: {got} != {n}")
        return out
    nruns = int(np.frombuffer(raw, dtype=np.uint32, count=1)[0])
    pair = np.dtype([("len", np.uint32), ("val", dtype)], align=False)
    runs = np.frombuffer(raw, dtype=pair, count=nruns, offset=4)
    out = np.repeat(runs["val"], runs["len"].astype(np.int64))
    if len(out) != n:
        raise ValueError(f"RLE decoded {len(out)} rows, expected {n}")
    return out


# ----------------------------------------------------- validity bitmaps

def pack_validity(valid: np.ndarray) -> bytes:
    return np.packbits(valid.astype(np.bool_), bitorder="little").tobytes()


def unpack_validity(buf, n: int) -> np.ndarray:
    bits = np.unpackbits(np.frombuffer(buf, dtype=np.uint8), bitorder="little")
    return bits[:n].astype(np.bool_)
