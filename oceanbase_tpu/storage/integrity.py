"""Shared storage-integrity envelope: end-to-end checksums for every
durable artifact, plus the disk-fault injection layer that proves the
readers actually check them.

Reference surface: OceanBase treats silent disk corruption as a
first-class failure mode — every macroblock carries a physical checksum
(ObMacroBlockCommonHeader / ObMicroBlockHeader data_checksum), a
background inspector re-verifies data at rest, and ERRSIM builds corrupt
I/O on purpose to exercise the repair paths. Before this module the
rebuild only protected the palf log (log/store.py crc32 + torn-tail
truncation); checkpoints, node meta, plan artifacts, spill segments and
backups were trusted blindly.

The envelope is a fixed 20-byte header in front of the payload:

    magic u32 | version u16 | flags u16 | length u64 | crc32 u32

crc32 (zlib) covers the payload; length must match the remaining bytes
exactly, so torn tails, truncation, and header bitflips all surface as a
typed CorruptBlock — never a half-parsed pickle. `write_atomic` layers
the envelope over the shared tmp -> fsync -> rename sequence, and
`read_verified` is the single verified read path every adopter shares
(storage/ckpt.py, storage/backup.py, storage/tmp_file.py spill segments,
engine/plan_artifact.py, sstable at-rest framing, node meta).

Fault injection (share/errsim.py arms, probability- and path-class-
scoped so a chaos run can corrupt ONLY checkpoints, or everything):

    EN_DISK_BITFLIP     flip one payload byte as it lands on disk /
                        decay one byte of the file before a read
    EN_DISK_TORN_WRITE  persist only a prefix of the envelope
    EN_DISK_TRUNCATE    lose the file's tail before a read
    EN_IO_ERROR         raise OSError at the read/write point
    EN_CRASH_TMP_PARTIAL / EN_CRASH_BEFORE_RENAME /
    EN_CRASH_AFTER_RENAME
                        kill the writer at each write/fsync/rename
                        boundary (the crash-consistency property test
                        schedules these and asserts recovery)
"""

from __future__ import annotations

import os
import struct
import zlib

from ..share.errsim import ERRSIM, InjectedError

MAGIC = 0x0B5EA1ED
VERSION = 1
_HDR = struct.Struct("<IHHQI")  # magic, version, flags, length, crc32
HEADER_SIZE = _HDR.size

# path classes: every adopter tags its reads/writes so errsim arms (and
# the scrubber's per-class accounting) can scope to one artifact family
CKPT = "ckpt"
META = "meta"
ARTIFACT = "artifact"
SPILL = "spill"
BACKUP = "backup"
SSTABLE = "sstable"

PATH_CLASSES = (CKPT, META, ARTIFACT, SPILL, BACKUP, SSTABLE)

#: quarantine subdirectory name (bad files move here exactly once and
#: are never re-read on the hot path)
QUARANTINE_DIR = "quarantine"


class CorruptBlock(Exception):
    """A persisted block failed integrity verification. Carries the path
    and a machine-checkable reason so recovery can be typed (checkpoint
    -> log replay, artifact -> recompute, tablet -> replica rebuild)."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt block {path}: {reason}")
        self.path = path
        self.reason = reason


class CounterSink:
    """Minimal metrics adapter for boot-time code that runs before the
    real metrics registry exists; counts fold into sysstat later."""

    def __init__(self, counts: dict[str, float] | None = None):
        self.counts = counts if counts is not None else {}

    def add(self, name: str, n: float = 1) -> None:
        self.counts[name] = self.counts.get(name, 0) + n


# ------------------------------------------------------------- envelope


def wrap(payload: bytes) -> bytes:
    """Prepend the integrity header to a payload."""
    payload = bytes(payload)
    return _HDR.pack(MAGIC, VERSION, 0, len(payload),
                     zlib.crc32(payload) & 0xFFFFFFFF) + payload


def unwrap(data: bytes, path: str = "<mem>") -> bytes:
    """Verify and strip the envelope; raises CorruptBlock on any damage."""
    if len(data) < HEADER_SIZE:
        raise CorruptBlock(path, f"short header ({len(data)} bytes)")
    magic, version, _flags, length, crc = _HDR.unpack_from(data)
    if magic != MAGIC:
        raise CorruptBlock(path, f"bad magic 0x{magic:08X}")
    if version != VERSION:
        raise CorruptBlock(path, f"unsupported envelope version {version}")
    payload = data[HEADER_SIZE:]
    if len(payload) != length:
        raise CorruptBlock(
            path, f"length mismatch: header {length}, got {len(payload)}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptBlock(path, "crc mismatch")
    return bytes(payload)


# ------------------------------------------------------ fault injection


def _flip_byte(data: bytes) -> bytes:
    """Deterministically flip one payload byte (middle of the payload
    region, so both crc and content checks see it)."""
    if not data:
        return data
    pos = HEADER_SIZE + max(0, (len(data) - HEADER_SIZE) // 2) \
        if len(data) > HEADER_SIZE else len(data) // 2
    pos = min(pos, len(data) - 1)
    b = bytearray(data)
    b[pos] ^= 0xFF
    return bytes(b)


def apply_write_faults(data: bytes, path_class: str | None) -> bytes:
    """Consult the disk-fault arms for one write: may raise OSError
    (EN_IO_ERROR) or return bytes corrupted the way a bad disk would
    persist them (the file on disk is then genuinely damaged, so every
    reader's corruption path and the scrubber are exercised for real)."""
    if ERRSIM.should_fire("EN_IO_ERROR", path_class):
        raise OSError(f"EN_IO_ERROR injected ({path_class})")
    if ERRSIM.should_fire("EN_DISK_BITFLIP", path_class):
        data = _flip_byte(data)
    if ERRSIM.should_fire("EN_DISK_TORN_WRITE", path_class):
        keep = HEADER_SIZE + max(1, (len(data) - HEADER_SIZE) // 2) \
            if len(data) > HEADER_SIZE + 1 else max(1, len(data) // 2)
        data = data[:keep]
    if ERRSIM.should_fire("EN_DISK_TRUNCATE", path_class):
        data = data[:max(0, len(data) - 8)]
    return data


def apply_read_faults(path: str, path_class: str | None) -> None:
    """Consult the disk-fault arms before one read: may raise OSError or
    persistently decay the on-disk file (bit rot / lost tail blocks) so
    detection, quarantine, and never-re-read semantics operate on a file
    that is actually bad."""
    if ERRSIM.should_fire("EN_IO_ERROR", path_class):
        raise OSError(f"EN_IO_ERROR injected ({path_class})")
    try:
        if ERRSIM.should_fire("EN_DISK_BITFLIP", path_class):
            with open(path, "r+b") as f:
                raw = f.read()
                if raw:
                    f.seek(0)
                    f.write(_flip_byte(raw))
        if ERRSIM.should_fire("EN_DISK_TRUNCATE", path_class):
            sz = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(0, sz - 8))
    except FileNotFoundError:
        pass


def _crash_point(name: str, path_class: str | None) -> None:
    if ERRSIM.should_fire(name, path_class):
        raise InjectedError(f"{name} ({path_class})")


# ------------------------------------------------------------ file I/O


def write_atomic(path: str, payload: bytes, fsync: bool = True,
                 path_class: str | None = None) -> None:
    """Envelope + tmp -> flush -> fsync -> rename -> fsync-dir. Crash
    points at every boundary let the crash-consistency harness kill the
    writer mid-sequence; a torn write is invisible (tmp never renamed)
    and a renamed file is complete."""
    data = apply_write_faults(wrap(payload), path_class)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    if ERRSIM.should_fire("EN_CRASH_TMP_PARTIAL", path_class):
        # die mid-write: a partial tmp file is left behind (never renamed,
        # so recovery must simply ignore it)
        with open(tmp, "wb") as f:
            f.write(data[:max(1, len(data) // 2)])
        raise InjectedError(f"EN_CRASH_TMP_PARTIAL ({path_class})")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    _crash_point("EN_CRASH_BEFORE_RENAME", path_class)
    os.replace(tmp, path)
    _crash_point("EN_CRASH_AFTER_RENAME", path_class)
    if fsync and d:
        try:
            dfd = os.open(d, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


def read_verified(path: str, path_class: str | None = None) -> bytes:
    """The single verified read path: FileNotFoundError means *missing*
    (a legitimate state, e.g. no checkpoint yet); CorruptBlock means the
    file exists but failed verification — the two are never conflated."""
    apply_read_faults(path, path_class)
    with open(path, "rb") as f:
        data = f.read()
    return unwrap(data, path)


def verify_file(path: str, path_class: str | None = None) -> int:
    """Scrubber entry point: verify one file's envelope, returning the
    payload length. Raises FileNotFoundError / CorruptBlock."""
    return len(read_verified(path, path_class))


def quarantine_file(path: str, reason: str = "") -> str | None:
    """Move a corrupt file into a sibling quarantine/ directory so it is
    kept for forensics but NEVER re-read on the hot path (re-reading a
    bad file on every boot/scan is the bug this exists to kill).
    Returns the quarantine path, or None when the move failed."""
    try:
        d = os.path.dirname(path) or "."
        qdir = os.path.join(d, QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        base = os.path.basename(path)
        dst = os.path.join(qdir, base)
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qdir, f"{base}.{n}")
        os.replace(path, dst)
        return dst
    except OSError:
        return None
