"""Host storage engine: LSM columnar storage feeding the TPU engine.

Layer map (SURVEY.md §2.3 -> rebuild):
  encoding.py    per-column codecs (native C++ + numpy twins)
  microblock.py  self-contained columnar block format
  sstable.py     immutable sorted runs w/ block index, zone maps, bloom
  memtable.py    MVCC mutable head (version chains, staged tx writes)
  scan_merge.py  snapshot fuse of memtables + sstables
  compaction.py  mini/minor/major merges
  tablet.py      the per-shard unit binding all of the above
"""

from .memtable import Memtable, WriteConflict
from .sstable import OP_DELETE, OP_PUT, SSTable, write_sstable
from .scan_merge import scan_merge
from .compaction import freeze_to_mini, major_compact, minor_compact
from .tablet import SnapshotDiscarded, Tablet

__all__ = [
    "Memtable",
    "WriteConflict",
    "SSTable",
    "write_sstable",
    "OP_PUT",
    "OP_DELETE",
    "scan_merge",
    "freeze_to_mini",
    "minor_compact",
    "major_compact",
    "Tablet",
    "SnapshotDiscarded",
]
