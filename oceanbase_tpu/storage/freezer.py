"""Tenant freezer + LSM maintenance orchestration.

Reference surface: ObTenantFreezer (storage/tx_storage/ob_tenant_freezer.h)
watches the tenant's memstore against its limit and freezes the busiest
memtables at the trigger ratio; ObTenantTabletScheduler
(storage/compaction/ob_tenant_tablet_scheduler.h:146) turns frozen
memtables and delta stacks into merge DAGs on the tenant dag scheduler.

The rebuild's MaintenanceService ties the same loop together over a set of
tablets: memstore accounting -> freeze -> MINI dag (dump frozen memtable)
-> MINOR dag when deltas pile up -> MAJOR dag on demand. `tick()` is
deterministic (tests / single-process); `start()` runs it on a timer.
"""

from __future__ import annotations

import threading

from ..share.dag_scheduler import Dag, DagPriority, TenantDagScheduler
from .tablet import Tablet


class TenantFreezer:
    """Memstore accounting + freeze triggering for one tenant."""

    def __init__(self, memstore_limit: int, trigger_ratio: float):
        self.memstore_limit = memstore_limit
        self.trigger_ratio = trigger_ratio
        self.freeze_count = 0

    def memstore_bytes(self, tablets: list[Tablet]) -> int:
        return sum(
            t.active.bytes_estimate + sum(m.bytes_estimate for m in t.frozen)
            for t in tablets
        )

    def should_freeze(self, tablets: list[Tablet]) -> bool:
        return self.memstore_bytes(tablets) >= (
            self.memstore_limit * self.trigger_ratio
        )

    def freeze_busiest(self, tablets: list[Tablet]) -> Tablet | None:
        """Freeze the tablet holding the most active-memtable memory (the
        reference freezes the top consumers until usage drops)."""
        busiest = max(
            tablets, key=lambda t: t.active.bytes_estimate, default=None
        )
        if busiest is None or busiest.active.nkeys == 0:
            return None
        busiest.freeze()
        self.freeze_count += 1
        return busiest


class MaintenanceService:
    """The freeze/compaction control loop over a set of tablets."""

    def __init__(self, dag_scheduler: TenantDagScheduler, config=None,
                 tablets_fn=None, snapshot_fn=None):
        """tablets_fn() -> list[Tablet]; snapshot_fn() -> current GTS (the
        major-compaction snapshot); config supplies memstore_limit /
        freeze_trigger_ratio / minor_compact_trigger (share/config)."""
        self.dags = dag_scheduler
        self.config = config
        self.tablets_fn = tablets_fn or (lambda: [])
        self.snapshot_fn = snapshot_fn or (lambda: 0)
        self._timer: threading.Timer | None = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------ params
    def _cfg(self, name: str, default):
        if self.config is None:
            return default
        return self.config[name]

    # -------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One control-loop pass: freeze if over trigger, schedule dumps
        for frozen memtables, minors for deep delta stacks. Returns what
        was scheduled (for tests/observability)."""
        tablets = list(self.tablets_fn())
        freezer = TenantFreezer(
            self._cfg("memstore_limit", 256 << 20),
            self._cfg("freeze_trigger_ratio", 0.5),
        )
        out = {"frozen": 0, "mini": 0, "minor": 0}
        # freezing moves bytes active -> frozen (total drops only at dump),
        # so bound the loop by the OVERSHOOT: freeze busiest tablets until
        # the frozen-and-dumpable mass covers it
        total = freezer.memstore_bytes(tablets)
        trigger = freezer.memstore_limit * freezer.trigger_ratio
        overshoot = total - trigger
        while overshoot > 0:
            busiest = max(
                tablets, key=lambda t: t.active.bytes_estimate, default=None
            )
            if busiest is None or busiest.active.nkeys == 0:
                break
            overshoot -= busiest.active.bytes_estimate
            freezer.freeze_busiest(tablets)
            out["frozen"] += 1
        minor_trigger = self._cfg("minor_compact_trigger", 2)
        for t in tablets:
            if t.frozen:
                if self.dags.add_dag(self._mini_dag(t)):
                    out["mini"] += 1
            if len(t.deltas) >= minor_trigger:
                if self.dags.add_dag(self._minor_dag(t)):
                    out["minor"] += 1
        return out

    def _mini_dag(self, t: Tablet) -> Dag:
        d = Dag("MINI_MERGE", DagPriority.MINI_MERGE, key=(t.tablet_id, "mini"))

        def dump():
            # a frozen memtable with staged-but-undecided rows must wait
            # for its writers (retried by a later tick)
            while t.frozen and not t.frozen[0].has_uncommitted:
                t.dump_mini()

        d.add_task(dump, "dump_frozen")
        return d

    def _minor_dag(self, t: Tablet) -> Dag:
        d = Dag("MINOR_MERGE", DagPriority.MINOR_MERGE,
                key=(t.tablet_id, "minor"))
        d.add_task(lambda: t.minor_compact(), "minor_compact")
        return d

    def schedule_major(self, t: Tablet) -> bool:
        """Major freeze entry (the RS major-freeze analog)."""
        d = Dag("MAJOR_MERGE", DagPriority.MAJOR_MERGE,
                key=(t.tablet_id, "major"))
        snapshot = self.snapshot_fn()
        d.add_task(lambda: t.major_compact(snapshot), "major_compact")
        return self.dags.add_dag(d)

    # --------------------------------------------------------- live mode
    def start(self, interval_s: float = 1.0) -> None:
        def loop():
            self.tick()
            self.dags.run_until_idle()
            with self._lock:
                if self._timer is not None:
                    self._timer = threading.Timer(interval_s, loop)
                    self._timer.daemon = True
                    self._timer.start()

        with self._lock:
            if self._timer is None:
                self._timer = threading.Timer(interval_s, loop)
                self._timer.daemon = True
                self._timer.start()

    def stop(self) -> None:
        with self._lock:
            t, self._timer = self._timer, None
        if t is not None:
            t.cancel()
