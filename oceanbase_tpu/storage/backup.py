"""Physical backup + restore (with log-archive PITR).

Reference surface: storage/backup + rootserver/backup (physical backup of
tablet data to object storage) and storage/restore + logservice/
restoreservice (restore a tenant from a backup set plus archived logs up
to a restore SCN).

Backup set layout under <root>/:
  meta.json                 backup_scn, table metadata (schema, key cols,
                            placement, dictionaries)
  <table>.sst               one full-snapshot sstable blob at backup_scn

restore_database() rebuilds a fresh cluster: recreate tables, install the
snapshot sstable as every replica's base, fast-forward GTS past the
backup SCN; with an archive root it then replays committed transactions
with backup_scn < commit_version <= restore_scn through the tablets
(point-in-time recovery).
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..core.dictionary import Dictionary
from ..core.dtypes import Field, Schema
from ..log.archive import ArchiveReader
from ..log.cdc import CdcClient, merge_streams
from ..sql.logical import _parse_type
from .sstable import (OP_DELETE, OP_PUT, SSTable, load_sstable,
                      save_sstable, write_sstable)


def backup_database(db, root: str) -> int:
    """Write a consistent full backup of every user table; returns the
    backup SCN."""
    os.makedirs(root, exist_ok=True)
    scn = db.cluster.gts.current()
    meta = {"backup_scn": scn, "tables": []}
    for name in sorted(db.tables):
        ti = db.tables[name]
        rep = db._leader_replica(ti)
        data = rep.tablets[ti.tablet_id].scan(scn)
        n = len(data[ti.schema.names()[0]]) if ti.schema.names() else 0
        # rows from scan_merge are rowkey-sorted — the sstable invariant
        blob = write_sstable(
            ti.schema, ti.key_cols, data,
            versions=np.full(n, scn, np.int64),
            ops=np.zeros(n, np.int8),
            base_version=0, end_version=scn,
        )
        from ..share.io_manager import GLOBAL_IO

        GLOBAL_IO.account("backup", len(blob))
        save_sstable(os.path.join(root, f"{name}.sst"), blob, fsync=False)
        meta["tables"].append({
            "name": name,
            "tablet_id": ti.tablet_id,  # archived redo references this id
            "fields": [
                (f.name, str(f.dtype), f.dtype.nullable)
                for f in ti.schema.fields
            ],
            "key_cols": list(ti.key_cols),
            "dicts": {c: d.values() for c, d in ti.dicts.items()},
            "rows": int(n),
        })
    from .integrity import BACKUP, write_atomic

    write_atomic(os.path.join(root, "meta.json"),
                 json.dumps(meta).encode(), fsync=False, path_class=BACKUP)
    return scn


def archive_database(db, archive_root: str) -> int:
    """Archive every LS's committed log (continuous-archive entry point)."""
    from ..log.archive import ArchiveWriter

    total = 0
    for ls_id, group in db.cluster.ls_groups.items():
        # any replica's committed prefix is valid; use the leader's
        node = db.location.leader(ls_id)
        palf = group[node].palf
        total += ArchiveWriter(archive_root, ls_id).archive_from(palf)
    return total


def restore_database(root: str, n_nodes: int = 3, n_ls: int = 2,
                     archive_root: str | None = None,
                     restore_scn: int | None = None):
    """Rebuild a Database from a backup set (+ optional archived-log PITR).

    Returns the restored Database. New writes get timestamps beyond the
    restored history (GTS fast-forward)."""
    from ..server.database import Database
    from .integrity import BACKUP, read_verified

    meta = json.loads(read_verified(
        os.path.join(root, "meta.json"), path_class=BACKUP))
    backup_scn = meta["backup_scn"]
    db = Database(n_nodes=n_nodes, n_ls=n_ls)

    # archived redo addresses ORIGINAL tablet ids; map them to the
    # restored placement
    old_to_new: dict[int, tuple] = {}
    for tmeta in meta["tables"]:
        fields = tuple(
            Field(n, _parse_type(t).with_nullable(nullable))
            for n, t, nullable in tmeta["fields"]
        )
        schema = Schema(fields)
        import oceanbase_tpu.sql.ast as A

        cols = tuple(
            A.ColumnDef(f.name, str(f.dtype), not f.dtype.nullable)
            for f in fields
        )
        db.create_table(A.CreateTable(
            tmeta["name"], cols, tuple(tmeta["key_cols"])))
        ti = db.tables[tmeta["name"]]
        for c, values in tmeta["dicts"].items():
            ti.dicts[c] = Dictionary(values)
            # codes inside the backup snapshot are already durable: the
            # first post-restore commit must not re-log the whole dict
            ti.logged_dict_len[c] = len(values)
        ss = load_sstable(os.path.join(root, f"{tmeta['name']}.sst"),
                          schema, ti.key_cols, cache=db.block_cache)
        blob = bytes(ss.buf)
        for rep in db.cluster.ls_groups[ti.ls_id].values():
            t = rep.tablets[ti.tablet_id]
            t.base = SSTable(blob, schema, ti.key_cols, cache=db.block_cache)
        ti.data_version += 1
        old_to_new[tmeta["tablet_id"]] = (ti, schema)

    db.cluster.gts.advance_to(backup_scn)
    # PRIMARY tablet id -> restored TableInfo: archived redo and standby
    # tailing (ha/standby.py) address original tablet ids
    db._restore_tablet_map = {old: ti for old, (ti, _s) in old_to_new.items()}
    db._restore_backup_scn = backup_scn

    if archive_root is not None:
        # PITR: replay archived commits in version order past the backup
        changes = []
        for ls_id in db.cluster.ls_groups:
            cdc = CdcClient(ls_id)
            changes.extend(cdc.poll_archive(ArchiveReader(archive_root, ls_id)))
        # pre-pass: collect ALL dictionary appends (commit order can differ
        # from code order — a later-committing tx may carry earlier codes;
        # applying by code keeps the mapping dense and order-independent.
        # Codes beyond restore_scn merely add unreferenced strings.)
        appends: dict[tuple[int, str], dict[int, str]] = {}
        for ch in changes:
            for tab_id, col, code, s in ch.dict_appends:
                appends.setdefault((tab_id, col), {})[code] = s
        for (tab_id, col), by_code in appends.items():
            hit = old_to_new.get(tab_id)
            if hit is None:
                continue
            d = hit[0].dicts[col]
            for code in sorted(by_code):
                if code == len(d):
                    d.encode_one(by_code[code])
                elif code < len(d) and d.decode_one(code) != by_code[code]:
                    raise IOError(
                        f"dictionary divergence at code {code} of {col}"
                    )
            hit[0].logged_dict_len[col] = max(
                hit[0].logged_dict_len.get(col, 0), len(d)
            )
        for ch in merge_streams(changes):
            if ch.commit_version <= backup_scn:
                continue  # already inside the backup snapshot
            if restore_scn is not None and ch.commit_version > restore_scn:
                continue
            for row in ch.rows:
                hit = old_to_new.get(row.tablet_id)
                if hit is None:
                    continue  # table not in the backup set
                ti, _schema = hit
                for rep in db.cluster.ls_groups[ti.ls_id].values():
                    rep.tablets[ti.tablet_id].active.replay(
                        row.key, OP_PUT if row.op == "put" else OP_DELETE,
                        row.values, ch.commit_version,
                    )
            db.cluster.gts.advance_to(ch.commit_version)
            ti_names = {old_to_new[r.tablet_id][0].name
                        for r in ch.rows if r.tablet_id in old_to_new}
            for nm in ti_names:
                db.tables[nm].data_version += 1

    return db
