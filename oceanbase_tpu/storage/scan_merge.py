"""Multi-source snapshot scan merge.

Reference surface: storage/access ObMultipleScanMerge / ObMultipleGetMerge
(ob_multiple_scan_merge.h) — fuse memtable + minor + major sstables under
MVCC into one row stream, resolving each rowkey to its newest committed
version <= the read snapshot and dropping delete tombstones.

The rebuild does the fuse as vectorized numpy (host control path): gather
candidate rows from every source, lexsort by (rowkey asc, version desc,
source recency desc), keep the first row per key, drop tombstones. Output
columns are sorted by rowkey — the order sstables want and a free property
for downstream merge algorithms.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import Schema, TypeKind
from .memtable import Memtable
from .sstable import OP_COL, OP_PUT, VERSION_COL, SSTable


def _memtable_arrays(
    mt: Memtable, schema: Schema, snapshot: int, tx_id: int
) -> dict[str, np.ndarray]:
    rows = mt.snapshot_rows(snapshot, tx_id)
    names = schema.names()

    def _empty(n):
        f = schema[n]
        if f.kind is TypeKind.VECTOR:
            return np.zeros((0, int(f.precision)), dtype=f.storage_np)
        return np.zeros(0, dtype=f.storage_np)

    if not rows:
        out = {n: _empty(n) for n in names}
        out[VERSION_COL] = np.zeros(0, np.int64)
        out[OP_COL] = np.zeros(0, np.int8)
        return out
    vals = list(rows.values())
    ops = np.array([op for op, _ in vals], dtype=np.int8)
    out = {}
    for ci, n in enumerate(names):
        dt = schema[n].storage_np
        key_pos = mt.key_cols.index(n) if n in mt.key_cols else -1
        if key_pos >= 0:
            out[n] = np.array([k[key_pos] for k in rows.keys()], dtype=dt)
        else:
            # a tombstone's filler must keep the cell's SHAPE: vector
            # cells are (d,) tuples, and a scalar 0 among them makes the
            # row list inhomogeneous
            fill = ((0.0,) * int(schema[n].precision)
                    if schema[n].kind is TypeKind.VECTOR else 0)
            out[n] = np.array(
                [v[ci] if op == OP_PUT else fill for op, v in vals],
                dtype=dt,
            )
    # staged rows of the reading tx are visible "infinitely new"
    out[VERSION_COL] = np.full(len(vals), np.iinfo(np.int64).max, np.int64)
    out[OP_COL] = ops
    return out


def scan_merge(
    schema: Schema,
    key_cols: list[str],
    sstables: list[SSTable],
    memtables: list[Memtable],
    snapshot: int,
    columns: list[str] | None = None,
    ranges: dict[str, tuple[float, float]] | None = None,
    tx_id: int = 0,
) -> dict[str, np.ndarray]:
    """Fused snapshot read.

    sstables/memtables ordered oldest -> newest. Zone-map pruning: ranges on
    KEY columns are always safe (a key either qualifies in every source or in
    none, so pruning cannot resurrect a stale version); ranges on value
    columns are applied only when exactly one non-empty source exists — with
    deltas present, pruning a base block on a value predicate could hide the
    base version of a key whose delta row fails the predicate.
    """
    names = columns if columns is not None else schema.names()
    need = list(dict.fromkeys(list(key_cols) + list(names)))
    live_memtables = [m for m in memtables if m.nkeys > 0]
    single_source = (len(sstables) + len(live_memtables)) == 1
    key_ranges = (
        {c: r for c, r in ranges.items() if c in key_cols} if ranges else None
    )
    parts: list[dict[str, np.ndarray]] = []
    ranks: list[np.ndarray] = []
    rank = 0
    for st in sstables:
        got = st.scan(need, ranges=ranges if single_source else key_ranges)
        mask = got[VERSION_COL] <= snapshot
        if not mask.all():
            got = {c: a[mask] for c, a in got.items()}
        parts.append(got)
        ranks.append(np.full(len(got[VERSION_COL]), rank, np.int32))
        rank += 1
    for mt in memtables:
        got = _memtable_arrays(mt, schema, snapshot, tx_id)
        if need != schema.names():
            got = {c: got[c] for c in need + [VERSION_COL, OP_COL]}
        parts.append(got)
        ranks.append(np.full(len(got[VERSION_COL]), rank, np.int32))
        rank += 1

    if not parts:
        return {n: np.zeros(0, dtype=schema[n].storage_np) for n in names}

    cat = {c: np.concatenate([p[c] for p in parts]) for c in need + [VERSION_COL, OP_COL]}
    rank_arr = np.concatenate(ranks) if ranks else np.zeros(0, np.int32)
    n = len(rank_arr)
    if n == 0:
        return {c: cat[c] for c in names}

    keys2d = np.stack([cat[k].astype(np.int64) for k in key_cols], axis=1)
    # lexsort: last key is primary -> (key0, key1, ..., -version, -rank)
    sort_keys = (-rank_arr, -cat[VERSION_COL]) + tuple(
        keys2d[:, j] for j in range(keys2d.shape[1] - 1, -1, -1)
    )
    order = np.lexsort(sort_keys)
    sorted_keys = keys2d[order]
    first = np.ones(n, dtype=bool)
    if n > 1:
        first[1:] = (sorted_keys[1:] != sorted_keys[:-1]).any(axis=1)
    keep = order[first & (cat[OP_COL][order] == OP_PUT)]
    return {c: cat[c][keep] for c in names}
