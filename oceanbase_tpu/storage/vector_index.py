"""IVF-flat vector index: ANN search as two rounds of matmul + top-k.

Reference surface: src/storage/vector_index (obvec's IVF/HNSW index
tables) and the ANN DAS iterators (src/sql/das/iter/ob_das_vec_*). The
reference walks graph/list structures pointer by pointer; the TPU
redesign picks the ONE ANN family whose probe is pure dense algebra:

  build:  k-means over the column (assignment = argmin of an (n, L)
          distance matmul — MXU work; centroid update = segment means)
  layout: rows permuted cluster-contiguous (perm), one offset per list —
          the same clustered-layout trick the engine uses everywhere
          (sorted projections, clustered-FK ranges)
  probe:  q @ centroids -> top-nprobe lists -> gather their contiguous
          row windows -> candidates @ q -> top-k.  Two matmuls, two
          top-ks, one gather: everything the MXU/VPU like.

The index is a derived structure cached like device columns: the
executor rebuilds it when the table version bumps (DML maintenance =
invalidate + lazy rebuild, the same contract as sorted projections and
fk_ranges; incremental list-append is a noted future refinement).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class IvfSpec:
    """Registration of a vector index on a Table (survives catalog
    snapshots via re-registration; the built artifact is cached in the
    executor keyed by table version)."""

    column: str
    lists: int = 0       # 0 = auto (~sqrt(n), power-of-two clamped)
    nprobe: int = 8


@dataclass
class IvfIndex:
    centroids: np.ndarray   # (L, d) float32
    perm: np.ndarray        # (n,) int32 — rows in cluster-contiguous order
    offsets: np.ndarray     # (L,) int32 — start of each list in perm
    lengths: np.ndarray     # (L,) int32
    max_list: int           # static per-list read window


import functools

import jax
import jax.numpy as jnp


@jax.jit
def _kmeans_assign(xd, cd):
    # ||x-c||^2 = ||x||^2 - 2 x.c + ||c||^2; argmin drops ||x||^2
    d2 = -2.0 * (xd @ cd.T) + jnp.sum(cd * cd, axis=1)[None, :]
    return jnp.argmin(d2, axis=1)


@functools.partial(jax.jit, static_argnums=2)
def _kmeans_update(xd, a_dev, L):
    # segment means on device: one scatter-add per iteration beats a
    # host np.add.at sweep by orders of magnitude at 1M x 128
    sums = jax.ops.segment_sum(xd, a_dev, num_segments=L)
    cnt = jax.ops.segment_sum(
        jnp.ones(xd.shape[0], jnp.float32), a_dev, num_segments=L)
    return sums, cnt


def _auto_lists(n: int) -> int:
    L = 1
    while L * L < n:
        L *= 2
    return max(4, min(L, 4096))


def build_ivf(x: np.ndarray, lists: int = 0, iters: int = 10,
              seed: int = 0) -> IvfIndex:
    """k-means build on device (jnp) — assignment distance matrices are
    matmuls, so a 1M x 128d build is sub-second on a v5e chip and still
    tractable on CPU test shapes."""
    x = np.asarray(x, dtype=np.float32)
    n, d = x.shape
    L = lists or _auto_lists(n)
    L = min(L, n)
    rng = np.random.default_rng(seed)
    cent = x[rng.choice(n, size=L, replace=False)].copy()

    # the data matrix rides as a jit ARGUMENT, never a closure capture: a
    # captured array becomes a program constant and the remote-compile
    # request would carry the whole 512MB (observed HTTP 413 at 1M x 128)
    xd = jnp.asarray(x)

    a = np.asarray(_kmeans_assign(xd, jnp.asarray(cent)))
    for _ in range(iters):
        sums, cnt = (
            np.asarray(v) for v in _kmeans_update(xd, jnp.asarray(a), L)
        )
        nonempty = cnt > 0
        cent[nonempty] = (
            sums[nonempty] / cnt[nonempty, None]).astype(np.float32)
        # re-seed empty clusters from random points
        for li in np.nonzero(~nonempty)[0]:
            cent[li] = x[rng.integers(0, n)]
        a2 = np.asarray(_kmeans_assign(xd, jnp.asarray(cent)))
        if np.array_equal(a2, a):
            a = a2
            break
        a = a2

    perm = np.argsort(a, kind="stable").astype(np.int32)
    lengths = np.bincount(a, minlength=L).astype(np.int32)
    offsets = np.concatenate(
        [[0], np.cumsum(lengths)[:-1]]).astype(np.int32)
    return IvfIndex(
        centroids=cent,
        perm=perm,
        offsets=offsets,
        lengths=lengths,
        max_list=int(lengths.max()) if L else 0,
    )


def register_vector_index(catalog, table: str, column: str,
                          lists: int = 0, nprobe: int = 8) -> None:
    """CREATE VECTOR INDEX surface: registers the spec on the Table; the
    executor builds (and version-caches) the artifact on first use."""
    t = catalog[table]
    t.vector_indexes = {
        **getattr(t, "vector_indexes", {}),
        column: IvfSpec(column, lists, nprobe),
    }


def drop_vector_index(catalog, table: str, column: str) -> None:
    t = catalog[table]
    vi = dict(getattr(t, "vector_indexes", {}))
    vi.pop(column, None)
    t.vector_indexes = vi
