"""Micro-block: the unit of columnar storage and decode.

Reference surface: OceanBase micro blocks (~16KB units inside 2MB macro
blocks, storage/blocksstable/ob_imicro_block_reader.h) whose readers decode
per-column streams directly into expression vectors (get_rows,
ob_imicro_block_reader.h:506-552). Here a micro block is a self-contained
byte string: header + per-column descriptors + encoded streams + crc32
trailer; the reader decodes whole columns into numpy arrays (the host half
of the device marshalling boundary — see core/table.py).

Layout (little-endian):
  u32 magic 0x0B5EB10C | u16 version | u16 ncols | u32 nrows | u32 reserved
  ncols * ColumnDesc {
     u8 enc | u8 dtype_code | u8 flags(bit0 has_nulls) | u8 for_width
     i64 for_min
     u32 data_off | u32 data_len | u32 null_off | u32 null_len
  }
  payload streams...
  u32 crc32 (over everything before the trailer)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from . import encoding as enc

MAGIC = 0x0B5EB10C
# compressed-wrapper frame: u32 magic | u32 raw_len | deflate payload —
# the reference wraps ENCODED micro blocks in a general-purpose block
# compressor (lz4/zstd/snappy, deps/oblib/src/lib/compress); this image
# ships zlib, and the wrapper composes with (never replaces) the
# lightweight per-column encodings, exactly like the reference
MAGIC_COMPRESSED = 0x0B5EB10D
VERSION = 1
_HEADER = struct.Struct("<IHHII")
_CHEADER = struct.Struct("<II")
_COLDESC = struct.Struct("<BBBBqIIII")

# dtype codes on the wire
_DTYPE_CODES: dict[np.dtype, int] = {
    np.dtype(np.bool_): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int16): 2,
    np.dtype(np.int32): 3,
    np.dtype(np.int64): 4,
    np.dtype(np.float32): 5,
    np.dtype(np.float64): 6,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}

DEFAULT_BLOCK_ROWS = 16384


@dataclass(frozen=True)
class ColumnZone:
    """Zone map entry: min/max over the block (ints: value; floats: raw)."""

    vmin: float
    vmax: float


def write_block(
    columns: list[np.ndarray], valids: list[np.ndarray | None],
    compress: bool = True, hints: list | None = None,
) -> tuple[bytes, list[ColumnZone]]:
    """Encode one micro block; returns (bytes, per-column zone maps).
    `hints` (aligned to `columns`) carries per-column advisor encoding
    preferences — honored when lossless for the block, else the cost
    model decides as usual."""
    nrows = len(columns[0]) if columns else 0
    descs = []
    streams: list[bytes] = []
    zones: list[ColumnZone] = []
    pos = 0
    for i, (a, valid) in enumerate(zip(columns, valids)):
        a = np.ascontiguousarray(a)
        hint = hints[i] if hints is not None else None
        if a.dtype == np.bool_:
            a8 = a.astype(np.int8)
            stats = enc.analyze_ints(a8)
            picked = enc.hinted_encoding(a8, stats, hint) if hint else None
            e, params = picked or enc.choose_encoding(a8, stats)
            data = enc.encode_column(a8, e, params)
            zones.append(ColumnZone(stats.vmin, stats.vmax))
        elif np.issubdtype(a.dtype, np.integer):
            stats = enc.analyze_ints(a)
            picked = enc.hinted_encoding(a, stats, hint) if hint else None
            e, params = picked or enc.choose_encoding(a, stats)
            data = enc.encode_column(a, e, params)
            zones.append(ColumnZone(stats.vmin, stats.vmax))
        else:
            e, params = enc.choose_encoding(a, enc.ColumnStats(0, 0, 0))
            data = enc.encode_column(a, e, params)
            if nrows:
                zones.append(ColumnZone(float(a.min()), float(a.max())))
            else:
                zones.append(ColumnZone(0.0, 0.0))
        has_nulls = valid is not None and not bool(valid.all())
        nulls = enc.pack_validity(valid) if has_nulls else b""
        descs.append(
            (
                e,
                _DTYPE_CODES[a.dtype if a.dtype != np.bool_ else np.dtype(np.int8)],
                1 if has_nulls else 0,
                params.get("width", 0),
                params.get("min", 0),
                pos,
                len(data),
                pos + len(data) if has_nulls else 0,
                len(nulls),
            )
        )
        streams.append(data)
        if has_nulls:
            streams.append(nulls)
            pos += len(data) + len(nulls)
        else:
            pos += len(data)
    out = bytearray()
    out += _HEADER.pack(MAGIC, VERSION, len(columns), nrows, 0)
    for d in descs:
        out += _COLDESC.pack(*d)
    for s in streams:
        out += s
    out += struct.pack("<I", enc.crc32(bytes(out)))
    raw = bytes(out)
    if compress:
        import zlib

        packed = zlib.compress(raw, 1)
        # only keep the wrapper when it actually saves space (already-
        # tight encodings often don't deflate further)
        if len(packed) + _CHEADER.size < int(len(raw) * 0.9):
            return (
                _CHEADER.pack(MAGIC_COMPRESSED, len(raw)) + packed,
                zones,
            )
    return raw, zones


@dataclass
class BlockReader:
    """Parsed block header; decodes columns lazily by index."""

    buf: memoryview
    nrows: int
    ncols: int
    _descs: list[tuple]
    _payload_off: int

    @staticmethod
    def open(buf: bytes | memoryview, verify: bool = True) -> "BlockReader":
        mv = memoryview(buf)
        magic2, raw_len = _CHEADER.unpack_from(mv, 0)
        if magic2 == MAGIC_COMPRESSED:
            import zlib

            try:
                raw = zlib.decompress(bytes(mv[_CHEADER.size:]))
            except zlib.error as e:  # corruption surfaces uniformly
                raise ValueError(f"micro-block decompress failed: {e}")
            if len(raw) != raw_len:
                raise ValueError("micro-block decompressed length mismatch")
            mv = memoryview(raw)
        magic, version, ncols, nrows, _ = _HEADER.unpack_from(mv, 0)
        if magic != MAGIC:
            raise ValueError(f"bad micro-block magic 0x{magic:08X}")
        if version != VERSION:
            raise ValueError(f"unsupported micro-block version {version}")
        if verify:
            (crc,) = struct.unpack_from("<I", mv, len(mv) - 4)
            if enc.crc32(bytes(mv[:-4])) != crc:
                raise ValueError("micro-block crc mismatch")
        descs = []
        off = _HEADER.size
        for _ in range(ncols):
            descs.append(_COLDESC.unpack_from(mv, off))
            off += _COLDESC.size
        return BlockReader(mv, nrows, ncols, descs, off)

    def column(self, i: int, as_bool: bool = False) -> tuple[np.ndarray, np.ndarray | None]:
        """Decode column i -> (values, validity-or-None)."""
        (e, dcode, flags, width, vmin, doff, dlen, noff, nlen) = self._descs[i]
        dtype = _CODE_DTYPES[dcode]
        start = self._payload_off + doff
        data = self.buf[start : start + dlen]
        params = {"min": vmin, "width": width} if e == enc.ENC_FOR else {}
        vals = enc.decode_column(data, e, params, dtype, self.nrows)
        if as_bool:
            vals = vals.astype(np.bool_)
        valid = None
        if flags & 1:
            nstart = self._payload_off + noff
            valid = enc.unpack_validity(self.buf[nstart : nstart + nlen], self.nrows)
        return vals, valid
