"""Background storage scrubber: proactive at-rest verification + typed
repair.

Reference surface: OceanBase's background macroblock inspector — data at
rest is re-verified on a cadence so bit rot is found by the scrubber,
not by the unlucky query that reads the block years later. Repair is
typed by artifact:

    checkpoint      quarantine the bad copy, rewrite a fresh snapshot
                    from the live replica ("checkpoint rewrites"); when
                    the replica's node is down too, fall back to a full
                    replica rebuild from a healthy peer (ha/rebuild.py,
                    "replica repairs")
    node meta       quarantine + rewrite from the live catalog
    sstable         an in-memory block whose payload crc fails means the
                    replica's storage is untrustworthy -> rebuild from a
                    healthy peer
    plan artifact   quarantine + drop the index entry; the next
                    statement recompiles (never a wrong answer)
    backup          quarantine only — there is nothing to regenerate a
                    backup from, so it stays UNREPAIRED and drives the
                    storage_corruption sentinel alert to critical

Scheduling: a BACKGROUND dag on the tenant dag scheduler, queued from
run_maintenance() when ob_scrub_interval elapsed (0 = off). Every file
visited counts "blocks scrubbed"; every verification failure counts
"checksum failures"; quarantines and repairs have their own counters and
all of it surfaces in __all_virtual_storage_integrity and AWR snapshots.

A quarantined file is NEVER re-read: it moves into a sibling
quarantine/ directory on first failure, so a scrub pass over a clean
tree reports zero failures — the pass after a corruption event proves
the repair actually converged.
"""

from __future__ import annotations

import os
import time

from .integrity import (ARTIFACT, BACKUP, CKPT, META, SSTABLE,
                        CorruptBlock, QUARANTINE_DIR, quarantine_file,
                        verify_file)

#: per-class accounting row shape (also the VT row shape)
_CLASSES = (CKPT, META, ARTIFACT, SSTABLE, BACKUP)


class StorageScrubber:
    """One tenant's scrubber; owns the pass loop and repair dispatch."""

    def __init__(self, db):
        self.db = db
        self.passes = 0
        self.last_pass_at: float | None = None
        #: extra roots to verify (backup sets registered by backup tools)
        self.backup_roots: list[str] = []
        self.by_class: dict[str, dict[str, int]] = {
            c: {"scrubbed": 0, "failures": 0, "quarantined": 0,
                "repaired": 0, "unrepaired": 0}
            for c in _CLASSES
        }
        #: (path_class, quarantine path, reason) — forensics surface
        self.quarantined: list[tuple[str, str, str]] = []

    # ---------------------------------------------------------- counting
    def _count(self, name: str, n: int = 1) -> None:
        m = getattr(self.db, "metrics", None)
        if m is not None:
            m.add(name, n)

    def _scrubbed(self, cls: str, n: int = 1) -> None:
        self.by_class[cls]["scrubbed"] += n
        self._count("blocks scrubbed", n)

    def _failed(self, cls: str) -> None:
        self.by_class[cls]["failures"] += 1
        self._count("checksum failures")

    def _quarantined(self, cls: str, qpath: str | None, reason: str) -> None:
        self.by_class[cls]["quarantined"] += 1
        self._count("quarantined files")
        if qpath:
            self.quarantined.append((cls, qpath, reason))

    def _repaired(self, cls: str) -> None:
        self.by_class[cls]["repaired"] += 1

    def _unrepaired(self, cls: str) -> None:
        self.by_class[cls]["unrepaired"] += 1

    # ------------------------------------------------------------ driver
    def maybe_queue(self) -> bool:
        """Queue one scrub pass as a BACKGROUND dag when the interval
        elapsed (dag key dedups a still-queued pass)."""
        try:
            interval = float(self.db.config["ob_scrub_interval"])
        except Exception:
            return False
        if interval <= 0:
            return False
        now = time.monotonic()
        if self.last_pass_at is not None \
                and now - self.last_pass_at < interval:
            return False
        from ..share.dag_scheduler import Dag, DagPriority

        dag = Dag("storage scrub", DagPriority.BACKGROUND,
                  key=("storage scrub",))
        dag.add_task(self.run_pass, name="scrub pass")
        self.db.dag_scheduler.add_dag(dag)
        return True

    def run_pass(self) -> dict:
        """One full verification sweep over every durable artifact class.
        Returns this pass's failure/repair tally (also folded into the
        cumulative stats the VT and AWR read)."""
        before = {c: dict(v) for c, v in self.by_class.items()}
        self._scrub_node_meta()
        self._scrub_checkpoints()
        self._scrub_sstables()
        self._scrub_plan_artifacts()
        self._scrub_backups()
        self.passes += 1
        self.last_pass_at = time.monotonic()
        delta = {
            c: {k: self.by_class[c][k] - before[c][k]
                for k in self.by_class[c]}
            for c in self.by_class
        }
        return {"pass": self.passes, "delta": delta}

    # ----------------------------------------------------------- targets
    def _scrub_node_meta(self) -> None:
        db = self.db
        if db.data_dir is None:
            return
        base = db._meta_path()
        bad = False
        for p in (base, base + ".prev"):
            if not os.path.exists(p):
                continue
            try:
                verify_file(p, META)
                self._scrubbed(META)
            except FileNotFoundError:
                continue
            except CorruptBlock as e:
                self._scrubbed(META)
                self._failed(META)
                self._quarantined(META, quarantine_file(p, e.reason),
                                  e.reason)
                bad = True
        if bad:
            # the live catalog is authoritative: rewrite the snapshot
            # (write rotates the surviving copy into .prev)
            try:
                db._save_node_meta()
                self._count("node meta rewrites")
                self._repaired(META)
            except Exception:
                self._unrepaired(META)

    def _scrub_checkpoints(self) -> None:
        db = self.db
        if db.data_dir is None:
            return
        from .ckpt import write_ls_checkpoint

        for ls_id, group in db.cluster.ls_groups.items():
            for node, rep in group.items():
                base = db._ckpt_path(node, ls_id)
                bad = False
                for p in (base, base + ".prev"):
                    if not os.path.exists(p):
                        continue
                    try:
                        verify_file(p, CKPT)
                        self._scrubbed(CKPT)
                    except FileNotFoundError:
                        continue
                    except CorruptBlock as e:
                        self._scrubbed(CKPT)
                        self._failed(CKPT)
                        self._quarantined(
                            CKPT, quarantine_file(p, e.reason), e.reason)
                        bad = True
                if not bad:
                    continue
                # typed repair: the live replica IS the data — cut a
                # fresh snapshot over the quarantined one
                try:
                    covered = write_ls_checkpoint(base, rep,
                                                  fsync=db._fsync)
                except Exception:
                    covered = None
                if covered is not None:
                    self._count("checkpoint rewrites")
                    self._repaired(CKPT)
                elif self._rebuild(ls_id, node):
                    self._repaired(CKPT)
                else:
                    self._unrepaired(CKPT)

    def _scrub_sstables(self) -> None:
        """Deep verify: every replica's resident sstable payload crc (the
        at-rest envelope covers the file; this covers the block bytes a
        checkpoint pickled). A failed replica-local block means that
        replica's storage lies -> rebuild it from a healthy peer."""
        db = self.db
        for ls_id, group in db.cluster.ls_groups.items():
            for node, rep in group.items():
                ok = True
                for t in rep.tablets.values():
                    tables = list(t.deltas)
                    if t.base is not None:
                        tables.append(t.base)
                    for ss in tables:
                        self._scrubbed(SSTABLE)
                        if not ss.verify():
                            self._failed(SSTABLE)
                            ok = False
                if not ok:
                    if self._rebuild(ls_id, node):
                        self._repaired(SSTABLE)
                    else:
                        self._unrepaired(SSTABLE)

    def _scrub_plan_artifacts(self) -> None:
        pa = getattr(self.db, "plan_artifact", None)
        if pa is None or not os.path.isdir(pa.root):
            return
        idx = pa._index_path()
        for name in sorted(os.listdir(pa.root)):
            path = os.path.join(pa.root, name)
            if not os.path.isfile(path) or ".tmp" in name:
                continue  # xla/ + quarantine/ subdirs, in-flight tmps
            try:
                verify_file(path, ARTIFACT)
                self._scrubbed(ARTIFACT)
                continue
            except FileNotFoundError:
                continue
            except CorruptBlock as e:
                self._scrubbed(ARTIFACT)
                self._failed(ARTIFACT)
                reason = e.reason
            # aid = filename up to the first dot ("<aid>.meta",
            # "<aid>.x", "<aid>.b<K>.x"); the index file quarantines
            # through the store too (it restarts empty)
            if path == idx:
                self._quarantined(ARTIFACT, quarantine_file(path, reason),
                                  reason)
                with pa._lock:
                    pa._index["entries"] = {}
                    pa._save_index()
                self._count("plan artifact quarantined")
                self._repaired(ARTIFACT)
                continue
            aid = name.split(".", 1)[0]
            pa.quarantine(aid, path, reason)
            self._quarantined(ARTIFACT, None, reason)
            # artifacts are recomputable: quarantine IS the repair (the
            # next statement honestly recompiles)
            self._repaired(ARTIFACT)

    def _scrub_backups(self) -> None:
        for root in list(self.backup_roots):
            if not os.path.isdir(root):
                continue
            for name in sorted(os.listdir(root)):
                path = os.path.join(root, name)
                if not os.path.isfile(path) or ".tmp" in name:
                    continue
                cls = BACKUP if name == "meta.json" else SSTABLE
                try:
                    verify_file(path, cls)
                    self._scrubbed(BACKUP)
                except FileNotFoundError:
                    continue
                except CorruptBlock as e:
                    self._scrubbed(BACKUP)
                    self._failed(BACKUP)
                    self._quarantined(
                        BACKUP, quarantine_file(path, e.reason), e.reason)
                    # nothing regenerates a backup set: stays unrepaired
                    # (the sentinel escalates to critical on this)
                    self._unrepaired(BACKUP)

    # ------------------------------------------------------------ repair
    def _rebuild(self, ls_id: int, node: int) -> bool:
        """Last-resort typed repair: wipe + resync one replica from a
        healthy peer (ha/rebuild.py)."""
        db = self.db
        try:
            from ..ha.rebuild import rebuild_replica

            rebuild_replica(db.cluster, ls_id, node,
                            data_dir=db.data_dir, fsync=db._fsync)
        except Exception:
            return False
        self._count("replica repairs")
        return True

    # ------------------------------------------------------------- stats
    def unrepaired_total(self) -> int:
        return sum(v["unrepaired"] for v in self.by_class.values())

    def stats(self) -> dict:
        """Cumulative scrub state for the VT, AWR snapshots and the
        sentinel's corruption rule."""
        return {
            "passes": self.passes,
            "last_pass_at": self.last_pass_at,
            "by_class": {c: dict(v) for c, v in self.by_class.items()},
            "quarantined": list(self.quarantined),
            "unrepaired": self.unrepaired_total(),
        }


def find_quarantined(root: str) -> list[str]:
    """Every quarantined file under a tree (diagnostics helper)."""
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        if os.path.basename(dirpath) == QUARANTINE_DIR:
            out.extend(os.path.join(dirpath, f) for f in sorted(filenames))
            dirnames[:] = []
    return out
