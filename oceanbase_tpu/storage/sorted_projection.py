"""Sorted projections: a covering secondary index materialized columnar.

Reference surface: ObTableSchema index tables + the ordered index-back
scan path (src/sql/das/ob_das_scan_op.h, storage index sstables laid out
in index-key order). The reference answers a selective range predicate by
walking an ordered index and looking rows back; the TPU redesign
materializes the index WITH its included columns as a second
column-ordered table (no row-ids, no look-back gathers) so a range
predicate becomes a contiguous device slice — the scan reads exactly the
qualifying rows instead of masking a full-table pass. TPC-H-legal for
date columns (clause 1.5.4 allows indexes on date attributes); the bench
builds one on lineitem.l_shipdate.

DML on the base table drops its projections (Database.invalidate path):
they are rebuilt on demand, the same contract as the device batch cache.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import Schema
from ..core.table import Table


def projection_name(table: str, key_col: str) -> str:
    return f"{table}#sp:{key_col}"


def make_sorted_projection(
    catalog, table: str, key_col: str, cols: list[str] | None = None
) -> str:
    """Materialize `table` re-ordered by `key_col` (stable) into the
    catalog under projection_name(); registers it on the base Table's
    `sorted_projections` map, which the executor's scan router consults.
    `cols` limits the covered columns (default: all)."""
    t = catalog[table]
    names = [f.name for f in t.schema.fields]
    keep = list(cols) if cols is not None else list(names)
    if key_col not in keep:
        keep.append(key_col)
    keep = [n for n in names if n in keep]  # schema order
    order = np.argsort(t.data[key_col], kind="stable")
    data = {c: np.ascontiguousarray(t.data[c][order]) for c in keep}
    valid = {c: np.ascontiguousarray(t.valid[c][order])
             for c in t.valid if c in keep}
    sub_schema = Schema(tuple(f for f in t.schema.fields if f.name in keep))
    pname = projection_name(table, key_col)
    catalog[pname] = Table(
        pname, sub_schema, data,
        {c: d for c, d in t.dicts.items() if c in keep}, valid,
    )
    t.sorted_projections = {
        **getattr(t, "sorted_projections", {}), key_col: pname
    }
    return pname


def drop_projections(catalog, table: str) -> None:
    """Remove every sorted projection of `table` (base data changed)."""
    t = catalog[table]
    projs = getattr(t, "sorted_projections", None)
    if not projs:
        return
    for pname in projs.values():
        try:
            del catalog[pname]
        except (KeyError, TypeError):
            pass
    t.sorted_projections = {}
