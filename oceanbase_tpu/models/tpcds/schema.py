"""TPC-DS star-schema subset: the dimensional core the star-join suite
needs (BASELINE config 5). Fact table store_sales plus the three
dimensions the classic brand/star queries (Q3/Q42/Q52/Q55) touch.

Column types follow the TPC-DS spec (surrogate int keys, decimal money);
names keep the spec's prefixes so the public query texts run unmodified."""

from ...core.dtypes import DataType as D, Schema

DATE_DIM = Schema.of(
    d_date_sk=D.int64(),
    d_date=D.date(),
    d_year=D.int32(),
    d_moy=D.int32(),
    d_dom=D.int32(),
)

ITEM = Schema.of(
    i_item_sk=D.int64(),
    i_brand_id=D.int32(),
    i_brand=D.varchar(),
    i_manufact_id=D.int32(),
    i_category_id=D.int32(),
    i_category=D.varchar(),
    i_manager_id=D.int32(),
)

STORE = Schema.of(
    s_store_sk=D.int64(),
    s_store_name=D.varchar(),
    s_state=D.varchar(),
)

STORE_SALES = Schema.of(
    ss_sold_date_sk=D.int64(),
    ss_item_sk=D.int64(),
    ss_store_sk=D.int64(),
    ss_customer_sk=D.int64(),
    ss_quantity=D.int32(),
    ss_ext_sales_price=D.decimal(12, 2),
    ss_net_profit=D.decimal(12, 2),
)

TABLES = {
    "date_dim": DATE_DIM,
    "item": ITEM,
    "store": STORE,
    "store_sales": STORE_SALES,
}
