"""TPC-DS star-join query texts (public TPC-DS specification queries,
restricted to the star-schema subset in schema.py).

Q3 / Q42 / Q52 / Q55 are the classic brand/star shape: fact scan with a
selective dimension filter, two-or-three-way star join, wide GROUP BY,
ORDER BY ... LIMIT 100 — BASELINE config 5's "multi-way hash join, wide
GROUP BY" surface."""

UNIQUE_KEYS = {
    "date_dim": ("d_date_sk",),
    "item": ("i_item_sk",),
    "store": ("s_store_sk",),
}

QUERIES = {
    3: """
        select dt.d_year, item.i_brand_id as brand_id, item.i_brand as brand,
               sum(ss.ss_ext_sales_price) as sum_agg
        from date_dim dt, store_sales ss, item
        where dt.d_date_sk = ss.ss_sold_date_sk
          and ss.ss_item_sk = item.i_item_sk
          and item.i_manufact_id = 128
          and dt.d_moy = 11
        group by dt.d_year, item.i_brand_id, item.i_brand
        order by dt.d_year, sum_agg desc, brand_id
        limit 100
    """,
    42: """
        select dt.d_year, item.i_category_id, item.i_category,
               sum(ss.ss_ext_sales_price) as s
        from date_dim dt, store_sales ss, item
        where dt.d_date_sk = ss.ss_sold_date_sk
          and ss.ss_item_sk = item.i_item_sk
          and item.i_manager_id = 1
          and dt.d_moy = 11
          and dt.d_year = 2000
        group by dt.d_year, item.i_category_id, item.i_category
        order by s desc, dt.d_year, item.i_category_id, item.i_category
        limit 100
    """,
    52: """
        select dt.d_year, item.i_brand_id as brand_id, item.i_brand as brand,
               sum(ss.ss_ext_sales_price) as ext_price
        from date_dim dt, store_sales ss, item
        where dt.d_date_sk = ss.ss_sold_date_sk
          and ss.ss_item_sk = item.i_item_sk
          and item.i_manager_id = 1
          and dt.d_moy = 11
          and dt.d_year = 2000
        group by dt.d_year, item.i_brand_id, item.i_brand
        order by dt.d_year, ext_price desc, brand_id
        limit 100
    """,
    55: """
        select item.i_brand_id as brand_id, item.i_brand as brand,
               sum(ss.ss_ext_sales_price) as ext_price
        from date_dim dt, store_sales ss, item
        where dt.d_date_sk = ss.ss_sold_date_sk
          and ss.ss_item_sk = item.i_item_sk
          and item.i_manager_id = 28
          and dt.d_moy = 11
          and dt.d_year = 1999
        group by item.i_brand_id, item.i_brand
        order by ext_price desc, brand_id
        limit 100
    """,
}
