from . import datagen, schema
from .sql_suite import QUERIES, UNIQUE_KEYS

__all__ = ["datagen", "schema", "QUERIES", "UNIQUE_KEYS"]
