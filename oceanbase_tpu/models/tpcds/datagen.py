"""Spec-shaped TPC-DS subset generator (vectorized numpy, no dsdgen).

Generates the star-schema core (date_dim / item / store / store_sales)
with the distributions the star-join queries rely on: a calendar spanning
1998-2002 with correct year/month/day breakdowns, items carrying
brand/manufacturer/category hierarchies, and a fact table whose foreign
keys are drawn non-uniformly (sales skew toward Q4 / popular items) so
group-bys and joins see realistic distributions.

`sf` scales the fact-table row count like dsdgen's scale factor:
sf=1 -> ~2.88M store_sales rows (the spec's ratio for SF1)."""

from __future__ import annotations

import numpy as np

from ...core.table import Table
from . import schema as S

EPOCH = np.datetime64("1970-01-01", "D")
CAL_START = np.datetime64("1998-01-01", "D")
CAL_END = np.datetime64("2002-12-31", "D")

CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]
STATES = ["TN", "CA", "TX", "WA", "NY", "GA", "OH", "IL"]


def _table(name, schema, cols, dict_cols=()):
    pydata = dict(cols)
    return Table.from_pydict(name, schema, pydata)


def gen_date_dim() -> Table:
    days = np.arange(CAL_START, CAL_END + np.timedelta64(1, "D"))
    dsk = np.arange(2450000, 2450000 + len(days), dtype=np.int64)
    years = days.astype("datetime64[Y]").astype(int) + 1970
    months = days.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (days - days.astype("datetime64[M]")).astype(int) + 1
    return _table("date_dim", S.DATE_DIM, {
        "d_date_sk": dsk,
        "d_date": (days - EPOCH).astype(np.int64),
        "d_year": years.astype(np.int32),
        "d_moy": months.astype(np.int32),
        "d_dom": dom.astype(np.int32),
    })


def gen_item(sf: float, rng) -> Table:
    n = max(int(18000 * min(sf, 1.0) + 2000 * sf), 1000)
    isk = np.arange(1, n + 1, dtype=np.int64)
    manufact = rng.integers(1, 1001, n).astype(np.int32)
    brand_id = (manufact * 100 + rng.integers(1, 10, n)).astype(np.int32)
    brand = np.char.add(
        np.char.add("Brand#", manufact.astype(str)), rng.integers(1, 10, n).astype(str)
    )
    cat_id = rng.integers(0, len(CATEGORIES), n)
    manager = rng.integers(1, 101, n).astype(np.int32)
    return _table("item", S.ITEM, {
        "i_item_sk": isk,
        "i_brand_id": brand_id,
        "i_brand": brand,
        "i_manufact_id": manufact,
        "i_category_id": (cat_id + 1).astype(np.int32),
        "i_category": np.array(CATEGORIES)[cat_id],
        "i_manager_id": manager,
    })


def gen_store(sf: float, rng) -> Table:
    n = max(int(12 * sf), 4)
    ssk = np.arange(1, n + 1, dtype=np.int64)
    return _table("store", S.STORE, {
        "s_store_sk": ssk,
        "s_store_name": np.array([f"Store{k:04d}" for k in range(n)]),
        "s_state": np.array(STATES)[rng.integers(0, len(STATES), n)],
    })


def gen_store_sales(sf: float, rng, dates: Table, n_item: int,
                    n_store: int) -> Table:
    n = max(int(2_880_000 * sf), 10_000)
    dsk = dates.data["d_date_sk"]
    moy = dates.data["d_moy"]
    # seasonal skew: November/December sell ~2x (the spec's holiday surge)
    w = np.where(np.isin(moy, (11, 12)), 2.0, 1.0)
    w = w / w.sum()
    date_pick = rng.choice(len(dsk), n, p=w)
    # popularity skew on items: Zipf-ish via squared uniform
    item_pick = (np.minimum(rng.random(n) ** 2 * n_item, n_item - 1)).astype(
        np.int64
    ) + 1
    qty = rng.integers(1, 101, n).astype(np.int32)
    price_c = rng.integers(100, 30001, n, dtype=np.int64)  # cents
    ext = price_c * qty
    profit = (ext * (rng.random(n) * 0.6 - 0.1)).astype(np.int64)
    return _table("store_sales", S.STORE_SALES, {
        "ss_sold_date_sk": dsk[date_pick],
        "ss_item_sk": item_pick,
        "ss_store_sk": rng.integers(1, n_store + 1, n).astype(np.int64),
        "ss_customer_sk": rng.integers(1, int(100_000 * max(sf, 0.01)) + 2, n).astype(np.int64),
        "ss_quantity": qty,
        "ss_ext_sales_price": ext / 100.0,
        "ss_net_profit": profit / 100.0,
    })


def generate(sf: float = 0.01, seed: int = 20030101) -> dict[str, Table]:
    rng = np.random.default_rng(seed)
    date_dim = gen_date_dim()
    item = gen_item(sf, rng)
    store = gen_store(sf, rng)
    store_sales = gen_store_sales(
        sf, rng, date_dim, item.nrows, store.nrows
    )
    return {
        "date_dim": date_dim,
        "item": item,
        "store": store,
        "store_sales": store_sales,
    }
