"""Hand-composed physical plans for TPC-H queries (kernel-level).

These are the reference physical plans the SQL compiler (oceanbase_tpu/sql)
must eventually reproduce from text; until then they serve as the
end-to-end slice (SURVEY.md §7 step 4) and the benchmark bodies. Each
builder returns a jitted device function over ColumnBatch pytrees plus a
host-side finisher that shapes the device outputs into result rows.

Q6: scan + fused filter + masked sum (one pass over 4 columns — the
    TPU analog of the reference's pushdown-filter + pushdown-aggregate path,
    storage/access/ob_aggregated_store_vec.h).
Q1: scan + filter + direct-addressed 8-slot group-by with 7 aggregates
    (packed returnflag×linestatus key — the adaptive low-NDV path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.column import ColumnBatch
from ...expr import BinaryOp, Compare, and_, col, compile_predicate, evaluate, lit
from ...ops import groupby_direct, pack_keys, scalar_aggregate


# ---------------------------------------------------------------------------
# Q6 — forecasting revenue change
# ---------------------------------------------------------------------------

Q6_PRED = and_(
    Compare(">=", col("l_shipdate"), lit("1994-01-01")),
    Compare("<", col("l_shipdate"), lit("1995-01-01")),
    Compare(">=", col("l_discount"), lit(0.05)),
    Compare("<=", col("l_discount"), lit(0.07)),
    Compare("<", col("l_quantity"), lit(24)),
)


def build_q6():
    rev = BinaryOp("*", col("l_extendedprice"), col("l_discount"))

    @jax.jit
    def q6(batch: ColumnBatch):
        mask = compile_predicate(Q6_PRED, batch)
        vals, _ = evaluate(rev, batch)
        (s,) = scalar_aggregate(mask, ["sum"], [vals])
        return s

    def finish(dev_out) -> float:
        return float(dev_out) / 1e4  # scale-4 decimal

    return q6, finish


# ---------------------------------------------------------------------------
# Q1 — pricing summary report
# ---------------------------------------------------------------------------


def build_q1(rf_domain: int, ls_domain: int):
    """rf_domain/ls_domain: dictionary sizes of returnflag/linestatus."""
    pred = Compare("<=", col("l_shipdate"), lit("1998-09-02"))
    disc_price = BinaryOp(
        "*", col("l_extendedprice"), BinaryOp("-", lit(1), col("l_discount"))
    )
    charge = BinaryOp(
        "*", disc_price, BinaryOp("+", lit(1), col("l_tax"))
    )

    @jax.jit
    def q1(batch: ColumnBatch):
        mask = compile_predicate(pred, batch)
        keys, domain = pack_keys(
            [batch.col("l_returnflag"), batch.col("l_linestatus")],
            [rf_domain, ls_domain],
        )
        qty = batch.col("l_quantity")
        price = batch.col("l_extendedprice")
        disc = batch.col("l_discount")
        dp, _ = evaluate(disc_price, batch)
        ch, _ = evaluate(charge, batch)
        slot_used, aggs = groupby_direct(
            keys,
            domain,
            mask,
            ["sum", "sum", "sum", "sum", "sum", "count"],
            [qty, price, dp, ch, disc, None],
        )
        return slot_used, aggs

    def finish(dev_out, rf_dict, ls_dict):
        slot_used, (s_qty, s_price, s_dp, s_ch, s_disc, cnt) = dev_out
        slot_used = np.asarray(slot_used)
        rows = []
        rf_bits = max(1, (rf_domain - 1).bit_length())
        for slot in np.nonzero(slot_used)[0]:
            rf_code = slot & ((1 << rf_bits) - 1)
            ls_code = slot >> rf_bits
            c = int(cnt[slot])
            rows.append(
                dict(
                    l_returnflag=rf_dict.decode_one(int(rf_code)),
                    l_linestatus=ls_dict.decode_one(int(ls_code)),
                    sum_qty=int(s_qty[slot]) / 100,
                    sum_base_price=int(s_price[slot]) / 100,
                    sum_disc_price=int(s_dp[slot]) / 1e4,
                    sum_charge=int(s_ch[slot]) / 1e6,
                    avg_qty=int(s_qty[slot]) / 100 / c,
                    avg_price=int(s_price[slot]) / 100 / c,
                    avg_disc=int(s_disc[slot]) / 100 / c,
                    count_order=c,
                )
            )
        rows.sort(key=lambda r: (r["l_returnflag"], r["l_linestatus"]))
        return rows

    return q1, finish


# ---------------------------------------------------------------------------
# numpy oracles (CPU vectorized baseline — the "reference CPU engine" side
# of BASELINE.json's >=5x target; measured, not cited)
# ---------------------------------------------------------------------------


def q6_numpy(lineitem) -> float:
    d = lineitem.data
    d0 = int(np.datetime64("1994-01-01", "D").astype(int))
    d1 = int(np.datetime64("1995-01-01", "D").astype(int))
    m = (
        (d["l_shipdate"] >= d0)
        & (d["l_shipdate"] < d1)
        & (d["l_discount"] >= 5)
        & (d["l_discount"] <= 7)
        & (d["l_quantity"] < 2400)
    )
    return float(
        np.sum(
            d["l_extendedprice"][m].astype(np.int64)
            * d["l_discount"][m].astype(np.int64)
        )
        / 1e4
    )


def q1_numpy_fast(lineitem):
    """Vectorized CPU Q1 (bincount on packed keys) — the honest baseline
    an optimized CPU vectorized engine would run; used for timing."""
    d = lineitem.data
    cutoff = int(np.datetime64("1998-09-02", "D").astype(int))
    m = d["l_shipdate"] <= cutoff
    rf = d["l_returnflag"].astype(np.int64)
    ls = d["l_linestatus"].astype(np.int64)
    nls = len(lineitem.dicts["l_linestatus"])
    key = (rf * nls + ls)[m]
    dom = len(lineitem.dicts["l_returnflag"]) * nls
    qty = d["l_quantity"].astype(np.int64)[m]
    price = d["l_extendedprice"].astype(np.int64)[m]
    disc = d["l_discount"].astype(np.int64)[m]
    tax = d["l_tax"].astype(np.int64)[m]
    dp = price * (100 - disc)
    ch = dp * (100 + tax)
    out = {
        "count": np.bincount(key, minlength=dom),
        "sum_qty": np.bincount(key, weights=qty, minlength=dom),
        "sum_price": np.bincount(key, weights=price, minlength=dom),
        "sum_dp": np.bincount(key, weights=dp.astype(np.float64), minlength=dom),
        "sum_ch": np.bincount(key, weights=ch.astype(np.float64), minlength=dom),
        "sum_disc": np.bincount(key, weights=disc, minlength=dom),
    }
    return out


def q1_numpy(lineitem):
    d = lineitem.data
    cutoff = int(np.datetime64("1998-09-02", "D").astype(int))
    m = d["l_shipdate"] <= cutoff
    rf = lineitem.dicts["l_returnflag"].decode(d["l_returnflag"])
    ls = lineitem.dicts["l_linestatus"].decode(d["l_linestatus"])
    rf = np.asarray(rf, dtype=object)
    ls = np.asarray(ls, dtype=object)
    qty = d["l_quantity"].astype(np.int64)
    price = d["l_extendedprice"].astype(np.int64)
    disc = d["l_discount"].astype(np.int64)
    tax = d["l_tax"].astype(np.int64)
    dp = price * (100 - disc)  # scale 4
    ch = dp * (100 + tax)  # scale 6
    rows = []
    for rfv in sorted(set(rf[m])):
        for lsv in sorted(set(ls[m])):
            g = m & (rf == rfv) & (ls == lsv)
            c = int(g.sum())
            if c == 0:
                continue
            rows.append(
                dict(
                    l_returnflag=rfv,
                    l_linestatus=lsv,
                    sum_qty=qty[g].sum() / 100,
                    sum_base_price=price[g].sum() / 100,
                    sum_disc_price=dp[g].sum() / 1e4,
                    sum_charge=ch[g].sum() / 1e6,
                    avg_qty=qty[g].sum() / 100 / c,
                    avg_price=price[g].sum() / 100 / c,
                    avg_disc=disc[g].sum() / 100 / c,
                    count_order=c,
                )
            )
    return rows
