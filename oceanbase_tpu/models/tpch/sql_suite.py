"""TPC-H query texts (spec defaults) + catalog metadata for the engine.

Only queries currently supported by the planner are listed in SUPPORTED;
the rest join the list as planner features land (subqueries, outer joins).
Texts follow the public TPC-H specification with default substitution
parameters.
"""

UNIQUE_KEYS = {
    "lineitem": (("l_orderkey", "l_linenumber"),),
    "orders": (("o_orderkey",),),
    "customer": (("c_custkey",),),
    "part": (("p_partkey",),),
    "supplier": (("s_suppkey",),),
    "partsupp": (("ps_partkey", "ps_suppkey"),),
    "nation": (("n_nationkey",),),
    "region": (("r_regionkey",),),
}

QUERIES = {
    1: """
select
    l_returnflag, l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
""",
    3: """
select
    l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
""",
    5: """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey
  and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and n_regionkey = r_regionkey
  and r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1995-01-01'
group by n_name
order by revenue desc
""",
    6: """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
""",
    10: """
select
    c_custkey, c_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    c_acctbal, n_name, c_address, c_phone, c_comment
from customer, orders, lineitem, nation
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate >= date '1993-10-01'
  and o_orderdate < date '1994-01-01'
  and l_returnflag = 'R'
  and c_nationkey = n_nationkey
group by c_custkey, c_name, c_acctbal, c_phone, n_name, c_address, c_comment
order by revenue desc
limit 20
""",
    12: """
select
    l_shipmode,
    sum(case when o_orderpriority = '1-URGENT' or o_orderpriority = '2-HIGH'
        then 1 else 0 end) as high_line_count,
    sum(case when o_orderpriority <> '1-URGENT' and o_orderpriority <> '2-HIGH'
        then 1 else 0 end) as low_line_count
from orders, lineitem
where o_orderkey = l_orderkey
  and l_shipmode in ('MAIL', 'SHIP')
  and l_commitdate < l_receiptdate
  and l_shipdate < l_commitdate
  and l_receiptdate >= date '1994-01-01'
  and l_receiptdate < date '1995-01-01'
group by l_shipmode
order by l_shipmode
""",
    14: """
select
    100.00 * sum(case when p_type like 'PROMO%'
        then l_extendedprice * (1 - l_discount) else 0 end)
    / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
from lineitem, part
where l_partkey = p_partkey
  and l_shipdate >= date '1995-09-01'
  and l_shipdate < date '1995-10-01'
""",
    19: """
select sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where (
    p_partkey = l_partkey
    and p_brand = 'Brand#12'
    and p_container in ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
    and l_quantity >= 1 and l_quantity <= 11
    and p_size between 1 and 5
    and l_shipmode in ('AIR', 'AIR REG')
    and l_shipinstruct = 'DELIVER IN PERSON'
) or (
    p_partkey = l_partkey
    and p_brand = 'Brand#23'
    and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
    and l_quantity >= 10 and l_quantity <= 20
    and p_size between 1 and 10
    and l_shipmode in ('AIR', 'AIR REG')
    and l_shipinstruct = 'DELIVER IN PERSON'
) or (
    p_partkey = l_partkey
    and p_brand = 'Brand#34'
    and p_container in ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
    and l_quantity >= 20 and l_quantity <= 30
    and p_size between 1 and 15
    and l_shipmode in ('AIR', 'AIR REG')
    and l_shipinstruct = 'DELIVER IN PERSON'
)
""",
}

SUPPORTED = (1, 3, 5, 6, 10, 12, 14, 19)
