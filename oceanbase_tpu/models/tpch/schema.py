"""TPC-H schema in oceanbase_tpu types.

The workload family the benchmarks run on (BASELINE.md configs). Types pick
the narrowest physical width that holds the TPC-H domain at the target scale
factors (keys int32 up to SF100's 600M lineitem rows need int64 for orderkey
at SF>=78 — orderkey max = SF * 6M * 4; we use int64 for orderkey, int32
elsewhere). Decimals: money DECIMAL(12,2), discounts/tax DECIMAL(9,2).
"""

from __future__ import annotations

from ...core.dtypes import DataType, Schema

D = DataType

LINEITEM = Schema.of(
    l_orderkey=D.int64(),
    l_partkey=D.int32(),
    l_suppkey=D.int32(),
    l_linenumber=D.int8(),
    l_quantity=D.decimal(9, 2),
    l_extendedprice=D.decimal(12, 2),
    l_discount=D.decimal(9, 2),
    l_tax=D.decimal(9, 2),
    l_returnflag=D.varchar(),
    l_linestatus=D.varchar(),
    l_shipdate=D.date(),
    l_commitdate=D.date(),
    l_receiptdate=D.date(),
    l_shipinstruct=D.varchar(),
    l_shipmode=D.varchar(),
)

ORDERS = Schema.of(
    o_orderkey=D.int64(),
    o_custkey=D.int32(),
    o_orderstatus=D.varchar(),
    o_totalprice=D.decimal(12, 2),
    o_orderdate=D.date(),
    o_orderpriority=D.varchar(),
    o_clerk=D.varchar(),
    o_shippriority=D.int32(),
    o_comment=D.varchar(),
)

CUSTOMER = Schema.of(
    c_custkey=D.int32(),
    c_name=D.varchar(),
    c_address=D.varchar(),
    c_nationkey=D.int8(),
    c_phone=D.varchar(),
    c_acctbal=D.decimal(12, 2),
    c_mktsegment=D.varchar(),
    c_comment=D.varchar(),
)

PART = Schema.of(
    p_partkey=D.int32(),
    p_name=D.varchar(),
    p_mfgr=D.varchar(),
    p_brand=D.varchar(),
    p_type=D.varchar(),
    p_size=D.int32(),
    p_container=D.varchar(),
    p_retailprice=D.decimal(12, 2),
)

SUPPLIER = Schema.of(
    s_suppkey=D.int32(),
    s_name=D.varchar(),
    s_address=D.varchar(),
    s_nationkey=D.int8(),
    s_phone=D.varchar(),
    s_acctbal=D.decimal(12, 2),
    s_comment=D.varchar(),
)

PARTSUPP = Schema.of(
    ps_partkey=D.int32(),
    ps_suppkey=D.int32(),
    ps_availqty=D.int32(),
    ps_supplycost=D.decimal(12, 2),
)

NATION = Schema.of(
    n_nationkey=D.int8(),
    n_name=D.varchar(),
    n_regionkey=D.int8(),
)

REGION = Schema.of(
    r_regionkey=D.int8(),
    r_name=D.varchar(),
)

TABLES = {
    "lineitem": LINEITEM,
    "orders": ORDERS,
    "customer": CUSTOMER,
    "part": PART,
    "supplier": SUPPLIER,
    "partsupp": PARTSUPP,
    "nation": NATION,
    "region": REGION,
}

# base cardinalities at SF=1
BASE_ROWS = {
    "lineitem": 6_001_215,
    "orders": 1_500_000,
    "customer": 150_000,
    "part": 200_000,
    "supplier": 10_000,
    "partsupp": 800_000,
    "nation": 25,
    "region": 5,
}
