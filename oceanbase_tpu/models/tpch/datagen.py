"""Spec-shaped TPC-H data generator (vectorized numpy, no dbgen).

Generates the 8 TPC-H tables with the distributions, domains and PK-FK
relationships the 22 queries rely on (dates within [1992-01-01, 1998-08-02],
shipdate = orderdate + U[1,121], returnflag correlated with receiptdate,
1-7 lineitems per order, etc.). Values are drawn with numpy vectorized RNG —
generation of SF1 (6M lineitem rows) takes seconds, and the same generator
with the same seed feeds both the CPU baseline and the TPU engine so
benchmark comparisons are apples-to-apples.

Comments are built from a small template vocabulary that still contains the
keyword patterns queries grep for (Q13 '%special%requests%',
Q16 '%Customer%Complaints%').
"""

from __future__ import annotations

import numpy as np

from ...core.dictionary import Dictionary
from ...core.table import Table
from . import schema as S

EPOCH = np.datetime64("1970-01-01", "D")
START = int(np.datetime64("1992-01-01", "D").astype(int))
END = int(np.datetime64("1998-12-01", "D").astype(int))
CURRENT = int(np.datetime64("1995-06-17", "D").astype(int))

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINERS_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINERS_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
P_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
    "white", "yellow",
]
COMMENT_WORDS = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "final", "pending", "regular", "express", "bold", "even", "silent",
    "unusual", "daring", "accounts", "packages", "deposits", "requests",
    "instructions", "foxes", "pinto", "beans", "theodolites", "platelets",
]


def _comments(rng: np.random.Generator, n: int, special: str | None = None,
              special_rate: float = 0.01) -> np.ndarray:
    """Short comments from a bounded vocabulary; optionally inject a keyword
    phrase (e.g. 'special requests') at special_rate."""
    w = rng.integers(0, len(COMMENT_WORDS), (n, 3))
    out = np.array(
        [" ".join(COMMENT_WORDS[j] for j in row) for row in w], dtype=object
    )
    if special:
        hit = rng.random(n) < special_rate
        out[hit] = np.char.add(
            np.char.add(out[hit].astype(str), " "), special
        ).astype(object)
    return out


def _money(rng, n, lo, hi):
    return np.round(rng.uniform(lo, hi, n), 2)


def gen_region() -> Table:
    return Table.from_pydict("region", S.REGION, {
        "r_regionkey": np.arange(5), "r_name": REGIONS,
    })


def gen_nation() -> Table:
    return Table.from_pydict("nation", S.NATION, {
        "n_nationkey": np.arange(25),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": [r for _, r in NATIONS],
    })


def gen_supplier(sf: float, rng) -> Table:
    n = max(1, int(S.BASE_ROWS["supplier"] * sf))
    keys = np.arange(1, n + 1)
    return Table.from_pydict("supplier", S.SUPPLIER, {
        "s_suppkey": keys,
        "s_name": [f"Supplier#{k:09d}" for k in keys],
        "s_address": _comments(rng, n),
        "s_nationkey": rng.integers(0, 25, n),
        "s_phone": [f"{10+k%25}-{k%1000:03d}-{(k*7)%1000:03d}-{(k*13)%10000:04d}" for k in keys],
        "s_acctbal": _money(rng, n, -999.99, 9999.99),
        "s_comment": _comments(rng, n, "Customer Complaints", 0.0005),
    })


def gen_customer(sf: float, rng) -> Table:
    n = max(1, int(S.BASE_ROWS["customer"] * sf))
    keys = np.arange(1, n + 1)
    return Table.from_pydict("customer", S.CUSTOMER, {
        "c_custkey": keys,
        "c_name": [f"Customer#{k:09d}" for k in keys],
        "c_address": _comments(rng, n),
        "c_nationkey": rng.integers(0, 25, n),
        "c_phone": [f"{10+k%25}-{k%1000:03d}-{(k*7)%1000:03d}-{(k*13)%10000:04d}" for k in keys],
        "c_acctbal": _money(rng, n, -999.99, 9999.99),
        "c_mktsegment": rng.choice(SEGMENTS, n),
        "c_comment": _comments(rng, n, "special requests", 0.01),
    })


def gen_part(sf: float, rng) -> Table:
    n = max(1, int(S.BASE_ROWS["part"] * sf))
    keys = np.arange(1, n + 1)
    w = rng.integers(0, len(P_NAME_WORDS), (n, 5))
    names = [" ".join(P_NAME_WORDS[j] for j in row) for row in w]
    mfgr = rng.integers(1, 6, n)
    brand = mfgr * 10 + rng.integers(1, 6, n)
    types = [
        f"{TYPE_S1[a]} {TYPE_S2[b]} {TYPE_S3[c]}"
        for a, b, c in zip(
            rng.integers(0, 6, n), rng.integers(0, 5, n), rng.integers(0, 5, n)
        )
    ]
    containers = [
        f"{CONTAINERS_1[a]} {CONTAINERS_2[b]}"
        for a, b in zip(rng.integers(0, 5, n), rng.integers(0, 8, n))
    ]
    return Table.from_pydict("part", S.PART, {
        "p_partkey": keys,
        "p_name": names,
        "p_mfgr": [f"Manufacturer#{m}" for m in mfgr],
        "p_brand": [f"Brand#{b}" for b in brand],
        "p_type": types,
        "p_size": rng.integers(1, 51, n),
        "p_container": containers,
        "p_retailprice": np.round(
            900 + (keys % 1000) / 10 + 100 * (keys % 10), 2
        ),
    })


def gen_partsupp(sf: float, rng, n_part: int, n_supp: int) -> Table:
    # 4 suppliers per part, spec-style spread
    pk = np.repeat(np.arange(1, n_part + 1), 4)
    n = len(pk)
    j = np.tile(np.arange(4), n_part)
    sk = ((pk + (j * (n_supp // 4 + (pk - 1) // n_supp))) % n_supp) + 1
    return Table.from_pydict("partsupp", S.PARTSUPP, {
        "ps_partkey": pk,
        "ps_suppkey": sk,
        "ps_availqty": rng.integers(1, 10000, n),
        "ps_supplycost": _money(rng, n, 1.00, 1000.00),
    })


def gen_orders_lineitem(sf: float, rng, n_cust: int, n_part: int, n_supp: int):
    n_ord = max(1, int(S.BASE_ROWS["orders"] * sf))
    okey = np.arange(1, n_ord + 1, dtype=np.int64) * 4  # sparse like spec
    # only 2/3 of customers have orders (spec): custkey % 3 != 0
    ck = rng.integers(1, max(n_cust, 2), n_ord).astype(np.int64)
    ck = np.where(ck % 3 == 0, np.maximum((ck + 1) % (n_cust + 1), 1), ck)
    odate = rng.integers(START, END - 151, n_ord)
    n_li_per = rng.integers(1, 8, n_ord)
    nl = int(n_li_per.sum())

    # lineitem parent mapping
    li_order = np.repeat(np.arange(n_ord), n_li_per)
    l_orderkey = okey[li_order]
    l_linenumber = (
        np.arange(nl) - np.repeat(np.cumsum(n_li_per) - n_li_per, n_li_per) + 1
    )
    l_partkey = rng.integers(1, n_part + 1, nl)
    l_suppkey = rng.integers(1, n_supp + 1, nl)
    qty = rng.integers(1, 51, nl).astype(np.float64)
    retail = 900 + (l_partkey % 1000) / 10 + 100 * (l_partkey % 10)
    extprice = np.round(qty * retail, 2)
    disc = rng.integers(0, 11, nl) / 100
    tax = rng.integers(0, 9, nl) / 100
    o_date_li = odate[li_order]
    shipdate = o_date_li + rng.integers(1, 122, nl)
    commitdate = o_date_li + rng.integers(30, 91, nl)
    receiptdate = shipdate + rng.integers(1, 31, nl)
    returned = receiptdate <= CURRENT
    rf = np.where(returned, np.where(rng.random(nl) < 0.5, "R", "A"), "N")
    ls = np.where(shipdate > CURRENT, "O", "F")

    lineitem = Table.from_pydict("lineitem", S.LINEITEM, {
        "l_orderkey": l_orderkey,
        "l_partkey": l_partkey,
        "l_suppkey": l_suppkey,
        "l_linenumber": l_linenumber,
        "l_quantity": qty,
        "l_extendedprice": extprice,
        "l_discount": disc,
        "l_tax": tax,
        "l_returnflag": rf,
        "l_linestatus": ls,
        "l_shipdate": shipdate,
        "l_commitdate": commitdate,
        "l_receiptdate": receiptdate,
        "l_shipinstruct": rng.choice(INSTRUCTS, nl),
        "l_shipmode": rng.choice(SHIPMODES, nl),
    })

    # order status/totalprice derived from lineitems
    charge = extprice * (1 - disc) * (1 + tax)
    totalprice = np.zeros(n_ord)
    np.add.at(totalprice, li_order, charge)
    all_f = np.ones(n_ord, bool)
    any_f = np.zeros(n_ord, bool)
    np.logical_and.at(all_f, li_order, ls == "F")
    np.logical_or.at(any_f, li_order, ls == "F")
    status = np.where(all_f, "F", np.where(any_f, "P", "O"))

    orders = Table.from_pydict("orders", S.ORDERS, {
        "o_orderkey": okey,
        "o_custkey": ck,
        "o_orderstatus": status,
        "o_totalprice": np.round(totalprice, 2),
        "o_orderdate": odate,
        "o_orderpriority": rng.choice(PRIORITIES, n_ord),
        "o_clerk": [f"Clerk#{k:09d}" for k in rng.integers(1, max(2, int(1000 * sf)), n_ord)],
        "o_shippriority": np.zeros(n_ord, dtype=np.int32),
        "o_comment": _comments(rng, n_ord, "special requests", 0.01),
    })
    return orders, lineitem


def generate(sf: float = 0.01, seed: int = 19920101) -> dict[str, Table]:
    """Generate all 8 tables at the given scale factor."""
    rng = np.random.default_rng(seed)
    region = gen_region()
    nation = gen_nation()
    supplier = gen_supplier(sf, rng)
    customer = gen_customer(sf, rng)
    part = gen_part(sf, rng)
    partsupp = gen_partsupp(sf, rng, part.nrows, supplier.nrows)
    orders, lineitem = gen_orders_lineitem(
        sf, rng, customer.nrows, part.nrows, supplier.nrows
    )
    return {
        "region": region,
        "nation": nation,
        "supplier": supplier,
        "customer": customer,
        "part": part,
        "partsupp": partsupp,
        "orders": orders,
        "lineitem": lineitem,
    }
