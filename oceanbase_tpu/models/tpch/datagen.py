"""Spec-shaped TPC-H data generator (vectorized numpy, no dbgen).

Generates the 8 TPC-H tables with the distributions, domains and PK-FK
relationships the 22 queries rely on (dates within [1992-01-01, 1998-08-02],
shipdate = orderdate + U[1,121], returnflag correlated with receiptdate,
1-7 lineitems per order, etc.). Values are drawn with numpy vectorized RNG —
generation of SF1 (6M lineitem rows) takes seconds, and the same generator
with the same seed feeds both the CPU baseline and the TPU engine so
benchmark comparisons are apples-to-apples.

Comments are built from a small template vocabulary that still contains the
keyword patterns queries grep for (Q13 '%special%requests%',
Q16 '%Customer%Complaints%').
"""

from __future__ import annotations

import numpy as np

from ...core.dictionary import Dictionary
from ...core.table import Table
from . import schema as S

EPOCH = np.datetime64("1970-01-01", "D")
START = int(np.datetime64("1992-01-01", "D").astype(int))
END = int(np.datetime64("1998-12-01", "D").astype(int))
CURRENT = int(np.datetime64("1995-06-17", "D").astype(int))

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINERS_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINERS_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
P_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
    "white", "yellow",
]
COMMENT_WORDS = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "final", "pending", "regular", "express", "bold", "even", "silent",
    "unusual", "daring", "accounts", "packages", "deposits", "requests",
    "instructions", "foxes", "pinto", "beans", "theodolites", "platelets",
]


def _comments(rng: np.random.Generator, n: int, special: str | None = None,
              special_rate: float = 0.01) -> np.ndarray:
    """Short comments from a bounded vocabulary; optionally inject a keyword
    phrase (e.g. 'special requests') at special_rate. Vectorized: numpy
    char ops over the word table, no per-row Python."""
    vocab = np.array(COMMENT_WORDS)
    w = rng.integers(0, len(COMMENT_WORDS), (n, 3))
    out = np.char.add(
        np.char.add(vocab[w[:, 0]], " "),
        np.char.add(np.char.add(vocab[w[:, 1]], " "), vocab[w[:, 2]]),
    )
    if special:
        hit = rng.random(n) < special_rate
        if hit.any():
            out = out.astype("U64")
            out[hit] = np.char.add(np.char.add(out[hit], " "), special)
    return out


def _comment_codes(
    rng: np.random.Generator, n: int, special: str | None = None,
    special_rate: float = 0.01,
):
    """Dict-code fast path for huge tables: every possible 3-word comment
    (optionally + special suffix) forms the dictionary; rows draw codes.
    Generation cost is O(n) int ops + one O(|vocab|^3) string build."""
    from ...core.dictionary import Dictionary

    nw = len(COMMENT_WORDS)
    combos = [
        f"{a} {b} {c}"
        for a in COMMENT_WORDS for b in COMMENT_WORDS for c in COMMENT_WORDS
    ]
    variants = list(combos)
    if special:
        variants += [f"{s} {special}" for s in combos]
    values, inv = np.unique(np.array(variants), return_inverse=True)
    d = Dictionary([str(v) for v in values], sorted_=True)
    w = rng.integers(0, nw, (n, 3))
    flat = (w[:, 0] * nw + w[:, 1]) * nw + w[:, 2]
    if special:
        sp = rng.random(n) < special_rate
        flat = flat + sp * (nw ** 3)
    return inv[flat].astype(np.int32), d


def _choice_codes(rng: np.random.Generator, values: list[str], n: int):
    """Dict-code fast path for a uniform choice over a small vocabulary."""
    from ...core.dictionary import Dictionary

    sv, _ = np.unique(np.array(values), return_inverse=True)
    d = Dictionary([str(v) for v in sv], sorted_=True)
    order = {v: i for i, v in enumerate(sv)}
    lut = np.array([order[v] for v in values], dtype=np.int32)
    return lut[rng.integers(0, len(values), n)], d


def _money(rng, n, lo, hi):
    return np.round(rng.uniform(lo, hi, n), 2)


def _zfill_name(prefix: str, keys: np.ndarray, width: int = 9) -> np.ndarray:
    return np.char.add(prefix, np.char.zfill(keys.astype(f"U{width}"), width))


def _phones(keys: np.ndarray) -> np.ndarray:
    k = keys.astype(np.int64)
    return np.char.add(
        np.char.add((10 + k % 25).astype("U2"), "-"),
        np.char.add(
            np.char.add(np.char.zfill((k % 1000).astype("U3"), 3), "-"),
            np.char.add(
                np.char.add(np.char.zfill(((k * 7) % 1000).astype("U3"), 3), "-"),
                np.char.zfill(((k * 13) % 10000).astype("U4"), 4),
            ),
        ),
    )


def _table_mixed(name, schema, plain: dict, coded: dict) -> "Table":
    """Build a Table from plain columns (from_pydict semantics) plus
    pre-dictionary-encoded VARCHAR columns (codes, Dictionary) — the fast
    path that keeps huge-table generation free of per-row Python."""
    from ...core.dtypes import TypeKind

    data: dict[str, np.ndarray] = {}
    dicts = {}
    for f in schema.fields:
        if f.name in coded:
            codes, d = coded[f.name]
            data[f.name] = np.asarray(codes, dtype=np.int32)
            dicts[f.name] = d
        elif f.dtype.kind is TypeKind.VARCHAR:
            arr = np.asarray(plain[f.name])
            if arr.dtype.kind not in ("U", "S"):
                arr = arr.astype(str)
            d, codes = Dictionary.from_strings_bulk(arr)
            data[f.name] = codes
            dicts[f.name] = d
        elif f.dtype.is_decimal:
            a = np.asarray(plain[f.name])
            if np.issubdtype(a.dtype, np.floating):
                a = np.round(a * f.dtype.decimal_factor)
            data[f.name] = a.astype(f.dtype.storage_np)
        else:
            data[f.name] = np.asarray(plain[f.name], dtype=f.dtype.storage_np)
    return Table(name, schema, data, dicts)


def gen_region() -> Table:
    return Table.from_pydict("region", S.REGION, {
        "r_regionkey": np.arange(5), "r_name": REGIONS,
    })


def gen_nation() -> Table:
    return Table.from_pydict("nation", S.NATION, {
        "n_nationkey": np.arange(25),
        "n_name": [n for n, _ in NATIONS],
        "n_regionkey": [r for _, r in NATIONS],
    })


def gen_supplier(sf: float, rng) -> Table:
    n = max(1, int(S.BASE_ROWS["supplier"] * sf))
    keys = np.arange(1, n + 1)
    return Table.from_pydict("supplier", S.SUPPLIER, {
        "s_suppkey": keys,
        "s_name": _zfill_name("Supplier#", keys),
        "s_address": _comments(rng, n),
        "s_nationkey": rng.integers(0, 25, n),
        "s_phone": _phones(keys),
        "s_acctbal": _money(rng, n, -999.99, 9999.99),
        "s_comment": _comments(rng, n, "Customer Complaints", 0.0005),
    })


def gen_customer(sf: float, rng) -> Table:
    n = max(1, int(S.BASE_ROWS["customer"] * sf))
    keys = np.arange(1, n + 1)
    return _table_mixed("customer", S.CUSTOMER, {
        "c_custkey": keys,
        "c_name": _zfill_name("Customer#", keys),
        "c_address": _comments(rng, n),
        "c_nationkey": rng.integers(0, 25, n),
        "c_phone": _phones(keys),
        "c_acctbal": _money(rng, n, -999.99, 9999.99),
        "c_mktsegment": rng.choice(SEGMENTS, n),
    }, {
        "c_comment": _comment_codes(rng, n, "special requests", 0.01),
    })


def gen_part(sf: float, rng) -> Table:
    n = max(1, int(S.BASE_ROWS["part"] * sf))
    keys = np.arange(1, n + 1)
    vocab = np.array(P_NAME_WORDS)
    w = rng.integers(0, len(P_NAME_WORDS), (n, 5))
    names = vocab[w[:, 0]]
    for j in range(1, 5):
        names = np.char.add(np.char.add(names, " "), vocab[w[:, j]])
    mfgr = rng.integers(1, 6, n)
    brand = mfgr * 10 + rng.integers(1, 6, n)
    types = [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2 for c in TYPE_S3]
    type_idx = (
        rng.integers(0, 6, n) * 25 + rng.integers(0, 5, n) * 5
        + rng.integers(0, 5, n)
    )
    containers = [f"{a} {b}" for a in CONTAINERS_1 for b in CONTAINERS_2]
    cont_idx = rng.integers(0, 5, n) * 8 + rng.integers(0, 8, n)
    t_codes, t_dict = _lut_codes(types, type_idx)
    c_codes, c_dict = _lut_codes(containers, cont_idx)
    return _table_mixed("part", S.PART, {
        "p_partkey": keys,
        "p_name": names,
        "p_mfgr": np.char.add("Manufacturer#", mfgr.astype("U1")),
        "p_brand": np.char.add("Brand#", brand.astype("U2")),
        "p_size": rng.integers(1, 51, n),
        "p_retailprice": np.round(
            900 + (keys % 1000) / 10 + 100 * (keys % 10), 2
        ),
    }, {
        "p_type": (t_codes, t_dict),
        "p_container": (c_codes, c_dict),
    })


def _lut_codes(values: list[str], idx: np.ndarray):
    """Codes for rows drawing strings by index into a small value list."""
    sv, inv = np.unique(np.array(values), return_inverse=True)
    d = Dictionary([str(v) for v in sv], sorted_=True)
    return inv.astype(np.int32)[idx], d


def gen_partsupp(sf: float, rng, n_part: int, n_supp: int) -> Table:
    # 4 suppliers per part, spec-style spread
    pk = np.repeat(np.arange(1, n_part + 1), 4)
    n = len(pk)
    j = np.tile(np.arange(4), n_part)
    sk = ((pk + (j * (n_supp // 4 + (pk - 1) // n_supp))) % n_supp) + 1
    return Table.from_pydict("partsupp", S.PARTSUPP, {
        "ps_partkey": pk,
        "ps_suppkey": sk,
        "ps_availqty": rng.integers(1, 10000, n),
        "ps_supplycost": _money(rng, n, 1.00, 1000.00),
    })


def gen_orders_lineitem(sf: float, rng, n_cust: int, n_part: int, n_supp: int):
    n_ord = max(1, int(S.BASE_ROWS["orders"] * sf))
    okey = np.arange(1, n_ord + 1, dtype=np.int64) * 4  # sparse like spec
    # only 2/3 of customers have orders (spec): custkey % 3 != 0
    ck = rng.integers(1, max(n_cust, 2), n_ord).astype(np.int64)
    ck = np.where(ck % 3 == 0, np.maximum((ck + 1) % (n_cust + 1), 1), ck)
    odate = rng.integers(START, END - 151, n_ord)
    n_li_per = rng.integers(1, 8, n_ord)
    nl = int(n_li_per.sum())

    # lineitem parent mapping
    li_order = np.repeat(np.arange(n_ord), n_li_per)
    l_orderkey = okey[li_order]
    l_linenumber = (
        np.arange(nl) - np.repeat(np.cumsum(n_li_per) - n_li_per, n_li_per) + 1
    )
    l_partkey = rng.integers(1, n_part + 1, nl)
    l_suppkey = rng.integers(1, n_supp + 1, nl)
    qty = rng.integers(1, 51, nl).astype(np.float64)
    retail = 900 + (l_partkey % 1000) / 10 + 100 * (l_partkey % 10)
    extprice = np.round(qty * retail, 2)
    disc = rng.integers(0, 11, nl) / 100
    tax = rng.integers(0, 9, nl) / 100
    o_date_li = odate[li_order]
    shipdate = o_date_li + rng.integers(1, 122, nl)
    commitdate = o_date_li + rng.integers(30, 91, nl)
    receiptdate = shipdate + rng.integers(1, 31, nl)
    returned = receiptdate <= CURRENT
    # dict-code fast paths: sorted vocab positions are fixed —
    # ["A","N","R"] and ["F","O"]
    rf_codes = np.where(
        returned, np.where(rng.random(nl) < 0.5, 2, 0), 1
    ).astype(np.int32)
    rf_dict = Dictionary(["A", "N", "R"], sorted_=True)
    is_open = shipdate > CURRENT
    ls_codes = is_open.astype(np.int32)
    ls_dict = Dictionary(["F", "O"], sorted_=True)
    si_codes, si_dict = _choice_codes(rng, INSTRUCTS, nl)
    sm_codes, sm_dict = _choice_codes(rng, SHIPMODES, nl)

    lineitem = _table_mixed("lineitem", S.LINEITEM, {
        "l_orderkey": l_orderkey,
        "l_partkey": l_partkey,
        "l_suppkey": l_suppkey,
        "l_linenumber": l_linenumber,
        "l_quantity": qty,
        "l_extendedprice": extprice,
        "l_discount": disc,
        "l_tax": tax,
        "l_shipdate": shipdate,
        "l_commitdate": commitdate,
        "l_receiptdate": receiptdate,
    }, {
        "l_returnflag": (rf_codes, rf_dict),
        "l_linestatus": (ls_codes, ls_dict),
        "l_shipinstruct": (si_codes, si_dict),
        "l_shipmode": (sm_codes, sm_dict),
    })

    # order status/totalprice derived from lineitems
    charge = extprice * (1 - disc) * (1 + tax)
    totalprice = np.zeros(n_ord)
    np.add.at(totalprice, li_order, charge)
    is_f = ~is_open
    all_f = np.ones(n_ord, bool)
    any_f = np.zeros(n_ord, bool)
    np.logical_and.at(all_f, li_order, is_f)
    np.logical_or.at(any_f, li_order, is_f)
    # sorted vocab ["F","O","P"]: F=0, O=1, P=2
    status_codes = np.where(all_f, 0, np.where(any_f, 2, 1)).astype(np.int32)
    status_dict = Dictionary(["F", "O", "P"], sorted_=True)
    pr_codes, pr_dict = _choice_codes(rng, PRIORITIES, n_ord)
    n_clerks = max(1, int(1000 * sf))
    clerk_vocab = [f"Clerk#{k:09d}" for k in range(1, n_clerks + 1)]
    clerk_codes = rng.integers(0, n_clerks, n_ord).astype(np.int32)
    clerk_dict = Dictionary(clerk_vocab, sorted_=True)
    oc_codes, oc_dict = _comment_codes(rng, n_ord, "special requests", 0.01)

    orders = _table_mixed("orders", S.ORDERS, {
        "o_orderkey": okey,
        "o_custkey": ck,
        "o_totalprice": np.round(totalprice, 2),
        "o_orderdate": odate,
        "o_shippriority": np.zeros(n_ord, dtype=np.int32),
    }, {
        "o_orderstatus": (status_codes, status_dict),
        "o_orderpriority": (pr_codes, pr_dict),
        "o_clerk": (clerk_codes, clerk_dict),
        "o_comment": (oc_codes, oc_dict),
    })
    return orders, lineitem


def generate(sf: float = 0.01, seed: int = 19920101) -> dict[str, Table]:
    """Generate all 8 tables at the given scale factor."""
    rng = np.random.default_rng(seed)
    region = gen_region()
    nation = gen_nation()
    supplier = gen_supplier(sf, rng)
    customer = gen_customer(sf, rng)
    part = gen_part(sf, rng)
    partsupp = gen_partsupp(sf, rng, part.nrows, supplier.nrows)
    orders, lineitem = gen_orders_lineitem(
        sf, rng, customer.nrows, part.nrows, supplier.nrows
    )
    return {
        "region": region,
        "nation": nation,
        "supplier": supplier,
        "customer": customer,
        "part": part,
        "partsupp": partsupp,
        "orders": orders,
        "lineitem": lineitem,
    }
