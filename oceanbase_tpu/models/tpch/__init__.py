from . import datagen, queries, schema

__all__ = ["datagen", "queries", "schema"]
