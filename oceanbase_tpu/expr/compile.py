"""Expression compiler: IR -> whole-batch JAX computation.

The analog of OceanBase's expression code generator + eval function library
(sql/code_generator/ob_static_engine_expr_cg.h:70,
sql/engine/expr/ob_expr_eval_functions.cpp:554). Differences by design:

- One eval mode: whole-batch arrays through XLA (the reference keeps scalar /
  batch / rich-vector triples, ob_expr.h:888-898). XLA fuses the resulting
  elementwise graphs into the surrounding operator kernels, which is the TPU
  replacement for the reference's hand-fused SIMD eval functions.
- Decimals are scaled integers with compile-time scales: + - rescale to the
  max scale, * adds scales (promoting storage to int64), / leaves the decimal
  domain and produces float (matching how the reference routes decimal
  division through lib/number only on the CPU).
- String predicates (=, <, LIKE, IN) on dictionary-encoded columns are
  evaluated once against the host-side dictionary, producing either a code
  threshold (sorted dicts) or a boolean lookup table that becomes a gather on
  device — the global-dictionary version of the reference's dict-decoder
  pushdown filters (storage/blocksstable/encoding/ob_dict_decoder_simd.cpp).
- NULL semantics: separate validity masks, Kleene AND/OR, comparisons yield
  NULL if either side is NULL; filters treat NULL as reject. (Reference:
  ObBitVector skip/eval flags, sql/engine/ob_bit_vector.h.)

evaluate() runs during jit tracing: host work (dictionary lookups, literal
parsing) folds into compile-time constants; everything per-row becomes XLA.
"""

from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from ..core.column import ColumnBatch
from ..core.dtypes import (
    BOOL,
    DataType,
    Schema,
    TypeKind,
    common_numeric_type,
)
from .ir import (
    Between,
    BinaryOp,
    BoolOp,
    Case,
    Cast,
    ColRef,
    Compare,
    Expr,
    Func,
    InList,
    IsNull,
    Literal,
    Not,
)

MAX_DECIMAL_SCALE = 6


# ---------------------------------------------------------------------------
# query parameters (plan-cache parameterized literals)
# ---------------------------------------------------------------------------

# Traced scalars for slotted Literals, active only while an executor traces /
# runs a parameterized plan. Reference: parameter frames bound into ObEvalCtx
# at execution (sql/plan_cache parameterization); here the "frame" is a tuple
# of 0-d device arrays passed as an extra jit argument.
_ACTIVE_PARAMS: tuple | None = None


def set_params(params: tuple | None):
    """Install the active parameter tuple; returns the previous one."""
    global _ACTIVE_PARAMS
    prev = _ACTIVE_PARAMS
    _ACTIVE_PARAMS = params
    return prev


def literal_scalar(e):
    """Traced storage-domain value of a Literal (slotted literals read
    the active parameter tuple so one executable serves every value) —
    for kernels that consume a literal directly (range-scan bounds, ANN
    query vectors) rather than as a broadcast column."""
    if e.slot is not None and _ACTIVE_PARAMS is not None:
        return _ACTIVE_PARAMS[e.slot]
    return jnp.asarray(bind_value(e.value, e.dtype))


# VECTOR literals resolve identically (the 'scalar' is a (d,) array)
evaluate_vector_literal = literal_scalar


def bind_value(value, dtype: DataType) -> np.generic:
    """Convert a python literal to its physical storage scalar (host side).

    Mirrors _literal_as so a bound parameter lands in exactly the domain the
    trace assumed: decimals as scaled ints, dates as int32 days."""
    if dtype.kind is TypeKind.VECTOR:
        if isinstance(value, str):
            value = [float(x) for x in value.strip("[] ").split(",")]
        a = np.asarray(value, dtype=np.float32)
        if a.shape != (dtype.precision,):
            raise ValueError(
                f"vector literal dim {a.shape} != column dim "
                f"({dtype.precision},)"
            )
        return a
    if dtype.kind is TypeKind.DATE:
        if isinstance(value, str):
            value = _parse_date(value)
        return np.int32(value)
    if dtype.is_decimal:
        return dtype.storage_np.type(int(round(float(value) * dtype.decimal_factor)))
    return dtype.storage_np.type(value)


# ---------------------------------------------------------------------------
# type inference
# ---------------------------------------------------------------------------


import functools


@functools.lru_cache(maxsize=65536)
def infer_type(e: Expr, schema: Schema) -> DataType:
    if isinstance(e, ColRef):
        return schema[e.name]
    if isinstance(e, Literal):
        return e.dtype
    if isinstance(e, BinaryOp):
        lt, rt = infer_type(e.left, schema), infer_type(e.right, schema)
        if e.op == "/":
            return DataType.float64(lt.nullable or rt.nullable)
        if lt.is_decimal or rt.is_decimal:
            # float operand forces float result
            if lt.is_float or rt.is_float:
                return DataType.float64(lt.nullable or rt.nullable)
            ls = lt.scale if lt.is_decimal else 0
            rs = rt.scale if rt.is_decimal else 0
            if e.op == "*":
                scale = min(ls + rs, MAX_DECIMAL_SCALE)
                return DataType.decimal(18, scale, lt.nullable or rt.nullable)
            scale = max(ls, rs)
            prec = 18 if (lt.storage_np.itemsize > 4 or rt.storage_np.itemsize > 4 or e.op in "+-") else 9
            return DataType.decimal(prec, scale, lt.nullable or rt.nullable)
        return common_numeric_type(lt, rt)
    if isinstance(e, (Compare, BoolOp, Not, IsNull, InList, Between)):
        return BOOL
    if isinstance(e, Cast):
        return e.dtype
    if isinstance(e, Case):
        branch_types = [infer_type(v, schema) for _, v in e.whens]
        if e.default is not None:
            branch_types.append(infer_type(e.default, schema))
        t = branch_types[0]
        for bt in branch_types[1:]:
            if bt != t:
                t = common_numeric_type(t, bt)
        return t
    if isinstance(e, Func):
        if e.name in ("vec_l2", "vec_ip", "vec_cosine"):
            return DataType.float32()
        if e.name in ("extract_year", "extract_month", "extract_day"):
            return DataType.int32()
        if e.name in ("like", "prefix", "contains", "fts_match",
                      "json_valid"):
            return BOOL
        if e.name in ("json_extract", "json_unquote", "json_type"):
            # path misses / invalid docs yield SQL NULL
            return DataType.varchar(nullable=True)
        if e.name == "json_array_length":
            return DataType.int64(nullable=True)
        if e.name in ("abs", "neg"):
            return infer_type(e.args[0], schema)
        if e.name in ("least", "greatest"):
            t = infer_type(e.args[0], schema)
            for a in e.args[1:]:
                t = common_numeric_type(t, infer_type(a, schema))
            return t
        if e.name == "substr" or e.name in CASE_FUNC_IMPL:
            return DataType.varchar(infer_type(e.args[0], schema).nullable)
        raise NotImplementedError(f"function {e.name}")
    raise NotImplementedError(type(e))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _parse_date(s: str) -> int:
    return int(np.datetime64(s, "D").astype(np.int64))


def _like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


# every function evaluable as a per-dictionary-value transform (the
# string-view family): ONE list shared by type inference, projection
# derivation, value-context errors, and the planner's group-key
# pre-projection — add new string functions here once
STRING_VIEW_FUNCS = (
    "substr", "json_extract", "json_unquote", "json_type",
    "lower", "upper", "trim",
)
# host implementations of the simple case/space transforms
CASE_FUNC_IMPL = {"lower": str.lower, "upper": str.upper, "trim": str.strip}


def _merge_valid(*vs):
    vs = [v for v in vs if v is not None]
    if not vs:
        return None
    out = vs[0]
    for v in vs[1:]:
        out = out & v
    return out


def _rescale_decimal(vals, from_scale: int, to_scale: int):
    if to_scale == from_scale:
        return vals
    if to_scale > from_scale:
        return vals.astype(jnp.int64) * (10 ** (to_scale - from_scale))
    # scale down, SQL round-half-away-from-zero (sign-aware)
    f = 10 ** (from_scale - to_scale)
    half = f // 2
    return jnp.where(vals >= 0, (vals + half) // f, -((-vals + half) // f))


def _literal_as(value, target: DataType, batch: ColumnBatch, col_name: str | None):
    """Materialize a python literal in the physical domain of `target`.

    Single source of truth is bind_value: traced constants and bound
    plan-cache parameters MUST land in bit-identical physical domains."""
    if value is None:
        return None
    if target.kind is TypeKind.VARCHAR:
        raise AssertionError("string literals handled by dictionary paths")
    return jnp.asarray(bind_value(value, target))


# ---------------------------------------------------------------------------
# evaluation (runs under jit tracing)
# ---------------------------------------------------------------------------


def evaluate(e: Expr, batch: ColumnBatch):
    """Evaluate an expression over a batch -> (values, valid|None)."""
    schema = batch.schema

    if isinstance(e, ColRef):
        return batch.cols[e.name], batch.valid.get(e.name)

    if isinstance(e, Literal):
        t = e.dtype
        if e.value is None:
            cap = batch.capacity
            return (
                jnp.zeros(cap, dtype=t.storage_np),
                jnp.zeros(cap, dtype=jnp.bool_),
            )
        if e.slot is not None and _ACTIVE_PARAMS is not None:
            # parameterized plan: the value is a traced scalar already in
            # the literal's physical storage domain (bind_value)
            return _ACTIVE_PARAMS[e.slot], None
        if t.kind is TypeKind.VARCHAR:
            raise NotImplementedError(
                "bare string literal outside a dictionary comparison"
            )
        return _literal_as(e.value, t, batch, None), None

    if isinstance(e, BinaryOp):
        return _eval_arith(e, batch)

    if isinstance(e, Compare):
        return _eval_compare(e, batch)

    if isinstance(e, BoolOp):
        vals_valid = [evaluate(a, batch) for a in e.args]
        if e.op == "and":
            out = vals_valid[0][0]
            for v, _ in vals_valid[1:]:
                out = out & v
            # Kleene: NULL unless result decidable
            if all(vv is None for _, vv in vals_valid):
                return out, None
            known_false = jnp.zeros_like(out)
            all_valid = jnp.ones_like(out)
            for v, vv in vals_valid:
                if vv is None:
                    known_false = known_false | ~v
                    continue
                known_false = known_false | (vv & ~v)
                all_valid = all_valid & vv
            return out, all_valid | known_false
        else:
            out = vals_valid[0][0]
            for v, _ in vals_valid[1:]:
                out = out | v
            if all(vv is None for _, vv in vals_valid):
                return out, None
            known_true = jnp.zeros_like(out)
            all_valid = jnp.ones_like(out)
            for v, vv in vals_valid:
                if vv is None:
                    known_true = known_true | v
                    continue
                known_true = known_true | (vv & v)
                all_valid = all_valid & vv
            return out, all_valid | known_true

    if isinstance(e, Not):
        v, valid = evaluate(e.arg, batch)
        return ~v, valid

    if isinstance(e, IsNull):
        # string-view exprs (json_*/substr) carry NULLness in their view,
        # not in a device validity channel: fold it here
        view = (
            _string_view(e.arg, batch)
            if isinstance(e.arg, Func) else None
        )
        if view is not None:
            codes, valid, vals = view
            valid = _fold_view_nulls(codes, valid, vals)
        else:
            _, valid = evaluate(e.arg, batch)
        if valid is None:
            out = jnp.zeros(batch.capacity, dtype=jnp.bool_)
        else:
            out = ~valid
        if e.negated:
            out = ~out
        return out, None

    if isinstance(e, Cast):
        return _eval_cast(e, batch)

    if isinstance(e, Case):
        return _eval_case(e, batch)

    if isinstance(e, InList):
        return _eval_in_list(e, batch)

    if isinstance(e, Between):
        from .ir import and_

        lo = Compare(">=", e.arg, e.low)
        hi = Compare("<=", e.arg, e.high)
        v, valid = evaluate(and_(lo, hi), batch)
        return (~v if e.negated else v), valid

    if isinstance(e, Func):
        return _eval_func(e, batch)

    raise NotImplementedError(type(e))


def _numeric_align(e_left: Expr, e_right: Expr, batch: ColumnBatch):
    """Evaluate two numeric operands into a common physical domain.

    Returns (lv, rv, lvalid, rvalid, result_kind, scale) where result_kind is
    'float' or 'decimal'/'int' with the given scale (0 for pure ints).
    """
    schema = batch.schema
    lt, rt = infer_type(e_left, batch.schema), infer_type(e_right, batch.schema)
    lv, lvalid = evaluate(e_left, batch)
    rv, rvalid = evaluate(e_right, batch)

    if lt.is_float or rt.is_float:
        tgt = jnp.result_type(lv.dtype if lt.is_float else jnp.float32,
                              rv.dtype if rt.is_float else jnp.float32)
        if lt.is_decimal:
            lv = lv.astype(tgt) / lt.decimal_factor
        else:
            lv = lv.astype(tgt)
        if rt.is_decimal:
            rv = rv.astype(tgt) / rt.decimal_factor
        else:
            rv = rv.astype(tgt)
        return lv, rv, lvalid, rvalid, "float", 0

    ls = lt.scale if lt.is_decimal else 0
    rs = rt.scale if rt.is_decimal else 0
    s = max(ls, rs)
    if s > 0:
        # literals were already scaled by _literal_as via evaluate()? No —
        # Literal ints evaluate at scale 0; rescale both sides to s.
        lv = _rescale_decimal(lv, ls, s)
        rv = _rescale_decimal(rv, rs, s)
        return lv, rv, lvalid, rvalid, "decimal", s
    return lv, rv, lvalid, rvalid, "int", 0


def _eval_arith(e: BinaryOp, batch: ColumnBatch):
    out_t = infer_type(e, batch.schema)
    lt = infer_type(e.left, batch.schema)
    rt = infer_type(e.right, batch.schema)

    if e.op == "/" or out_t.is_float:
        lv, rv, lvalid, rvalid, _, _ = _numeric_align_float(e.left, e.right, batch)
        ops = {
            "+": jnp.add,
            "-": jnp.subtract,
            "*": jnp.multiply,
            "/": jnp.divide,
            "%": jnp.mod,
        }
        return ops[e.op](lv, rv), _merge_valid(lvalid, rvalid)

    if e.op == "*" and (lt.is_decimal or rt.is_decimal):
        lv, lvalid = evaluate(e.left, batch)
        rv, rvalid = evaluate(e.right, batch)
        prod = lv.astype(jnp.int64) * rv.astype(jnp.int64)
        ls = lt.scale if lt.is_decimal else 0
        rs = rt.scale if rt.is_decimal else 0
        prod = _rescale_decimal(prod, ls + rs, out_t.scale)
        return prod.astype(out_t.storage_np), _merge_valid(lvalid, rvalid)

    lv, rv, lvalid, rvalid, kind, s = _numeric_align(e.left, e.right, batch)
    tgt = out_t.storage_np
    lv = lv.astype(tgt)
    rv = rv.astype(tgt)
    if e.op == "+":
        out = lv + rv
    elif e.op == "-":
        out = lv - rv
    elif e.op == "*":
        out = lv * rv
    elif e.op == "%":
        out = jnp.where(rv != 0, lv % jnp.where(rv == 0, 1, rv), 0)
    else:
        raise NotImplementedError(e.op)
    return out, _merge_valid(lvalid, rvalid)


def _numeric_align_float(e_left: Expr, e_right: Expr, batch: ColumnBatch):
    lt, rt = infer_type(e_left, batch.schema), infer_type(e_right, batch.schema)
    lv, lvalid = evaluate(e_left, batch)
    rv, rvalid = evaluate(e_right, batch)
    tgt = jnp.float64 if (lt.kind is TypeKind.FLOAT64 or rt.kind is TypeKind.FLOAT64
                          or not (lt.is_float or rt.is_float)) else jnp.float32
    if lt.is_decimal:
        lv = lv.astype(tgt) / lt.decimal_factor
    else:
        lv = lv.astype(tgt)
    if rt.is_decimal:
        rv = rv.astype(tgt) / rt.decimal_factor
    else:
        rv = rv.astype(tgt)
    return lv, rv, lvalid, rvalid, "float", 0


_CMP = {
    "=": lambda a, b: a == b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}

def _eval_compare(e: Compare, batch: ColumnBatch):
    lt = infer_type(e.left, batch.schema)
    rt = infer_type(e.right, batch.schema)

    # date vs 'YYYY-MM-DD' string literal: parse on host, compare as int days
    if lt.kind is TypeKind.DATE and isinstance(e.right, Literal) and isinstance(e.right.value, str):
        lv, lvalid = evaluate(e.left, batch)
        rv = _literal_as(e.right.value, lt, batch, None)
        return _CMP[e.op](lv, rv), lvalid
    if rt.kind is TypeKind.DATE and isinstance(e.left, Literal) and isinstance(e.left.value, str):
        rv, rvalid = evaluate(e.right, batch)
        lv = _literal_as(e.left.value, rt, batch, None)
        return _CMP[e.op](lv, rv), rvalid

    # --- dictionary string comparisons -------------------------------
    if lt.kind is TypeKind.VARCHAR or rt.kind is TypeKind.VARCHAR:
        if isinstance(e.right, Literal) and isinstance(e.left, ColRef):
            return _dict_compare(e.left, e.op, e.right.value, batch)
        if isinstance(e.left, Literal) and isinstance(e.right, ColRef):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
            op = flip.get(e.op, e.op)
            return _dict_compare(e.right, op, e.left.value, batch)
        # string transforms (substr) vs literal: boolean LUT over the view
        if isinstance(e.right, Literal):
            view = _string_view(e.left, batch)
            if view is not None:
                codes, valid, vals = view
                valid = _fold_view_nulls(codes, valid, vals)
                lut = np.fromiter(
                    (
                        False if v is None else _CMP[e.op](v, e.right.value)
                        for v in vals
                    ),
                    dtype=np.bool_, count=len(vals),
                )
                n = max(len(vals) - 1, 0)
                return jnp.asarray(lut)[jnp.clip(codes, 0, n)], valid
        if lt.kind is TypeKind.VARCHAR and rt.kind is TypeKind.VARCHAR:
            # col-vs-col code comparison is only sound when both columns
            # share one dictionary object (e.g. post-join copies); distinct
            # dictionaries assign incomparable codes.
            if (
                isinstance(e.left, ColRef)
                and isinstance(e.right, ColRef)
                and batch.dicts.get(e.left.name) is not batch.dicts.get(e.right.name)
            ):
                raise NotImplementedError(
                    f"varchar comparison {e.left.name} vs {e.right.name}: "
                    "columns use different dictionaries; requires dictionary "
                    "translation (not yet implemented)"
                )
            lv, lvalid = evaluate(e.left, batch)
            rv, rvalid = evaluate(e.right, batch)
            return _CMP[e.op](lv, rv), _merge_valid(lvalid, rvalid)
        raise NotImplementedError("varchar comparison form")

    lv, rv, lvalid, rvalid, _, _ = _numeric_align(e.left, e.right, batch)
    return _CMP[e.op](lv, rv), _merge_valid(lvalid, rvalid)


def _dict_compare(col_expr: ColRef, op: str, value: str, batch: ColumnBatch):
    d = batch.dicts.get(col_expr.name)
    if d is None:
        raise KeyError(f"no dictionary for varchar column {col_expr.name}")
    codes, valid = evaluate(col_expr, batch)
    if d.sorted and op in ("<", "<=", ">", ">="):
        import bisect

        vals = d.values()
        if op in ("<", ">="):
            thr = bisect.bisect_left(vals, value)
            out = codes < thr if op == "<" else codes >= thr
        else:
            thr = bisect.bisect_right(vals, value)
            out = codes < thr if op == "<=" else codes >= thr
        return out, valid
    if op in ("=", "=="):
        code = d.encode_one(value, add=False)
        return codes == jnp.asarray(code, dtype=jnp.int32), valid
    if op in ("!=", "<>"):
        code = d.encode_one(value, add=False)
        return codes != jnp.asarray(code, dtype=jnp.int32), valid
    # general fallback: boolean LUT over dictionary values
    lut = np.fromiter(
        (_CMP[op](v, value) for v in d.values()), dtype=np.bool_, count=len(d)
    )
    return jnp.asarray(lut)[jnp.clip(codes, 0, max(len(d) - 1, 0))], valid


def _eval_cast(e: Cast, batch: ColumnBatch):
    src_t = infer_type(e.arg, batch.schema)
    dst = e.dtype
    if src_t.kind is TypeKind.VARCHAR and dst.kind is not TypeKind.VARCHAR:
        # string -> number through the dictionary: parse each DISTINCT
        # value once into a numeric LUT (unparseable -> SQL NULL); this is
        # what makes predicates on extracted JSON scalars pushable —
        # CAST(j->>'$.price' AS decimal) compiles to one gather + compare
        view = _string_view(e.arg, batch)
        if view is None:
            raise NotImplementedError(
                f"CAST from varchar requires a dictionary view: {e.arg}")
        codes, valid, vals = view

        def parse(v):
            if v is None:
                return None
            try:
                return float(v)
            except ValueError:
                return None

        nums = [parse(v) for v in vals]
        nn = np.fromiter(
            (x is not None for x in nums), dtype=np.bool_,
            count=len(nums),
        )
        fl = np.fromiter(
            (0.0 if x is None else x for x in nums), dtype=np.float64,
            count=len(nums),
        )
        n = max(len(vals) - 1, 0)
        cl = jnp.clip(codes, 0, n)
        fv = jnp.asarray(fl)[cl]
        valid = _merge_valid(valid, jnp.asarray(nn)[cl])
        if dst.is_decimal:
            out = jnp.round(fv * dst.decimal_factor).astype(dst.storage_np)
        elif dst.is_integer:
            out = jnp.round(fv).astype(dst.storage_np)
        else:
            out = fv.astype(dst.storage_np)
        return out, valid
    v, valid = evaluate(e.arg, batch)
    if src_t.is_decimal and dst.is_decimal:
        return _rescale_decimal(v, src_t.scale, dst.scale).astype(dst.storage_np), valid
    if src_t.is_decimal and dst.is_float:
        return (v.astype(dst.storage_np) / src_t.decimal_factor), valid
    if src_t.is_decimal and dst.is_integer:
        return _rescale_decimal(v, src_t.scale, 0).astype(dst.storage_np), valid
    if dst.is_decimal:
        if src_t.is_float:
            return jnp.round(v * dst.decimal_factor).astype(dst.storage_np), valid
        return (v.astype(dst.storage_np) * dst.decimal_factor), valid
    return v.astype(dst.storage_np), valid


def _eval_case(e: Case, batch: ColumnBatch):
    out_t = infer_type(e, batch.schema)
    np_dt = out_t.storage_np
    if e.default is not None:
        out, out_valid = evaluate(Cast(e.default, out_t), batch)
    else:
        out = jnp.zeros(batch.capacity, dtype=np_dt)
        out_valid = jnp.zeros(batch.capacity, dtype=jnp.bool_)
    for cond, val in reversed(e.whens):
        c, cvalid = evaluate(cond, batch)
        take = c if cvalid is None else (c & cvalid)
        v, vvalid = evaluate(Cast(val, out_t), batch)
        out = jnp.where(take, v, out)
        if out_valid is not None or vvalid is not None:
            ov = out_valid if out_valid is not None else jnp.ones(batch.capacity, jnp.bool_)
            vv = vvalid if vvalid is not None else jnp.ones(batch.capacity, jnp.bool_)
            out_valid = jnp.where(take, vv, ov)
    return out, out_valid


def _eval_in_list(e: InList, batch: ColumnBatch):
    t = infer_type(e.arg, batch.schema)
    if t.kind is TypeKind.VARCHAR:
        view = _string_view(e.arg, batch)
        if view is None:
            raise NotImplementedError(f"IN over varchar expr {e.arg}")
        codes, valid, vals = view
        valid = _fold_view_nulls(codes, valid, vals)
        members = set(e.values)
        lut = np.fromiter(
            (v is not None and v in members for v in vals),
            dtype=np.bool_, count=len(vals),
        )
        out = jnp.asarray(lut)[jnp.clip(codes, 0, max(len(vals) - 1, 0))]
        return (~out if e.negated else out), valid
    v, valid = evaluate(e.arg, batch)
    out = jnp.zeros(batch.capacity, dtype=jnp.bool_)
    for item in e.values:
        out = out | (v == _literal_as(item, t, batch, None))
    return (~out if e.negated else out), valid


# --- date decomposition (Howard Hinnant's civil-from-days, branch-free) ----


def _civil_from_days(days):
    z = days.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _string_view(e: Expr, batch: ColumnBatch):
    """A 'string view' of an expression: (codes, valid, per-code values).

    Works for a dictionary-encoded column or a host-computable string
    transform of one (substr with literal bounds). The per-code value list
    lets predicates become boolean LUTs indexed by code — the TPU-friendly
    compile of string functions (strings never reach the device; this is the
    global-dictionary analog of the reference's dict-encoded pushdowns,
    storage/blocksstable/encoding/ob_dict_decoder_simd.cpp).
    """
    if isinstance(e, ColRef):
        d = batch.dicts.get(e.name)
        if d is None:
            return None
        codes, valid = evaluate(e, batch)
        return codes, valid, list(d.values())
    if isinstance(e, Func) and e.name == "substr":
        base = _string_view(e.args[0], batch)
        if base is None:
            return None
        codes, valid, vals = base
        if not (isinstance(e.args[1], Literal) and isinstance(e.args[2], Literal)):
            return None
        s0 = int(e.args[1].value) - 1  # SQL is 1-based
        length = int(e.args[2].value)
        if length >= 0:
            vals2 = [None if v is None else v[s0 : s0 + length] for v in vals]
        else:
            vals2 = [None if v is None else v[s0:] for v in vals]
        return codes, valid, vals2
    if isinstance(e, Func) and e.name in CASE_FUNC_IMPL:
        # case mapping / trimming once per DISTINCT value: the engine's
        # answer to case-insensitive collations (ob_charset.h) — compare /
        # group / join on lower(col) instead of a per-row collation sweep
        base = _string_view(e.args[0], batch)
        if base is None:
            return None
        codes, valid, vals = base
        f = CASE_FUNC_IMPL[e.name]
        return codes, valid, [None if v is None else f(v) for v in vals]
    if isinstance(e, Func) and e.name in (
        "json_extract", "json_unquote", "json_type"
    ):
        # JSON transforms compose through the view like substr: evaluated
        # once per DISTINCT document, rows map by code; a None in vals is
        # SQL NULL and is folded into `valid` by _fold_view_nulls at the
        # consumer boundary (ob_expr_json_extract.cpp evaluates per row —
        # the columnar LUT is the redesign)
        from .jsonpath import (
            extract_repr,
            json_type_of,
            parse_path,
            unquote,
        )

        base = _string_view(e.args[0], batch)
        if base is None:
            return None
        codes, valid, vals = base
        if e.name == "json_extract":
            if not isinstance(e.args[1], Literal):
                return None
            steps = parse_path(str(e.args[1].value))
            vals2 = [
                None if v is None else extract_repr(v, steps) for v in vals
            ]
        elif e.name == "json_unquote":
            vals2 = [unquote(v) for v in vals]
        else:
            vals2 = [json_type_of(v) for v in vals]
        return codes, valid, vals2
    return None


def _fold_view_nulls(codes, valid, vals):
    """NULL results in a string view (None entries) become row-level
    invalidity; remaining values are safe to feed LUT builders."""
    if any(v is None for v in vals):
        nn = np.fromiter(
            (v is not None for v in vals), dtype=np.bool_, count=len(vals)
        )
        notnull = jnp.asarray(nn)[jnp.clip(codes, 0, max(len(vals) - 1, 0))]
        valid = _merge_valid(valid, notnull)
    return valid


def derive_dict_column(e: Expr, batch: ColumnBatch):
    """Materialize a string-transform expr as a NEW dict column:
    (codes, valid, Dictionary). Used by projections so downstream operators
    (group-by, joins, output decode) see an ordinary dict column."""
    from ..core.dictionary import Dictionary

    if not (isinstance(e, Func) and e.name in STRING_VIEW_FUNCS):
        return None
    view = _string_view(e, batch)
    if view is None:
        return None
    codes, valid, vals = view
    valid = _fold_view_nulls(codes, valid, vals)
    safe = ["" if v is None else v for v in vals]  # NULL rows are invalid
    d2, mapping = Dictionary.from_strings_bulk(np.asarray(safe, dtype=str))
    lut = jnp.asarray(mapping.astype(np.int32))
    n = max(len(vals) - 1, 0)
    return lut[jnp.clip(codes, 0, n)], valid, d2


def _eval_func(e: Func, batch: ColumnBatch):
    if e.name in ("extract_year", "extract_month", "extract_day"):
        v, valid = evaluate(e.args[0], batch)
        y, m, d = _civil_from_days(v)
        return {"extract_year": y, "extract_month": m, "extract_day": d}[e.name], valid

    if e.name == "like":
        col_expr, pat = e.args
        assert isinstance(col_expr, ColRef) and isinstance(pat, Literal)
        d = batch.dicts[col_expr.name]
        rx = _like_to_regex(str(pat.value))
        lut = np.fromiter(
            (rx.match(v) is not None for v in d.values()),
            dtype=np.bool_,
            count=len(d),
        )
        codes, valid = evaluate(col_expr, batch)
        return jnp.asarray(lut)[jnp.clip(codes, 0, max(len(d) - 1, 0))], valid

    if e.name == "fts_match":
        # word-level full-text match against a dict-encoded column: the
        # dictionary IS the index (reference: src/storage/fts tokenizes
        # raw rows into an inverted index; here every distinct value
        # tokenizes ONCE into a boolean LUT and rows match by code)
        col_expr, q = e.args
        assert isinstance(col_expr, ColRef) and isinstance(q, Literal)
        d = batch.dicts[col_expr.name]
        want = [t for t in str(q.value).lower().split() if t]
        lut = np.fromiter(
            (
                all(t in v.lower().split() for t in want)
                for v in d.values()
            ),
            dtype=np.bool_,
            count=len(d),
        )
        codes, valid = evaluate(col_expr, batch)
        return jnp.asarray(lut)[jnp.clip(codes, 0, max(len(d) - 1, 0))], valid

    if e.name == "json_valid":
        view = _string_view(e.args[0], batch)
        if view is None:
            raise NotImplementedError("json_valid needs a dictionary view")
        from .jsonpath import is_valid

        codes, valid, vals = view
        lut = np.fromiter(
            (v is not None and is_valid(v) for v in vals),
            dtype=np.bool_, count=len(vals),
        )
        return jnp.asarray(lut)[jnp.clip(codes, 0, max(len(vals) - 1, 0))], valid

    if e.name == "json_array_length":
        from .jsonpath import array_length, parse_path

        view = _string_view(e.args[0], batch)
        if view is None:
            raise NotImplementedError(
                "json_array_length needs a dictionary view")
        codes, valid, vals = view
        steps = (
            parse_path(str(e.args[1].value)) if len(e.args) > 1 else ()
        )
        lens = [None if v is None else array_length(v, steps) for v in vals]
        valid = _fold_view_nulls(codes, valid, lens)
        lut = np.fromiter(
            (0 if x is None else x for x in lens), dtype=np.int64,
            count=len(lens),
        )
        return jnp.asarray(lut)[jnp.clip(codes, 0, max(len(vals) - 1, 0))], valid

    if e.name in STRING_VIEW_FUNCS and e.name != "substr":
        # value context without a dictionary sink (e.g. a join key):
        # unreachable from projections (derive_dict_column handles those)
        raise NotImplementedError(
            f"{e.name} used where a dictionary column cannot form")

    if e.name in ("prefix", "contains"):
        col_expr, pat = e.args
        assert isinstance(col_expr, ColRef) and isinstance(pat, Literal)
        d = batch.dicts[col_expr.name]
        p = str(pat.value)
        test = (lambda v: v.startswith(p)) if e.name == "prefix" else (lambda v: p in v)
        lut = np.fromiter((test(v) for v in d.values()), dtype=np.bool_, count=len(d))
        codes, valid = evaluate(col_expr, batch)
        return jnp.asarray(lut)[jnp.clip(codes, 0, max(len(d) - 1, 0))], valid

    if e.name in ("vec_l2", "vec_ip", "vec_cosine"):
        # vector distances in matmul form (the n*d work lands on the MXU
        # instead of a VPU sweep): squared L2 = ||x||^2 - 2 x.q + ||q||^2;
        # vec_ip = NEGATIVE inner product and vec_cosine = 1 - cosine
        # similarity, both oriented so ORDER BY <dist> ASC LIMIT k means
        # "nearest" for every metric. Used by the brute-force exact path
        # (plain TopN) and IVF candidate re-ranking.
        xv, valid = evaluate(e.args[0], batch)
        q = evaluate_vector_literal(e.args[1])
        xq = xv @ q
        if e.name == "vec_ip":
            return -xq, valid
        if e.name == "vec_cosine":
            xn = jnp.sqrt(jnp.sum(xv * xv, axis=1))
            qn = jnp.sqrt(jnp.sum(q * q))
            return 1.0 - xq / jnp.maximum(xn * qn, 1e-30), valid
        xn = jnp.sum(xv * xv, axis=1)
        return xn - 2.0 * xq + jnp.sum(q * q), valid
    if e.name == "abs":
        v, valid = evaluate(e.args[0], batch)
        return jnp.abs(v), valid
    if e.name == "neg":
        v, valid = evaluate(e.args[0], batch)
        return -v, valid
    if e.name in ("least", "greatest"):
        op = jnp.minimum if e.name == "least" else jnp.maximum
        v, valid = evaluate(e.args[0], batch)
        for a in e.args[1:]:
            v2, valid2 = evaluate(a, batch)
            v = op(v, v2)
            valid = _merge_valid(valid, valid2)
        return v, valid
    raise NotImplementedError(f"function {e.name}")


def compile_predicate(e: Expr, batch: ColumnBatch) -> jnp.ndarray:
    """Predicate -> bool mask over the batch; NULL results reject the row."""
    v, valid = evaluate(e, batch)
    mask = v if valid is None else (v & valid)
    return mask & batch.sel
