"""JSON path evaluation over dictionary-encoded documents.

Reference surface: the ob_expr_json_* family under src/sql/engine/expr/
(ob_expr_json_extract.cpp, ob_expr_json_unquote.cpp, ...) and the
ObJsonPath parser (src/share/json_type). The reference evaluates paths
per ROW over a binary JSON format; the columnar rebuild evaluates each
path ONCE per DISTINCT document (documents are dict-encoded varchar, so
the dictionary is the set of distinct docs) and rows map through their
int32 codes — the same LUT recipe as every string function in
expr/compile.py. Parsing cost is O(distinct docs), device cost is one
gather.

Path grammar (the MySQL subset that covers the ob_expr_json tests):
    $                whole document
    .key   ."a b"    object member
    [N]              array element (non-negative)
Chained arbitrarily: $.a.b[0]."c d".
"""

from __future__ import annotations

import json


class JsonPathError(ValueError):
    pass


_MISSING = object()  # sentinel: path not present (differs from JSON null)


def parse_path(path: str) -> tuple:
    """'$' '.key' '[0]' chain -> tuple of steps (str member | int index)."""
    s = path.strip()
    if not s.startswith("$"):
        raise JsonPathError(f"JSON path must start with $: {path!r}")
    i, steps = 1, []
    while i < len(s):
        c = s[i]
        if c == ".":
            i += 1
            if i < len(s) and s[i] == '"':
                # backslash escapes inside quoted members ($."a\"b")
                j, buf = i + 1, []
                while j < len(s) and s[j] != '"':
                    if s[j] == "\\" and j + 1 < len(s):
                        buf.append(s[j + 1])
                        j += 2
                    else:
                        buf.append(s[j])
                        j += 1
                if j >= len(s):
                    raise JsonPathError(f"unterminated quote in {path!r}")
                steps.append("".join(buf))
                i = j + 1
            else:
                j = i
                while j < len(s) and s[j] not in ".[":
                    j += 1
                if j == i:
                    raise JsonPathError(f"empty member name in {path!r}")
                steps.append(s[i:j])
                i = j
        elif c == "[":
            j = s.find("]", i)
            if j < 0:
                raise JsonPathError(f"missing ] in {path!r}")
            idx = s[i + 1:j].strip()
            if not idx.isdigit():
                raise JsonPathError(f"bad array index in {path!r}")
            steps.append(int(idx))
            i = j + 1
        else:
            raise JsonPathError(f"unexpected {c!r} in {path!r}")
    return tuple(steps)


def _walk(doc, steps):
    cur = doc
    for st in steps:
        if isinstance(st, str):
            if not isinstance(cur, dict) or st not in cur:
                return _MISSING
            cur = cur[st]
        else:
            if not isinstance(cur, list) or st >= len(cur):
                return _MISSING
            cur = cur[st]
    return cur


def json_repr(v) -> str:
    """MySQL-style JSON text (', '/': ' separators, like JSON_OBJECT)."""
    return json.dumps(v, separators=(", ", ": "), ensure_ascii=False)


def extract_repr(doc_text: str, steps: tuple) -> str | None:
    """json_extract: JSON representation of the value at path, or None
    (SQL NULL) when the document is invalid or the path is missing."""
    try:
        doc = json.loads(doc_text)
    except (ValueError, TypeError):
        return None
    v = _walk(doc, steps)
    if v is _MISSING:
        return None
    return json_repr(v)


def unquote(json_text: str | None) -> str | None:
    """json_unquote: a quoted JSON string loses its quotes; everything
    else (numbers, objects, arrays, true/false/null) keeps its JSON text.
    SQL NULL propagates."""
    if json_text is None:
        return None
    t = json_text.strip()
    if t.startswith('"'):
        try:
            v = json.loads(t)
        except ValueError:
            return json_text
        if isinstance(v, str):
            return v
    return json_text


def json_type_of(json_text: str | None) -> str | None:
    """json_type over a JSON text fragment (MySQL type names)."""
    if json_text is None:
        return None
    try:
        v = json.loads(json_text)
    except (ValueError, TypeError):
        return None
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def array_length(doc_text: str, steps: tuple = ()) -> int | None:
    try:
        doc = json.loads(doc_text)
    except (ValueError, TypeError):
        return None
    v = _walk(doc, steps)
    if v is _MISSING or not isinstance(v, list):
        return None
    return len(v)


def is_valid(doc_text: str) -> bool:
    try:
        json.loads(doc_text)
        return True
    except (ValueError, TypeError):
        return False
