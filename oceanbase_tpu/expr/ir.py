"""Expression IR.

The analog of OceanBase's ObRawExpr trees (sql/resolver/expr/ob_raw_expr.h)
and their compiled ObExpr form (sql/engine/expr/ob_expr.h:447). The reference
maintains three eval modes per expr (scalar, batch, rich-vector,
ob_expr.h:888-898) plus a 552-file library of eval functions; the TPU rebuild
needs exactly one mode — whole-batch evaluation compiled through XLA — so the
IR stays small and the "eval function table" is the compiler in
expr/compile.py.

Nodes are frozen/hashable: expression identity participates in plan-cache
keys (reference: sql/plan_cache parameterized keys).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.dtypes import DataType


class Expr:
    """Base class for expression nodes."""

    __slots__ = ()


@dataclass(frozen=True)
class ColRef(Expr):
    name: str

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class Literal(Expr):
    """A constant. When `slot` is set, the literal is a plan-cache parameter:
    its value arrives at run time as a traced scalar (expr/compile.py
    bind_params), so one XLA executable serves every literal value of the
    same type — the TPU analog of ObPlanCache's parameterized plans
    (sql/plan_cache/ob_plan_cache.h:227), where recompilation is seconds,
    not microseconds. `value` keeps the first-seen constant for host-side
    decisions and unparameterized evaluation."""

    value: object  # python int/float/str/bool/None
    dtype: DataType
    slot: int | None = None

    def __str__(self):
        if self.slot is not None:
            return f"?{self.slot}"
        return repr(self.value)

    def __repr__(self):
        # slotted literals must repr independent of their first-seen value:
        # plan fingerprints (sql/plan_cache.plan_fingerprint) feed on repr,
        # and a value leak would defeat parameterized plan sharing
        if self.slot is not None:
            return f"Literal(?{self.slot}, {self.dtype})"
        return f"Literal({self.value!r}, {self.dtype})"


@dataclass(frozen=True)
class BinaryOp(Expr):
    """Arithmetic: + - * / %  (decimal-aware, see compile.py)."""

    op: str
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass(frozen=True)
class Compare(Expr):
    """Comparison: = != < <= > >= producing BOOL with 3-valued nulls."""

    op: str
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass(frozen=True)
class BoolOp(Expr):
    """AND / OR over 2+ args with Kleene semantics."""

    op: str  # 'and' | 'or'
    args: tuple[Expr, ...]

    def __str__(self):
        return "(" + f" {self.op} ".join(map(str, self.args)) + ")"


@dataclass(frozen=True)
class Not(Expr):
    arg: Expr

    def __str__(self):
        return f"(not {self.arg})"


@dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr
    negated: bool = False


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    dtype: DataType

    def __str__(self):
        return f"cast({self.arg} as {self.dtype})"


@dataclass(frozen=True)
class Case(Expr):
    """CASE WHEN c1 THEN v1 ... ELSE d END."""

    whens: tuple[tuple[Expr, Expr], ...]
    default: Expr | None = None


@dataclass(frozen=True)
class InList(Expr):
    arg: Expr
    values: tuple[object, ...]
    negated: bool = False


@dataclass(frozen=True)
class Between(Expr):
    arg: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass(frozen=True)
class Func(Expr):
    """Scalar function call.

    Supported names (grown as the SQL surface grows):
      extract_year, extract_month, extract_day  — on DATE
      like                                      — args (col, pattern-literal);
                                                  evaluated against the host
                                                  dictionary, device gather
      substr_eq / prefix / contains             — dict-string helpers
      abs, neg, least, greatest
    """

    name: str
    args: tuple[Expr, ...]

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


# ---- convenience builders -------------------------------------------------


def col(name: str) -> ColRef:
    return ColRef(name)


def lit(value, dtype: DataType | None = None) -> Literal:
    from ..core.dtypes import BOOL, FLOAT64, INT64, VARCHAR, DataType as DT

    if dtype is None:
        if isinstance(value, bool):
            dtype = BOOL
        elif isinstance(value, int):
            dtype = INT64
        elif isinstance(value, float):
            # SQL semantics: a literal with a decimal point is DECIMAL, not
            # float (exact). Critical on TPU where float division is an
            # approximate reciprocal: 0.05 as f32 would misclassify
            # decimal-column comparisons. Fall back to FLOAT64 only when the
            # value doesn't fit an exact short decimal.
            from decimal import Decimal

            d = Decimal(repr(value))
            exp = -d.as_tuple().exponent
            digits = len(d.as_tuple().digits)
            if 0 <= exp <= 6 and digits <= 18:
                dtype = DT.decimal(max(digits, exp + 1), exp)
            else:
                dtype = FLOAT64
        elif isinstance(value, str):
            dtype = VARCHAR
        elif value is None:
            dtype = DT.int64(nullable=True)
        else:
            raise TypeError(f"cannot infer literal type for {value!r}")
    return Literal(value, dtype)


def and_(*args: Expr) -> Expr:
    flat: list[Expr] = []
    for a in args:
        if isinstance(a, BoolOp) and a.op == "and":
            flat.extend(a.args)
        else:
            flat.append(a)
    return flat[0] if len(flat) == 1 else BoolOp("and", tuple(flat))


def or_(*args: Expr) -> Expr:
    flat: list[Expr] = []
    for a in args:
        if isinstance(a, BoolOp) and a.op == "or":
            flat.extend(a.args)
        else:
            flat.append(a)
    return flat[0] if len(flat) == 1 else BoolOp("or", tuple(flat))


def walk(e: Expr):
    """Yield all nodes in the expression tree (pre-order)."""
    yield e
    children: tuple[Expr, ...] = ()
    if isinstance(e, (BinaryOp, Compare)):
        children = (e.left, e.right)
    elif isinstance(e, BoolOp):
        children = e.args
    elif isinstance(e, (Not, IsNull, Cast)):
        children = (e.arg,)
    elif isinstance(e, Case):
        children = tuple(x for w in e.whens for x in w) + (
            (e.default,) if e.default is not None else ()
        )
    elif isinstance(e, InList):
        children = (e.arg,)
    elif isinstance(e, Between):
        children = (e.arg, e.low, e.high)
    elif isinstance(e, Func):
        children = e.args
    for c in children:
        yield from walk(c)


def referenced_columns(e: Expr) -> set[str]:
    return {n.name for n in walk(e) if isinstance(n, ColRef)}
