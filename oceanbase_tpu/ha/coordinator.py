"""Failure detection feeding leadership placement.

Reference surface: logservice/leader_coordinator — ObFailureDetector
(ob_failure_detector.h:48) aggregates local health events (clog disk hang,
schema refresh stuck, RS connectivity) and feeds the election priority so
a sick node's leaders demote to healthy replicas within a lease window.

The rebuild keeps the two halves:
  * FailureDetector: named health checks per node; any failing check makes
    the node unhealthy (events mirror the reference's FailureEvent list);
  * LeaderCoordinator: watches every LS whose leader sits on an unhealthy
    node and hands leadership to a healthy replica via the palf
    TimeoutNow handshake (the election-priority demotion analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class FailureDetector:
    """Per-node aggregate of named health checks (True = healthy)."""

    def __init__(self):
        self._checks: dict[str, object] = {}

    def register(self, name: str, check) -> None:
        self._checks[name] = check

    def failing(self) -> list[str]:
        return [n for n, c in self._checks.items() if not c()]

    @property
    def healthy(self) -> bool:
        return not self.failing()


@dataclass
class LeaderCoordinator:
    """Moves leadership off unhealthy nodes.

    ls_groups: {ls_id: {node: LSReplica}}; detectors: {node:
    FailureDetector}. tick() starts at most one transfer per LS per call
    (transfers complete asynchronously through the consensus messages)."""

    ls_groups: dict
    detectors: dict[int, FailureDetector]
    transfers_started: int = 0
    _inflight: set = field(default_factory=set)

    def tick(self) -> int:
        started = 0
        for ls_id, group in self.ls_groups.items():
            leader_node = None
            for node, rep in group.items():
                if rep.is_leader:
                    leader_node = node
                    break
            if leader_node is None:
                self._inflight.discard(ls_id)
                continue
            det = self.detectors.get(leader_node)
            if det is None or det.healthy:
                self._inflight.discard(ls_id)
                continue
            if ls_id in self._inflight:
                continue  # handshake already underway
            target = next(
                (n for n, r in sorted(group.items())
                 if n != leader_node
                 and self.detectors.get(n) is not None
                 and self.detectors[n].healthy),
                None,
            )
            if target is None:
                continue  # nowhere healthy to go
            # transfer_leader returns False while the target is still
            # catching up (it sent a catch-up append, not TimeoutNow) —
            # keep retrying on later ticks rather than marking inflight
            if group[leader_node].palf.transfer_leader(
                group[target].palf.node_id
            ):
                self._inflight.add(ls_id)
                self.transfers_started += 1
                started += 1
        return started
