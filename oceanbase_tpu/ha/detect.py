"""Peer-death detection + orphaned-state GC.

Reference surface:
  * ObNetKeepAlive (deps/oblib/src/rpc/obrpc/ob_net_keepalive.h): every
    node pings its peers off the RPC path; a peer silent past the window
    is reported dead so RPC callers fail fast instead of timing out;
  * ObDetectManager (share/detect/ob_detect_manager.h): components
    register (peer, resource) pairs — PX tasks, DTL channels, tx contexts
    — and get a cleanup callback when the peer dies, GC'ing state that
    would otherwise leak forever.

The rebuild runs both over the deterministic LocalBus: keepalive ids live
in their own id space (KA_BASE + node) so they coexist with palf
replica handlers.
"""

from __future__ import annotations

from dataclasses import dataclass, field

KA_BASE = 10_000_000


@dataclass
class _Ping:
    t: float


@dataclass
class _Pong:
    t: float


class NetKeepAlive:
    """One node's keepalive endpoint over the bus."""

    def __init__(self, bus, node: int, peers: list[int],
                 interval: float = 0.5, dead_after: float = 2.0):
        self.bus = bus
        self.node = node
        self.my_id = KA_BASE + node
        self.peer_ids = {p: KA_BASE + p for p in peers if p != node}
        self.interval = interval
        self.dead_after = dead_after
        self._last_heard: dict[int, float] = {p: bus.now for p in self.peer_ids}
        self._last_ping = -1e9
        bus.register(self.my_id, self._on_message)

    def _on_message(self, src: int, msg) -> None:
        if isinstance(msg, _Ping):
            self.bus.send(self.my_id, src, _Pong(msg.t))
        elif isinstance(msg, _Pong):
            self._last_heard[src - KA_BASE] = self.bus.now

    def tick(self) -> None:
        if self.bus.now - self._last_ping >= self.interval:
            self._last_ping = self.bus.now
            for pid in self.peer_ids.values():
                self.bus.send(self.my_id, pid, _Ping(self.bus.now))

    def is_dead(self, peer: int) -> bool:
        return (self.bus.now - self._last_heard.get(peer, -1e9)) > self.dead_after

    def dead_peers(self) -> set[int]:
        return {p for p in self.peer_ids if self.is_dead(p)}


class DetectManager:
    """Register distributed resources against the peer that owns their
    remote half; when keepalive declares the peer dead, run the cleanups."""

    def __init__(self, keepalive: NetKeepAlive):
        self.keepalive = keepalive
        self._resources: dict[int, dict[object, object]] = {}
        self.cleaned: list[tuple[int, object]] = []

    def register(self, peer: int, resource_id, cleanup) -> None:
        self._resources.setdefault(peer, {})[resource_id] = cleanup

    def unregister(self, peer: int, resource_id) -> None:
        self._resources.get(peer, {}).pop(resource_id, None)

    def tick(self) -> int:
        """GC resources of dead peers; returns cleanups run."""
        n = 0
        for peer in list(self._resources):
            if self.keepalive.is_dead(peer):
                for rid, cleanup in self._resources.pop(peer).items():
                    cleanup()
                    self.cleaned.append((peer, rid))
                    n += 1
        return n
