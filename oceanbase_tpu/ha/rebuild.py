"""Replica rebuild: replace a dead/wrecked replica from a leader snapshot.

Reference surface: storage/high_availability — ObLSMigrationHandler
(ob_ls_migration_handler.h:88) and ObStorageHAService (ob_storage_ha_service.h:27)
rebuild/migrate replicas by copying a macro-block snapshot from a source
replica and then catching up through the log; the rootserver's disaster
recovery tasks trigger them when a replica is permanently gone.

The rebuild's analog: a dead (ls, node) replica is replaced in place —
same consensus address, so no membership change — by

  1. a consistent storage snapshot cut from the current READY leader
     (tablets + tx table + pending 2PC redo at its applied LSN; refused
     while the leader holds locally-staged uncommitted rows, exactly like
     the checkpointer);
  2. a fresh palf replica whose log starts EMPTY at base = covered+1 with
     the base-predecessor term recorded, so ordinary log replication
     back-fills everything after the snapshot (the "copy then catch up"
     shape of the reference's migration);
  3. swapping the replica into the LS group and the node's TransService.

RebuildService watches the failure detectors and triggers rebuilds for
nodes reported dead — the disaster-recovery-task analog.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field

from ..log.palf import LogView, PalfReplica
from ..tx.ls import LSReplica


class RebuildError(Exception):
    pass


def snapshot_source(leader: LSReplica) -> dict:
    """Deep-copied storage snapshot of a READY source replica."""
    if leader._locally_staged:
        raise RebuildError(
            "source leader has in-flight staged txs; retry after they end"
        )
    state = {
        "applied_lsn": leader.palf.applied_lsn,
        "tablets": leader.tablets,
        "tx_table": dict(leader.tx_table),
        "pending_redo": dict(leader._pending_redo),
    }
    return pickle.loads(pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL))


def rebuild_replica(cluster, ls_id: int, node: int,
                    data_dir: str | None = None, fsync: bool = True) -> LSReplica:
    """Rebuild the (ls, node) replica from the group's ready leader."""
    group = cluster.ls_groups[ls_id]
    old = group[node]
    addr = old.palf.node_id
    peers = list(old.palf.peers)
    leader = next(
        (r for n2, r in group.items() if n2 != node and r.is_ready), None
    )
    if leader is None:
        raise RebuildError(f"ls {ls_id}: no ready leader to copy from")
    state = snapshot_source(leader)
    covered = state["applied_lsn"]
    if covered >= leader.palf.log.base:
        prev_term = leader.palf.log[covered].term
    else:
        prev_term = leader.palf.log.base_prev_term

    # the address returns to service with a brand-new identity
    cluster.bus.revive(addr)
    store = None
    if data_dir is not None:
        import os
        import shutil

        from ..log.store import LogStore

        root = os.path.join(data_dir, f"n{node}", f"ls_{ls_id}")
        shutil.rmtree(root, ignore_errors=True)
        store = LogStore(root, fsync=fsync)
        store.set_base_info(covered, prev_term)
    palf = PalfReplica(addr, peers, cluster.bus, store=store)
    palf.log = LogView(covered + 1, [], prev_term)
    palf.commit_lsn = covered
    palf.applied_lsn = covered

    rep = LSReplica(ls_id, node, palf)
    rep.tablets = state["tablets"]
    rep.tx_table = dict(state["tx_table"])
    rep._pending_redo = dict(state["pending_redo"])
    rep.on_record = old.on_record
    rep.on_tx_applied = old.on_tx_applied

    group[node] = rep
    svc = cluster.services.get(node)
    if svc is not None:
        svc.replicas[ls_id] = rep
    return rep


@dataclass
class RebuildService:
    """Disaster-recovery task runner: rebuilds every LS replica of nodes
    their failure detectors report dead (rootserver DR-task analog)."""

    cluster: object
    detectors: dict[int, object]  # node -> ha.FailureDetector
    data_dir: str | None = None
    fsync: bool = True
    rebuilds: int = 0
    on_rebuilt: object = None  # callback(ls_id, node, replica)

    def tick(self) -> int:
        done = 0
        for node, det in self.detectors.items():
            if det.healthy:
                continue
            for ls_id, group in self.cluster.ls_groups.items():
                rep = group[node]
                # "dead" = its consensus address is disconnected
                if rep.palf.node_id not in self.cluster.bus._down:
                    continue
                try:
                    new_rep = rebuild_replica(
                        self.cluster, ls_id, node,
                        data_dir=self.data_dir, fsync=self.fsync,
                    )
                except RebuildError:
                    continue  # no ready source yet; retry next tick
                self.rebuilds += 1
                done += 1
                if self.on_rebuilt is not None:
                    self.on_rebuilt(ls_id, node, new_rep)
        return done
