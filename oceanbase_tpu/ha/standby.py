"""Standby cluster: a follower database fed continuously by the log archive.

Reference surface: logservice/restoreservice (ob_log_restore_service.h) —
a physical standby tenant starts from a backup set, tails the primary's
archived logs, replays continuously, serves reads, and PROMOTES to a
writable primary on failover.

Rebuild shape:
  * base state = restore_database(backup_root) — schema + sstable
    snapshots (DDL is meta-level, not logged; tables created after the
    backup need a fresh backup, matching the reference's restore-source
    schema version gate);
  * catch_up() tails every LS's archive through the stateful CdcClient
    cursors and applies committed transactions in commit-version order;
  * cross-LS (2PC/XA) transactions apply ATOMICALLY: a tx buffers until
    every participant LS's stream has emitted it (the TxChange carries
    the prepare record's participant list) — a lagging participant
    archive can delay a tx but never tear it;
  * reads run through ordinary sessions; every write statement is
    refused while in standby role;
  * promote() stops the tailing role and opens the database for writes
    (GTS already rides ahead of every applied commit version).
"""

from __future__ import annotations

from ..log.archive import ArchiveReader
from ..log.cdc import CdcClient, merge_streams
from ..storage import OP_DELETE, OP_PUT


class StandbyError(Exception):
    pass


_WRITE_PREFIXES = (
    "insert", "update", "delete", "create", "drop", "alter", "grant",
    "revoke", "truncate", "xa", "call", "lock", "refresh",
)


class StandbyCluster:
    def __init__(self, backup_root: str, archive_root: str,
                 n_nodes: int = 1, n_ls: int = 2):
        from ..storage.backup import restore_database

        self.archive_root = archive_root
        self.db = restore_database(backup_root, n_nodes=n_nodes, n_ls=n_ls)
        self.promoted = False
        # per-LS stateful cursors; fast-forward past what the BACKUP
        # already contains happens naturally: replayed versions at or
        # below the snapshot scn are skipped in _apply_tx
        self._cdc = {ls: CdcClient(ls) for ls in self.db.cluster.ls_groups}
        self._snapshot_scn = self.db._restore_backup_scn
        self.applied_scn = self._snapshot_scn
        # tablet id on the PRIMARY -> restored TableInfo (archived redo
        # addresses original tablet ids; restore_database records the map)
        self._by_primary_tablet = dict(self.db._restore_tablet_map)
        # per-LS FIFO of not-yet-applied changes: apply must follow each
        # stream's LOG ORDER — a held cross-LS tx BLOCKS everything behind
        # it on its stream (prefix consistency: a later tx may depend on
        # state — e.g. dictionary codes — the held tx creates)
        from collections import deque

        self._queues: dict[int, deque] = {
            ls: deque() for ls in self.db.cluster.ls_groups
        }
        self.catch_up()

    # ------------------------------------------------------------- tailing
    def catch_up(self) -> int:
        """Poll every LS archive and apply the COMPLETE PREFIX of each
        stream: single-LS txs apply in log order; a cross-LS tx applies
        only once it heads every participant's queue (atomic, and nothing
        behind it on any stream overtakes it). Returns txs applied."""
        if self.promoted:
            raise StandbyError("already promoted; standby tailing ended")
        for ls, cdc in self._cdc.items():
            self._queues[ls].extend(
                cdc.poll_archive(ArchiveReader(self.archive_root, ls)))
        ready = []
        progress = True
        while progress:
            progress = False
            for ls in sorted(self._queues):
                q = self._queues[ls]
                while q:
                    ch = q[0]
                    parts = set(ch.participants) or {ls}
                    if len(parts) <= 1:
                        ready.append(q.popleft())
                        progress = True
                        continue
                    heads_ok = all(
                        self._queues.get(p)
                        and self._queues[p][0].tx_id == ch.tx_id
                        for p in parts
                    )
                    if heads_ok:
                        for p in sorted(parts):
                            ready.append(self._queues[p].popleft())
                        progress = True
                        continue
                    break  # blocked: everything behind waits (prefix order)
        n = 0
        seen_tx = set()
        for ch in merge_streams(ready):
            self._apply_tx(ch)
            if ch.tx_id not in seen_tx:
                seen_tx.add(ch.tx_id)
                n += 1
        return n

    def _apply_tx(self, ch) -> None:
        if ch.commit_version <= self._snapshot_scn:
            return  # inside the restored snapshot already
        from ..server.database import apply_dict_appends

        db = self.db
        # dictionary growth first: row values reference the codes
        apply_dict_appends(self._by_primary_tablet, ch.dict_appends)
        touched = set()
        for row in ch.rows:
            ti = self._by_primary_tablet.get(row.tablet_id)
            if ti is None:
                continue  # table not in the backup set
            for rep in db.cluster.ls_groups[ti.ls_id].values():
                rep.tablets[ti.tablet_id].active.replay(
                    row.key, OP_PUT if row.op == "put" else OP_DELETE,
                    row.values, ch.commit_version)
            touched.add(ti.name)
        db.cluster.gts.advance_to(ch.commit_version)
        for nm in touched:
            ti = db.tables[nm]
            ti.data_version += 1
            ti.cached_data_version = -1
        self.applied_scn = max(self.applied_scn, ch.commit_version)

    # ------------------------------------------------------------- serving
    def sql(self, text: str):
        """Read-only statement surface while in standby role."""
        if self.promoted:
            raise StandbyError("promoted: use the database directly")
        head = text.lstrip().split(None, 1)
        if head and head[0].lower().rstrip(";") in _WRITE_PREFIXES:
            raise StandbyError(
                f"standby is read-only (refused {head[0].upper()})")
        return self.db.session().sql(text)

    # ------------------------------------------------------------ failover
    def promote(self):
        """End the standby role: final catch-up, then open for writes.
        Returns the now-primary Database."""
        self.catch_up()
        # a torn multi-LS tx at the failover point: the primary died
        # before every participant archived its COMMIT — the decided
        # half (and everything queued behind it) must not apply (the
        # reference resolves through the coordinator log; without it,
        # consistent = drop the tail)
        for q in self._queues.values():
            q.clear()
        self.promoted = True
        return self.db
