"""High availability & failure handling.

coordinator.py  FailureDetector + LeaderCoordinator (demote sick leaders)
detect.py       NetKeepAlive (peer-death detection) + DetectManager
                (GC of orphaned distributed state)
rebuild.py      replica rebuild from a leader snapshot + log catch-up
                (storage/high_availability migration analog)
"""

from .coordinator import FailureDetector, LeaderCoordinator
from .detect import DetectManager, NetKeepAlive
from .rebuild import RebuildError, RebuildService, rebuild_replica

__all__ = [
    "FailureDetector",
    "LeaderCoordinator",
    "NetKeepAlive",
    "DetectManager",
    "RebuildError",
    "RebuildService",
    "rebuild_replica",
]
