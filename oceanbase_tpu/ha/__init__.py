"""High availability & failure handling.

coordinator.py  FailureDetector + LeaderCoordinator (demote sick leaders)
detect.py       NetKeepAlive (peer-death detection) + DetectManager
                (GC of orphaned distributed state)
"""

from .coordinator import FailureDetector, LeaderCoordinator
from .detect import DetectManager, NetKeepAlive

__all__ = [
    "FailureDetector",
    "LeaderCoordinator",
    "NetKeepAlive",
    "DetectManager",
]
