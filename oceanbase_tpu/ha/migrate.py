"""Live replica migration + load balancing.

Reference surface: storage/high_availability — ObLSMigrationHandler
(ob_ls_migration_handler.h:88) moves a healthy replica between servers
(snapshot copy + log catch-up + member-list change) while the group keeps
serving; src/rootserver/balance drives such moves when servers join or
load skews.

Rebuild shape (reusing the rebuild machinery in ha/rebuild.py):

  1. cut a storage snapshot from the group's READY leader;
  2. start the destination replica seeded at the snapshot LSN;
  3. leader logs ADD(dst) (palf single-member config change) — ordinary
     replication back-fills dst from the snapshot point;
  4. once dst is caught up, leader logs REMOVE(src); the source replica
     is detached and its node forgets the LS.

The group serves reads/writes throughout: quorum during the 4-member
window is 3, and the leader never moves (a leader migration first
transfers leadership away).

`balance_cluster` is the rootserver balance loop: after add_node(), it
migrates replicas from the most- to the least-loaded nodes until replica
counts are level (the reference's ob_balance_group_ls_stat / LS balance)."""

from __future__ import annotations

from .rebuild import RebuildError, snapshot_source


class MigrateError(Exception):
    pass


def migrate_replica(cluster, ls_id: int, src_node: int, dst_node: int,
                    max_time: float = 30.0):
    """Move the (ls, src_node) replica to dst_node while serving."""
    from ..log.palf import LogView, PalfReplica
    from ..tx.ls import LSReplica

    group = cluster.ls_groups[ls_id]
    if dst_node in group:
        raise MigrateError(f"ls {ls_id} already has a replica on {dst_node}")
    if src_node not in group:
        raise MigrateError(f"ls {ls_id} has no replica on {src_node}")
    src = group[src_node]
    addr_src = src.palf.node_id
    base = addr_src - src_node  # group addressing: base + node id
    addr_dst = base + dst_node

    # the leader must survive the move: shift leadership off src first
    if cluster.leader_node(ls_id) == src_node:
        other = next(n for n in group if n != src_node)
        cluster.transfer_leader(ls_id, other)

    def ready_leader():
        return next((r for r in group.values() if r.is_ready), None)

    cluster.leader_node(ls_id)
    leader = ready_leader()

    # 1) snapshot (retry while the leader has in-flight staged txs)
    state = None
    def try_snap():
        nonlocal state
        try:
            state = snapshot_source(leader)
            return True
        except RebuildError:
            return False
    if not cluster.drive_until(try_snap, max_time=max_time):
        raise MigrateError(f"ls {ls_id}: leader never quiesced for snapshot")
    covered = state["applied_lsn"]
    if covered >= leader.palf.log.base:
        prev_term = leader.palf.log[covered].term
    else:
        prev_term = leader.palf.log.base_prev_term

    # 2) destination replica seeded at the snapshot point
    store = None
    if cluster.data_dir is not None:
        import os
        import shutil

        from ..log.store import LogStore

        root = os.path.join(cluster.data_dir, f"n{dst_node}", f"ls_{ls_id}")
        shutil.rmtree(root, ignore_errors=True)
        store = LogStore(root, fsync=cluster.fsync)
        store.set_base_info(covered, prev_term)
    palf = PalfReplica(
        addr_dst, list(leader.palf.peers) + [addr_dst], cluster.bus,
        store=store,
    )
    palf.log = LogView(covered + 1, [], prev_term)
    palf.commit_lsn = covered
    palf.applied_lsn = covered
    rep = LSReplica(ls_id, dst_node, palf)
    rep.tablets = state["tablets"]
    rep.tx_table = dict(state["tx_table"])
    rep._pending_redo = dict(state["pending_redo"])
    rep.on_record = src.on_record
    group[dst_node] = rep
    svc = cluster.services.get(dst_node)
    if svc is not None:
        svc.replicas[ls_id] = rep
        # the dst node's TransService must learn about applies on its new
        # replica (tx completion acks); src's chained callback stays as
        # prev so tenant observers keep firing (foreign tx ids are
        # ignored by the src service's lookup)
        rep.on_tx_applied = svc._make_applied_cb(ls_id, src.on_tx_applied)
    else:
        rep.on_tx_applied = src.on_tx_applied

    # 3) ADD(dst), drive to commit + dst catch-up
    add_lsn = leader.palf.submit_config(
        list(leader.palf.peers) + [addr_dst])
    if add_lsn is None:
        raise MigrateError("leader lost leadership during ADD")
    ok = cluster.drive_until(
        lambda: rep.palf.commit_lsn >= add_lsn
        and rep.palf.applied_lsn >= add_lsn,
        max_time=max_time,
    )
    if not ok:
        raise MigrateError(f"ls {ls_id}: dst never caught up past ADD")

    # 4) REMOVE(src), detach. The lease can lapse between steps: drive
    # until a ready leader exists again (and retry the submit on it)
    rm_holder: list = [None]

    def try_remove():
        lead = ready_leader()
        if lead is None:
            return False
        lsn = lead.palf.submit_config(
            [p for p in lead.palf.peers if p != addr_src])
        if lsn is None:
            return False
        rm_holder[0] = (lead, lsn)
        return True

    if not cluster.drive_until(try_remove, max_time=max_time):
        raise MigrateError("no ready leader to log REMOVE")
    leader2, rm_lsn = rm_holder[0]
    ok = cluster.drive_until(
        lambda: leader2.palf.commit_lsn >= rm_lsn, max_time=max_time
    )
    if not ok:
        raise MigrateError(f"ls {ls_id}: REMOVE never committed")
    cluster.bus.kill(addr_src)  # the retired address goes dark
    del group[src_node]
    src_svc = cluster.services.get(src_node)
    if src_svc is not None:
        src_svc.replicas.pop(ls_id, None)
    return rep


def replica_counts(cluster) -> dict[int, int]:
    counts = {n: 0 for n in cluster.services}
    for g in cluster.ls_groups.values():
        for n in g:
            counts[n] = counts.get(n, 0) + 1
    return counts


def balance_cluster(cluster, max_moves: int = 64) -> int:
    """Migrate replicas from the most- to the least-loaded nodes until
    per-node replica counts are level (spread <= 1). Returns moves made."""
    moves = 0
    while moves < max_moves:
        counts = replica_counts(cluster)
        hi = max(counts, key=lambda n: counts[n])
        lo = min(counts, key=lambda n: counts[n])
        if counts[hi] - counts[lo] <= 1:
            return moves
        # an LS hosted on hi but not on lo
        ls_id = next(
            (ls for ls, g in cluster.ls_groups.items()
             if hi in g and lo not in g),
            None,
        )
        if ls_id is None:
            return moves
        migrate_replica(cluster, ls_id, hi, lo)
        moves += 1
    return moves
