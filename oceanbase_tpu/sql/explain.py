"""EXPLAIN: render the planned (and physically-routed) operator tree.

Reference surface: the plan printer (sql/printer, EXPLAIN [FORMAT=...])
— the operator tree with estimated rows and physical choices. Here the
annotations surface THIS engine's physical decisions: which join rides
direct-address/merge/expand, which scan swapped onto a sorted projection
(and its slice capacity), which aggregate collapsed into clustered-FK
segment reductions, which TopN serves from the IVF index. EXPLAIN never
compiles: everything shown is host-side planning state."""

from __future__ import annotations

from .logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    Project,
    Scan,
    SetOp,
    Sort,
    TopN,
    Window,
)


def explain_plan(executor, plan, params) -> list[str]:
    """Lines of an EXPLAIN rendering for a routed plan + seeded params."""
    from ..engine.executor import _number_nodes

    nodes = _number_nodes(plan)
    nid_of = {id(op): nid for nid, op in nodes.items()}
    lines: list[str] = []

    def est(op) -> str:
        try:
            return f"~{int(executor._est_rows(op))} rows"
        except Exception:
            return ""

    def join_route(op: JoinOp) -> str:
        if op.kind in ("semi", "anti"):
            if len(op.left_keys) == 1 and executor._affine_build_info(
                op
            ) is not None:
                return "direct-address probe"
            return "sorted-build range probe"
        if not executor._merge_joinable(op):
            return "expand (M:N sort + binary search)"
        if op.left_keys and executor._affine_build_info(op) is not None:
            return "direct-address (affine build key)"
        return "merge (combined sort, unique build)"

    def rec(op, depth):
        pad = "  " * depth
        nid = nid_of.get(id(op))
        if isinstance(op, Scan):
            extra = ""
            if "#sp:" in op.table:
                cap = params.scan_cap.get(nid)
                extra = " [sorted projection"
                extra += f", sliced cap={cap}]" if cap else "]"
            flt = f" filter={op.pushed_filter}" if op.pushed_filter else ""
            lines.append(
                f"{pad}SCAN {op.table} as {op.alias}{extra}{flt} {est(op)}"
            )
            return
        if isinstance(op, JoinOp):
            lines.append(
                f"{pad}JOIN {op.kind} [{join_route(op)}] "
                f"on {list(map(str, op.left_keys))} = "
                f"{list(map(str, op.right_keys))} {est(op)}"
            )
        elif isinstance(op, Aggregate):
            spec = params.clustered_aggs.get(nid)
            mode = (
                f"clustered-FK segment reduction over "
                f"{spec.probe_table}.{spec.fk_col} -> "
                f"{spec.build_table}.{spec.pk_col}"
                if spec is not None else
                "grouping sets expand" if op.grouping_sets is not None
                else "sort/direct group-by"
            )
            keys = [n for n, _ in op.group_keys]
            lines.append(
                f"{pad}AGGREGATE [{mode}] keys={keys} "
                f"aggs={[f'{f}({n})' for n, f, _a, _d in op.aggs]} {est(op)}"
            )
        elif isinstance(op, TopN):
            vspec = params.vector_topns.get(nid)
            if vspec is not None:
                mode = (
                    f"ANN IVF probe (nprobe={vspec.nprobe}, "
                    f"max_list={vspec.max_list}")
                if vspec.filters or getattr(vspec, "est_sel", 1.0) < 1.0:
                    nf = len(vspec.filters) + (
                        1 if getattr(vspec.scan, "pushed_filter", None)
                        is not None else 0)
                    mode += (f", filtered sel~{vspec.est_sel:.3g}"
                             f" fused={nf}")
                if vspec.nprobe > vspec.base_nprobe > 0:
                    mode += f", over-probe from {vspec.base_nprobe}"
                mode += (f") route: ivf={vspec.ivf_cost:.3g} < "
                         f"brute={vspec.brute_cost:.3g}"
                         f" [{vspec.cost_basis}]")
            else:
                mode = "top-n sort"
            lines.append(f"{pad}TOPN [{mode}] n={op.n} {est(op)}")
        elif isinstance(op, Filter):
            lines.append(f"{pad}FILTER {op.pred}")
        elif isinstance(op, Project):
            lines.append(
                f"{pad}PROJECT {[n for n, _ in op.exprs]}"
            )
        elif isinstance(op, Sort):
            lines.append(f"{pad}SORT {[str(e) for e, _ in op.keys]}")
        elif isinstance(op, Limit):
            lines.append(f"{pad}LIMIT {op.n} offset={op.offset}")
        elif isinstance(op, Distinct):
            lines.append(f"{pad}DISTINCT")
        elif isinstance(op, SetOp):
            lines.append(
                f"{pad}{op.kind.upper()}{' ALL' if op.all else ''}"
            )
        elif isinstance(op, Window):
            lines.append(
                f"{pad}WINDOW {[n for n, *_ in op.funcs]}"
            )
        else:
            lines.append(f"{pad}{type(op).__name__}")
        for attr in ("child", "left", "right"):
            c = getattr(op, attr, None)
            if c is not None:
                rec(c, depth + 1)

    rec(plan, 0)
    return lines


def annotate_plan_lines(lines, op_profile, miss_mark: float = 8.0
                        ) -> list[str]:
    """EXPLAIN ANALYZE: fold a profiled run's per-operator measurements
    (engine/plan_profile.py, via Session.last_op_profile) into the plan
    rendering. explain_plan emits exactly one line per operator in the
    SAME pre-order _number_nodes assigns, so line i annotates node i:
    est vs actual rows, the misestimation factor (`>>` marker at >=
    miss_mark x) and the operator's fenced device time."""
    from ..engine.plan_profile import miss_factor

    samples = {s.node_id: s for s in op_profile.get("samples", ())}
    est = op_profile.get("estimates", {})
    absorbed = op_profile.get("absorbed", {}) or {}
    out = []
    for i, ln in enumerate(lines):
        s = samples.get(i)
        if s is None:
            if i in absorbed:
                # never emitted standalone: its work is measured inside
                # the absorbing parent's stage
                out.append(f"{ln} (absorbed into node {absorbed[i]})")
            else:
                out.append(ln)
            continue
        e = int(est.get(i, 0))
        mf = miss_factor(e, s.rows)
        mark = ">> " if mf >= miss_mark else ""
        out.append(
            f"{mark}{ln} (est_rows={e} actual_rows={s.rows} "
            f"miss={mf:.1f}x device={int(s.device_us)}us)"
        )
    return out
