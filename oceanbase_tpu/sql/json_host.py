"""JSON_OBJECT / JSON_ARRAY constructors — host-side output formatting.

Reference surface: ob_expr_json_object.cpp / ob_expr_json_array.cpp. The
reference builds per-row JSON values inside the expression engine; in the
columnar rebuild per-row STRING CONSTRUCTION cannot run on device (the
device never sees strings, only dictionary codes). Constructors in the
select list therefore split: the argument expressions execute on device
as hidden output columns, and the JSON text materializes on the host as
the result set is assembled — the same place dictionary codes decode to
strings anyway. Constructors outside the top-level select list are
rejected at resolve time.

The split happens BEFORE planning (AST level) so the device plan, the
plan cache key, and the host formatting spec stay consistent:
`split_host_json` returns the rewritten AST plus a spec; `apply` turns
the executed columns into the final result columns.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from . import ast as A

_CTORS = ("json_object", "json_array")


@dataclass(frozen=True)
class HostJsonSpec:
    """One constructor output column: its name, select-list position, and
    formatting tree. Tree nodes: ("lit", v) | ("col", hidden_name) |
    ("obj", ((key, node), ...)) | ("arr", (node, ...))."""

    name: str
    position: int
    tree: tuple


def _is_ctor(e) -> bool:
    return isinstance(e, A.FuncCall) and e.name in _CTORS


def _lit_value(e):
    if isinstance(e, A.StringLit):
        return e.value
    if isinstance(e, A.NumberLit):
        try:
            return int(e.value)
        except ValueError:
            return float(e.value)
    if isinstance(e, A.Name) and e.parts == ("null",):
        return None
    return _NOT_LIT


_NOT_LIT = object()


def _build_tree(e, hidden: list) -> tuple:
    if _is_ctor(e):
        if e.name == "json_object":
            if len(e.args) % 2:
                raise ValueError("json_object needs key/value pairs")
            pairs = []
            for k, v in zip(e.args[::2], e.args[1::2]):
                if not isinstance(k, A.StringLit):
                    raise ValueError("json_object keys must be string literals")
                pairs.append((k.value, _build_tree(v, hidden)))
            return ("obj", tuple(pairs))
        return ("arr", tuple(_build_tree(a, hidden) for a in e.args))
    lv = _lit_value(e)
    if lv is not _NOT_LIT:
        return ("lit", lv)
    name = f"$jh{len(hidden)}"
    hidden.append(A.SelectItem(e, name))
    return ("col", name)


def split_host_json(sel):
    """(ast', specs, hidden_names): replace top-level constructor select
    items with position-preserving placeholders + hidden argument columns
    appended at the end. Returns (sel, (), ()) when nothing applies."""
    if not isinstance(sel, A.Select):
        return sel, (), ()
    if not any(_is_ctor(it.expr) for it in sel.items):
        return sel, (), ()
    if sel.distinct:
        raise ValueError("DISTINCT over JSON constructors is not supported")
    specs: list[HostJsonSpec] = []
    hidden: list[A.SelectItem] = []
    items = []
    ctor_names = set()
    for pos, it in enumerate(sel.items):
        if _is_ctor(it.expr):
            name = it.alias or it.expr.name
            specs.append(HostJsonSpec(name, pos, _build_tree(it.expr, hidden)))
            ctor_names.add(name)
            # placeholder keeps select-list POSITIONS stable (ordinal
            # ORDER BY / GROUP BY references to other items still hold)
            items.append(A.SelectItem(A.NumberLit("0"), name))
        else:
            items.append(it)
    ctor_positions = {s.position for s in specs}
    for clause, refs in (("ORDER BY", [o.expr for o in sel.order_by]),
                         ("GROUP BY", list(sel.group_by))):
        for e in refs:
            if isinstance(e, A.Name) and len(e.parts) == 1 and \
                    e.parts[0] in ctor_names:
                raise ValueError(
                    f"{clause} a JSON constructor is not supported")
            if isinstance(e, A.NumberLit) and \
                    int(e.value) - 1 in ctor_positions:
                raise ValueError(
                    f"{clause} a JSON constructor is not supported")
    from dataclasses import replace

    sel2 = replace(sel, items=tuple(items) + tuple(hidden))
    return sel2, tuple(specs), tuple(it.alias for it in hidden)


def _cell(col, i):
    v = col[i]
    if v is None:
        return None
    if isinstance(v, (np.floating, float)):
        f = float(v)
        if f != f:  # NaN carries SQL NULL through float channels
            return None
        return int(f) if f.is_integer() else f
    if isinstance(v, (np.integer, int)):
        return int(v)
    if isinstance(v, (np.bool_, bool)):
        return bool(v)
    if isinstance(v, np.datetime64):
        return str(v)
    return str(v)


def _format(tree, cols, i):
    kind = tree[0]
    if kind == "lit":
        return tree[1]
    if kind == "col":
        return _cell(cols[tree[1]], i)
    if kind == "obj":
        return {k: _format(t, cols, i) for k, t in tree[1]}
    return [_format(t, cols, i) for t in tree[1]]


def apply_host_json(specs, hidden_names, names, cols):
    """Post-execution: build constructor columns from the hidden argument
    columns, drop the hidden columns, restore the select-list order."""
    if not specs:
        return names, cols
    n = len(next(iter(cols.values()))) if cols else 0
    out_cols = {k: v for k, v in cols.items() if k not in set(hidden_names)}
    for spec in specs:
        out_cols[spec.name] = [
            json.dumps(_format(spec.tree, cols, i),
                       separators=(", ", ": "), ensure_ascii=False)
            for i in range(n)
        ]
    out_names = tuple(nm for nm in names if nm not in set(hidden_names))
    return out_names, out_cols
