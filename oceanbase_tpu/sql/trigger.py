"""Row-level triggers (BEFORE/AFTER INSERT/UPDATE/DELETE ... FOR EACH ROW).

Reference surface: src/sql/resolver/ddl/ob_trigger_resolver.cpp and the
trigger execution hooks in the DML executors. The rebuild keeps the
MySQL-shaped subset that covers the reference's row-trigger tests:

  * body = one statement or BEGIN ... END; statements are
      - SET NEW.col = <expr>        (BEFORE INSERT/UPDATE only)
      - INSERT / UPDATE / DELETE    (audit-log style side effects)
  * NEW.col / OLD.col references substitute per row as LITERALS into the
    body's AST before execution — side-effect DML then runs through the
    normal session dispatch INSIDE the firing statement's transaction
    (atomic with it, like the reference executing trigger bodies through
    the inner-SQL connection of the same tx).

Bodies parse at CREATE TRIGGER (errors surface to the DDL, not the first
firing) and the parsed form is cached per trigger.
"""

from __future__ import annotations

from dataclasses import replace

from . import ast as A
from .parser import Parser, tokenize


class TriggerError(ValueError):
    pass


def parse_body(body: str) -> tuple:
    """Trigger body text -> tuple of actions:
    ("setnew", col, expr_ast) | ("stmt", stmt_ast)."""
    text = body.strip().rstrip(";")
    toks = tokenize(text)
    if toks and toks[0].value == "begin":
        # BEGIN ... END block: strip the wrapper on the RAW text
        if toks[-2].value != "end":  # [-1] is eof
            raise TriggerError("BEGIN block missing END")
        text = text[toks[0].pos + 5:toks[-2].pos].strip()
    stmts = _split_statements(text)
    if not stmts:
        raise TriggerError("empty trigger body")
    actions = []
    for s in stmts:
        st = tokenize(s)
        if st and st[0].value == "set":
            p = Parser(s)
            p.expect("set")
            t = p.next()
            if not (t.value == "new" and p.accept(".")):
                raise TriggerError("SET target must be NEW.<column>")
            col = p.next().value
            p.expect("=")
            expr = p.expr()
            actions.append(("setnew", col, expr))
        else:
            node = Parser(s).parse_statement()
            if not isinstance(node, (A.Insert, A.Update, A.Delete)):
                raise TriggerError(
                    "trigger statements must be SET NEW.x or DML, got "
                    f"{type(node).__name__}")
            actions.append(("stmt", node))
    return tuple(actions)


def _split_statements(text: str) -> list[str]:
    """Split on top-level ';' using token positions (string literals with
    semicolons stay intact)."""
    cuts = [t.pos for t in tokenize(text) if t.kind == "op" and t.value == ";"]
    out, start = [], 0
    for c in cuts:
        piece = text[start:c].strip()
        if piece:
            out.append(piece)
        start = c + 1
    tail = text[start:].strip()
    if tail:
        out.append(tail)
    return out


def _literal_node(v) -> A.Node:
    import numpy as np

    if v is None:
        return A.Name(("null",))
    if isinstance(v, (bool, np.bool_)):
        return A.NumberLit(str(int(v)))
    if isinstance(v, str):
        return A.StringLit(v)
    if isinstance(v, (int, np.integer)):
        # ints stay ints: a float round-trip corrupts values above 2^53
        return A.NumberLit(str(int(v)))
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return A.NumberLit(str(int(f)))
    return A.NumberLit(repr(f))


def substitute(node: A.Node, new_map: dict | None, old_map: dict | None):
    """Replace NEW.col / OLD.col name references with literal AST nodes
    (one shared walker: ast.rewrite)."""

    def fn(n):
        if isinstance(n, A.Name) and len(n.parts) == 2:
            scope, col = n.parts
            if scope == "new":
                if new_map is None or col not in new_map:
                    raise TriggerError(f"no NEW.{col} in this trigger event")
                return _literal_node(new_map[col])
            if scope == "old":
                if old_map is None or col not in old_map:
                    raise TriggerError(f"no OLD.{col} in this trigger event")
                return _literal_node(old_map[col])
        return None

    return A.rewrite(node, fn)
