"""Plan cache with literal parameterization.

Reference surface: ObPlanCache + the fast-parser parameterization pipeline
(src/sql/plan_cache/ob_plan_cache.h:227, sql/parser/ob_fast_parser.h). The
reference caches physical plans keyed by literal-normalized SQL so repeated
statements skip the compiler; a "plan set" under each key matches incoming
parameter types to a compiled plan.

On TPU the cached artifact is the jitted XLA executable, and a recompile
costs seconds — so parameterization is not an optimization but the thing
that makes a plan cache meaningful at all:

- numeric / decimal / date literals become runtime scalars (Literal.slot)
  fed to the jitted program as an extra argument; one executable serves
  every value.
- string literals, LIKE patterns, IN lists and function arguments stay
  baked: they drive host-side dictionary lookup tables at trace time (the
  reference marks the analogous cases "must be checked" fixed consts). Their
  values join the cache key, so a different pattern compiles a new plan
  rather than reusing a wrong one.

Eviction is LRU by entry count (the reference evicts by memory watermark,
ob_plan_cache.h evict_expired_plan; entry count is the honest proxy here
because the dominant cost is one XLA executable per entry).
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from dataclasses import dataclass, field, replace as dc_replace

import numpy as np

from ..core.dtypes import TypeKind
from ..expr import ir as E
from .logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    LogicalOp,
    Project,
    Scan,
    SetOp,
    Sort,
    TopN,
    Window,
)

# literal kinds whose values may become runtime parameters
_PARAM_KINDS = {
    TypeKind.INT8,
    TypeKind.INT16,
    TypeKind.INT32,
    TypeKind.INT64,
    TypeKind.FLOAT32,
    TypeKind.FLOAT64,
    TypeKind.DECIMAL,
    TypeKind.DATE,
    TypeKind.VECTOR,
}


@dataclass
class ParamizeResult:
    plan: LogicalOp
    values: list  # python values per slot, in slot order
    dtypes: list  # DataType per slot
    sig: tuple  # parameter type signature (part of the cache key)
    baked: tuple  # non-parameterizable literal values (part of the cache key)


class _Paramizer:
    def __init__(self):
        self.values = []
        self.dtypes = []
        self.baked = []

    # ---- expressions -----------------------------------------------------
    def expr(self, e: E.Expr | None, in_func: bool = False) -> E.Expr | None:
        if e is None:
            return None
        if isinstance(e, E.Literal):
            if (
                not in_func
                and e.value is not None
                and e.dtype.kind in _PARAM_KINDS
            ):
                slot = len(self.values)
                self.values.append(e.value)
                self.dtypes.append(e.dtype)
                return E.Literal(e.value, e.dtype, slot=slot)
            self.baked.append(e.value)
            return e
        if isinstance(e, E.ColRef):
            return e
        if isinstance(e, E.BinaryOp):
            return E.BinaryOp(e.op, self.expr(e.left, in_func), self.expr(e.right, in_func))
        if isinstance(e, E.Compare):
            return E.Compare(e.op, self.expr(e.left, in_func), self.expr(e.right, in_func))
        if isinstance(e, E.BoolOp):
            return E.BoolOp(e.op, tuple(self.expr(a, in_func) for a in e.args))
        if isinstance(e, E.Not):
            return E.Not(self.expr(e.arg, in_func))
        if isinstance(e, E.IsNull):
            return E.IsNull(self.expr(e.arg, in_func), e.negated)
        if isinstance(e, E.Cast):
            return E.Cast(self.expr(e.arg, in_func), e.dtype)
        if isinstance(e, E.Case):
            whens = tuple(
                (self.expr(c, in_func), self.expr(v, in_func)) for c, v in e.whens
            )
            return E.Case(whens, self.expr(e.default, in_func))
        if isinstance(e, E.InList):
            # membership sets become boolean LUTs / unrolled comparisons at
            # trace time; keep them baked and key-relevant
            self.baked.extend(e.values)
            return E.InList(self.expr(e.arg, in_func), e.values, e.negated)
        if isinstance(e, E.Between):
            return E.Between(
                self.expr(e.arg, in_func),
                self.expr(e.low, in_func),
                self.expr(e.high, in_func),
                e.negated,
            )
        if isinstance(e, E.Func):
            if e.name in ("vec_l2", "vec_ip", "vec_cosine"):
                # the QUERY VECTOR parameterizes (one executable per
                # column serves every query point — the ANN qps story);
                # the column ref stays structural
                return E.Func(e.name, (
                    self.expr(e.args[0], True),
                    self.expr(e.args[1], False),
                ))
            # function args (LIKE patterns, substr bounds) drive host-side
            # dictionary transforms during tracing: never parameterize
            return E.Func(e.name, tuple(self.expr(a, True) for a in e.args))
        raise NotImplementedError(type(e))

    # ---- plan nodes ------------------------------------------------------
    def plan(self, op: LogicalOp) -> LogicalOp:
        if isinstance(op, Scan):
            return dc_replace(op, pushed_filter=self.expr(op.pushed_filter))
        if isinstance(op, Filter):
            return dc_replace(op, child=self.plan(op.child), pred=self.expr(op.pred))
        if isinstance(op, Project):
            return dc_replace(
                op,
                child=self.plan(op.child),
                exprs=tuple((n, self.expr(e)) for n, e in op.exprs),
            )
        if isinstance(op, JoinOp):
            return dc_replace(
                op,
                left=self.plan(op.left),
                right=self.plan(op.right),
                left_keys=tuple(self.expr(e) for e in op.left_keys),
                right_keys=tuple(self.expr(e) for e in op.right_keys),
                residual=self.expr(op.residual),
            )
        if isinstance(op, Aggregate):
            if op.grouping_sets is not None:
                # set structure shapes the physical program: structural
                self.baked.append(("gsets", op.grouping_sets))
            return dc_replace(
                op,
                child=self.plan(op.child),
                group_keys=tuple((n, self.expr(e)) for n, e in op.group_keys),
                aggs=tuple(
                    (n, fn, self.expr(a), d) for n, fn, a, d in op.aggs
                ),
            )
        if isinstance(op, Sort):
            return dc_replace(
                op,
                child=self.plan(op.child),
                keys=tuple((self.expr(e), d) for e, d in op.keys),
            )
        if isinstance(op, Limit):
            # limit/offset shape the static output capacity: structural
            self.baked.append(("limit", op.n, op.offset))
            return dc_replace(op, child=self.plan(op.child))
        if isinstance(op, Distinct):
            return dc_replace(op, child=self.plan(op.child))
        if isinstance(op, TopN):
            # n/offset shape the static output capacity: structural
            self.baked.append(("topn", op.n, op.offset))
            return dc_replace(
                op,
                child=self.plan(op.child),
                keys=tuple((self.expr(e), d) for e, d in op.keys),
            )
        if isinstance(op, SetOp):
            # kind/all are structural (they shape the physical program)
            self.baked.append(("setop", op.kind, op.all))
            return dc_replace(
                op, left=self.plan(op.left), right=self.plan(op.right)
            )
        if isinstance(op, Window):
            def fix_extra(fn, x):
                # frame bounds / ntile buckets are ints shaping the kernel:
                # structural. lag/lead defaults are exprs: parameterize.
                if fn in ("lag", "lead") and x is not None:
                    off, dflt = x
                    self.baked.append(("winoff", off))
                    return (off, self.expr(dflt) if dflt is not None else None)
                if x is not None:
                    self.baked.append(("winextra", fn, x))
                return x

            return dc_replace(
                op,
                child=self.plan(op.child),
                funcs=tuple(
                    (
                        n, fn, self.expr(a),
                        tuple(self.expr(p) for p in pk),
                        tuple((self.expr(o), d) for o, d in ok),
                        fix_extra(fn, x),
                    )
                    for n, fn, a, pk, ok, x in op.funcs
                ),
            )
        raise NotImplementedError(type(op))


def parameterize(plan: LogicalOp) -> ParamizeResult:
    p = _Paramizer()
    plan2 = p.plan(plan)
    sig = tuple(str(t) for t in p.dtypes)
    return ParamizeResult(plan2, p.values, p.dtypes, sig, tuple(map(repr, p.baked)))


_GENSYM_RE = None


def plan_fingerprint(plan: LogicalOp) -> str:
    """Structural digest of a (parameterized) plan.

    Part of the cache key: literals the PLANNER consumes (ORDER BY ordinals,
    hoisted conjuncts, unnesting choices) leave no Literal node behind, so
    normalized SQL + params alone can collide across genuinely different
    plans. The dataclass repr covers node types, column refs, sort keys,
    limits and slot numbers deterministically; md5 keeps the key small.

    Gensym names ($agg3, $sub1, ...) come from global counters so two
    plannings of the SAME query get different numbers — canonicalize them
    by first occurrence before hashing."""
    import hashlib
    import re

    global _GENSYM_RE
    if _GENSYM_RE is None:
        _GENSYM_RE = re.compile(r"\$([a-z]+)\d+")
    mapping: dict[str, str] = {}

    def canon(m):
        tok = m.group(0)
        if tok not in mapping:
            mapping[tok] = f"${m.group(1)}#{len(mapping)}"
        return mapping[tok]

    r = _GENSYM_RE.sub(canon, repr(plan))
    return hashlib.md5(r.encode()).hexdigest()


def bind(values, dtypes) -> tuple:
    """Host-convert literal values to physical scalars for the jit call."""
    import jax.numpy as jnp

    from ..expr.compile import bind_value

    return tuple(
        jnp.asarray(bind_value(v, t)) for v, t in zip(values, dtypes)
    )


# ---- text-keyed fast tier (the ObPlanCache fast-parser front end) ----------
#
# The logical cache above still pays parse + resolve + rewrite + plan +
# parameterize on every statement just to COMPUTE its key. The fast tier
# keys on the kind-marked normalized text alone (parser.fast_normalize, one
# regex pass) and stores everything needed to rebuild the logical key
# without planning: the parameter signature, baked literals, plan
# fingerprint and referenced tables. A fast hit therefore still goes
# through PlanCache.get() with a freshly computed key_extra — schema-version
# bumps, flush() and LRU eviction of the logical entry all invalidate the
# fast path with no extra bookkeeping.
#
# Correctness of literal re-binding rests on token accounting built at
# registration time: every literal token of the statement is either
#   - mapped to exactly one parameter slot whose registered value provably
#     round-trips from the token text through one recorded converter
#     (int / float / date), with the slot matched by no other token, or
#   - marked BAKED: the raw token text must match the registration text
#     exactly on every fast hit (strings, IN-list members, LIMIT counts,
#     planner-folded literals like date + interval — anything whose value
#     the planner consumed rather than slotted).
# Any ambiguity (duplicate values, a token matching two slots, a folded
# slot colliding with a token) degrades to BAKED, never to a guess: a
# mismatch falls back to the full parse path, which is always correct.

_DATE_TOK_RE = re.compile(r"\d{4}-\d{2}-\d{2}$")


def _tok_candidate(tok: str, kind: str):
    """The (converter_tag, value) the slow path would produce for this
    literal token, or None. Mirrors sql/logical.py exactly: a num token
    types int unless it contains '.', a quoted YYYY-MM-DD behind DATE
    becomes epoch days."""
    try:
        if kind == "num":
            if "." in tok:
                return ("float", float(tok))
            return ("int", int(tok))
        if _DATE_TOK_RE.match(tok):
            return ("date", int(np.datetime64(tok, "D").astype(np.int64)))
        if kind == "str" and tok.startswith("[") and tok.endswith("]"):
            # vector literal: the slot value IS the raw bracket text
            # (sql/logical.py binds it at execution), so the slot match
            # below is plain string equality — a fresh embedding per
            # statement re-binds instead of baking a fast-tier miss
            return ("vec", tok)
    except ValueError:
        pass
    return None


def _convert_token(tok: str, tag: str):
    """Re-apply a recorded converter to a NEW token text. Returns the
    bound value or None when the token no longer fits the registered
    typing (dtype widening '5' -> '5.5', malformed dates) — the caller
    falls back to the full parse path and a separate plan entry."""
    try:
        if tag == "int":
            return int(tok)  # raises on '5.5': widening is a fast miss
        if tag == "float":
            if "." not in tok:
                return None  # would have typed int: different signature
            return float(tok)
        if tag == "date":
            if not _DATE_TOK_RE.match(tok):
                return None
            return int(np.datetime64(tok, "D").astype(np.int64))
        if tag == "vec":
            if not (tok.startswith("[") and tok.endswith("]")):
                return None
            # validate components parse; dimension is checked by
            # bind_value at execution (a mismatch raises there exactly
            # like the slow path would)
            [float(x) for x in tok[1:-1].split(",")]
            return tok
    except ValueError:
        return None
    return None


def build_slot_map(params: tuple, kinds: tuple, values: list) -> tuple:
    """Token accounting for one registered statement: per literal token,
    ("slot", slot_idx, converter_tag) when the token<->slot correspondence
    is unambiguous, else ("baked", raw_token_text)."""
    cands = [_tok_candidate(t, k) for t, k in zip(params, kinds)]
    tok_edges: list[list[int]] = [[] for _ in params]
    slot_edges: list[list[tuple[int, str]]] = [[] for _ in values]
    for i, c in enumerate(cands):
        if c is None:
            continue
        tag, cv = c
        for j, v in enumerate(values):
            # exact-type equality: an int token must not cross-bind a
            # float slot (or epoch-day ints a same-valued INT slot — the
            # bipartite uniqueness check below catches that collision)
            if type(cv) is type(v) and cv == v:
                tok_edges[i].append(j)
                slot_edges[j].append((i, tag))
    out = []
    for i, tok in enumerate(params):
        es = tok_edges[i]
        if len(es) == 1 and len(slot_edges[es[0]]) == 1:
            out.append(("slot", es[0], slot_edges[es[0]][0][1]))
        else:
            out.append(("baked", tok))
    return tuple(out)


@dataclass
class FastEntry:
    """One text-tier entry: the material to rebuild the LOGICAL cache key
    (norm_key/sig/baked/fingerprint + referenced tables for key_extra)
    plus the token->slot accounting that re-binds literals without
    parsing. Holds no compiled artifact — the executable stays owned by
    the logical tier, so eviction/flush there invalidates here for free."""

    norm_key: str
    sig: tuple
    baked: tuple
    fingerprint: str
    tables: tuple[str, ...]
    slot_map: tuple
    base_values: tuple  # registration-time slot values (fixed slots replay)
    stmt_type: str = "Select"
    hits: int = 0

    def bind_tokens(self, params: tuple) -> list | None:
        """Slot values for a repeat statement's raw literal tokens, or
        None when any baked token differs / any converter rejects —
        the caller takes the full parse path."""
        if len(params) != len(self.slot_map):
            return None
        vals = list(self.base_values)
        for tok, m in zip(params, self.slot_map):
            if m[0] == "baked":
                if tok != m[1]:
                    return None
            else:
                v = _convert_token(tok, m[2])
                if v is None:
                    return None
                vals[m[1]] = v
        return vals


@dataclass
class CacheEntry:
    prepared: object  # engine.executor.PreparedPlan
    output_names: tuple[str, ...]
    dtypes: list
    hits: int = 0
    monitor: object = None  # server/diag.PlanMonitorEntry (if enabled)


@dataclass
class PlanCacheStats:
    hits: int = 0
    misses: int = 0
    evictions: int = 0
    # text-keyed fast tier (fast-parser front end)
    fast_hits: int = 0
    fast_misses: int = 0
    fast_evictions: int = 0
    fast_invalidations: int = 0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    @property
    def fast_hit_rate(self) -> float:
        total = self.fast_hits + self.fast_misses
        return self.fast_hits / total if total else 0.0


class PlanCache:
    """LRU cache: (normalized SQL, param signature, baked literals) ->
    compiled plan. One entry = one XLA executable."""

    def __init__(self, capacity: int = 128, metrics=None):
        self.capacity = capacity
        # one lock over both tiers: every public method mutates shared
        # OrderedDicts (move_to_end reorders even on reads) and the
        # server's ThreadingTCPServer drives them from one thread per
        # connection. RLock because metrics callbacks stay inside the
        # critical section and a re-entrant flush must not self-deadlock.
        self._lock = threading.RLock()
        self._entries: OrderedDict[tuple, CacheEntry] = OrderedDict()
        # text tier: kind-marked normalized text -> FastEntry. Same
        # capacity: a FastEntry is tiny next to the XLA executable its
        # logical entry holds, and a text entry whose logical entry was
        # evicted self-invalidates on its next hit anyway.
        self._fast: OrderedDict[str, FastEntry] = OrderedDict()
        # A/B switch (latency_bench --no-fastpath, tests): disabled means
        # lookups miss and registrations drop; the logical tier is
        # untouched so only the text tier's contribution is isolated
        self.fast_enabled = True
        self.stats = PlanCacheStats()
        # tenant metrics registry (share/metrics): mirrors hit/miss/evict
        # into __all_virtual_sysstat next to every other engine stat
        self.metrics = metrics
        # on-disk tier (engine/plan_artifact.PlanArtifactStore) wired by
        # the server when ob_plan_artifact_mode != off: misses hydrate
        # exported executables from it, flush() covers it
        self.artifact_store = None
        # hook: engine/result_cache.ResultCache — flushes with the plan
        # tiers (the server wires it; see flush())
        self.result_cache = None

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def get(self, key: tuple, count_miss: bool = True) -> CacheEntry | None:
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                ent.hits += 1
                self.stats.hits += 1
                if self.metrics is not None:
                    self.metrics.add("plan cache hit")
            elif count_miss:
                self.stats.misses += 1
                if self.metrics is not None:
                    self.metrics.add("plan cache miss")
            return ent

    def put(self, key: tuple, entry: CacheEntry):
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.stats.evictions += 1
                if self.metrics is not None:
                    self.metrics.add("plan cache eviction")

    # ---- text tier -------------------------------------------------------
    def fast_peek(self, text_key: str) -> FastEntry | None:
        """Text-tier lookup WITHOUT hit/miss accounting: a peeked entry
        still has to survive literal re-binding and the logical-tier get
        before it counts as a hit (Session.fast_lookup does the counting,
        so a bind mismatch is honestly a miss)."""
        if not self.fast_enabled:
            return None
        with self._lock:
            ent = self._fast.get(text_key)
            if ent is not None:
                self._fast.move_to_end(text_key)
            return ent

    def fast_hit_get(self, key: tuple,
                     defer_adds: list | None = None) -> CacheEntry | None:
        """Logical-tier get + hit accounting for a VALIDATED fast hit,
        under one lock acquisition — the serving hot path runs this once
        per statement, where get() + note_fast_hit() would take the cache
        lock twice and the metrics lock twice (nested, at that). Metric
        bumps move after the cache lock releases; a caller that flushes a
        per-statement counter batch at statement end (the server session)
        passes `defer_adds` and the bumps ride its one bulk() instead of
        taking the metrics lock here. A None return means the logical
        entry is gone; the caller notes the miss and drops the text entry
        exactly as with get(count_miss=False)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                ent.hits += 1
                self.stats.hits += 1
                self.stats.fast_hits += 1
        if ent is not None and self.metrics is not None:
            if defer_adds is not None:
                defer_adds.append(("plan cache hit", 1))
                defer_adds.append(("plan cache fast hit", 1))
            else:
                self.metrics.bulk(adds=(("plan cache hit", 1),
                                        ("plan cache fast hit", 1)))
        return ent

    def note_fast_hit(self) -> None:
        with self._lock:
            self.stats.fast_hits += 1
            if self.metrics is not None:
                self.metrics.add("plan cache fast hit")

    def note_fast_miss(self) -> None:
        with self._lock:
            self.stats.fast_misses += 1
            if self.metrics is not None:
                self.metrics.add("plan cache fast miss")

    def fast_put(self, text_key: str, entry: FastEntry) -> None:
        if not self.fast_enabled:
            return
        with self._lock:
            self._fast[text_key] = entry
            self._fast.move_to_end(text_key)
            while len(self._fast) > self.capacity:
                self._fast.popitem(last=False)
                self.stats.fast_evictions += 1
                if self.metrics is not None:
                    self.metrics.add("plan cache fast eviction")

    def fast_invalidate(self, text_key: str) -> None:
        """Drop one stale text entry (its logical entry vanished, or a
        fast execution failed) — the next occurrence re-registers."""
        with self._lock:
            if self._fast.pop(text_key, None) is not None:
                self.stats.fast_invalidations += 1
                if self.metrics is not None:
                    self.metrics.add("plan cache fast invalidation")

    def census(self) -> tuple[list[dict], list[dict]]:
        """(logical entries, fast-text entries) for the device census —
        per-entry hit counts, the pow2 batch-bucket shapes compiled so
        far, and the memoized device-input bytes. One lock hold; values
        are plain dicts so the census owns nothing live."""
        with self._lock:
            logical = []
            for k, e in self._entries.items():
                memo = getattr(e.prepared, "_dev_bytes_memo", None)
                batched = getattr(e.prepared, "_batched", None)
                aref = getattr(e.prepared, "artifact_ref", None)
                logical.append({
                    "norm_key": k[1],
                    "hits": e.hits,
                    "buckets": tuple(sorted(batched)) if batched else (),
                    "dev_bytes": int(memo[2]) if memo is not None else 0,
                    # artifact tier: which on-disk executable backs this
                    # entry, and whether it was hydrated (vs compiled)
                    "artifact_id": aref[1] if aref is not None else "",
                    "warm": int(not getattr(e.prepared, "_traceable", True)),
                })
            fast = [
                {"text_key": k, "hits": fe.hits,
                 "stmt_type": fe.stmt_type, "tables": list(fe.tables)}
                for k, fe in self._fast.items()
            ]
        return logical, fast

    def flush(self, memory_only: bool = False):
        """Flush BOTH tiers. Retry policies with flush_plan_cache
        (OB_SCHEMA_EAGAIN), DDL-driven invalidation and ALTER SYSTEM all
        land here — a text entry surviving a flush would replay a plan
        compiled against a dead schema.

        memory_only=True flushes ONLY the in-memory tiers: a process
        restart loses RAM, not the disk store, and warm boot rehydrates
        from it. Schema-driven invalidation MUST NOT set it — the schema
        a disk artifact was compiled against is just as dead."""
        with self._lock:
            self._entries.clear()
            if self._fast:
                self.stats.fast_invalidations += len(self._fast)
                if self.metrics is not None:
                    self.metrics.add(
                        "plan cache fast invalidation", len(self._fast))
                self._fast.clear()
            # the artifact tier flushes with the in-memory tiers: an
            # exported executable surviving a schema-driven flush would
            # hydrate a plan compiled against a dead schema
            if not memory_only and self.artifact_store is not None:
                self.artifact_store.flush()
        # the result cache sits ABOVE the plan tiers (cached frames came
        # from entries that just died) and must flush with them — its
        # hook rides the plan cache so every flush caller is covered
        rc = getattr(self, "result_cache", None)
        if rc is not None:
            rc.flush()
